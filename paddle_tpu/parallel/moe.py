"""Mixture-of-Experts with expert parallelism (EP).

Capability analog of the reference MoE stack:
``python/paddle/incubate/distributed/models/moe/moe_layer.py:263``
(``MoELayer``), gates under ``moe/gate/`` (naive/gshard/switch), capacity
pruning (``distributed/models/moe/utils.py:20-178``), and the
``global_scatter``/``global_gather`` all-to-all pair
(``python/paddle/distributed/utils/moe_utils.py:20,153``).

TPU-first: the GShard formulation — gating produces dense dispatch/combine
tensors and the token shuffle is two einsums over an expert-sharded buffer;
annotating the ``[E, C, H]`` buffer's E dim over the ``ep`` mesh axis makes
GSPMD emit the all-to-all over ICI (the reference's global_scatter/gather
NCCL calls).  Expert FFNs are *stacked* weights ``[E, H, FF]`` so every
expert's matmul is one big batched MXU contraction.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from ..core.dispatch import run_op
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.initializer import Constant, XavierNormal
from ..nn.layers import Layer
from .utils import annotate_param, axis_size, sharding_constraint

EP_AXIS = "sep"  # expert parallelism rides the sep axis of the 5-axis mesh


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def _topk_gating(logits, capacity, k, normalize=True):
    """Generic top-k gating with GShard capacity semantics (generalizes
    ``moe/gate/gshard_gate.py``): every token routes to its k highest-prob
    experts, all j-th choices take capacity slots before any (j+1)-th
    choice, and tokens beyond an expert's capacity are dropped.

    ``normalize=True`` renormalizes the surviving gate weights to sum 1
    (GShard / Mixtral ``norm_topk_prob``); ``normalize=False`` keeps the
    raw softmax probabilities (Switch top-1, DeepSeek-MoE, Qwen2-MoE).
    k=1 never renormalizes — a single surviving gate would be pinned to
    exactly 1.0, erasing the learned gate magnitude.
    logits: [T, E] float32.

    Fully vectorized over k (one ``lax.top_k`` + one cumsum over the
    [T, k, E] choice tensor — graph size constant in k; the k-unrolled
    argmax/cumsum formulation grew linearly and k=8 presets paid for it).
    The sequential "offset carries KEPT slots of higher-priority choices"
    rule has the closed form ``offset_j(e) = min(capacity,
    Σ_{j'<j} count_{j'}(e))``: round-j positions are contiguous from the
    running offset, so the kept count is ``min(capacity, offset+count) -
    offset`` and the recursion telescopes."""
    normalize = normalize and k > 1
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)

    # priority-ordered choices: idx[t, j] = token t's j-th expert
    vals, idx = jax.lax.top_k(probs, k)           # [T, k] each
    M = _one_hot(idx, E)                          # [T, k, E]

    # aux loss: mean(prob per expert) * mean(tokens top-1-routed) * E
    density = jnp.mean(M[:, 0, :], axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * E

    # capacity accounting (see closed form above): all j-th choices take
    # slots before any (j+1)-th choice; within a round, token order
    counts = jnp.sum(M, axis=0)                   # [k, E] per-round totals
    before = jnp.cumsum(counts, axis=0) - counts  # exclusive prefix
    offset = jnp.minimum(capacity, before)        # [k, E] kept-slot offset
    p = (jnp.cumsum(M, axis=0) + offset[None]) * M - 1.0
    kept = M * (p < capacity)                     # [T, k, E]

    gates = vals * jnp.sum(kept, axis=-1)         # [T, k]; dropped -> 0
    if normalize:
        denom = jnp.sum(gates, axis=-1, keepdims=True)
        gates = gates / jnp.where(denom > 0, denom, 1.0)

    pi = jnp.sum(p * kept, axis=-1).astype(jnp.int32)   # [T, k] slot index
    slot = _one_hot(pi, capacity)                       # [T, k, C]
    combine = jnp.einsum("tk,tke,tkc->tec", gates, kept, slot)
    dispatch = combine > 0.0
    return combine, dispatch, aux


class BaseGate(Layer):
    def __init__(self, d_model: int, num_experts: int):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.weight = self.create_parameter(
            [d_model, num_experts], default_initializer=XavierNormal())
        self.loss = None

    def logits(self, x):
        # gate math in f32 for routing stability (reference casts likewise)
        return run_op(
            "gate_logits",
            lambda v, w: jnp.matmul(v.astype(jnp.float32), w.astype(jnp.float32)),
            x, self.weight)


class TopKGate(BaseGate):
    """Generic top-k gate: k routed experts per token with GShard capacity
    semantics; ``normalize=False`` keeps raw softmax weights (DeepSeek-MoE
    / Qwen2-MoE ``norm_topk_prob=False``)."""

    def __init__(self, d_model: int, num_experts: int, k: int = 2,
                 normalize: bool = True):
        super().__init__(d_model, num_experts)
        if not 1 <= k <= num_experts:
            # k > E would silently re-select expert 0 once all experts
            # are masked out of the argmax loop
            raise ValueError(
                f"top-k {k} must be in [1, num_experts={num_experts}]")
        self.top_k = k
        self.normalize = normalize

    def gating(self, logits_val, capacity):
        return _topk_gating(logits_val, capacity, self.top_k, self.normalize)


class GShardGate(TopKGate):
    def __init__(self, d_model: int, num_experts: int):
        super().__init__(d_model, num_experts, k=2, normalize=True)


class SwitchGate(TopKGate):
    def __init__(self, d_model: int, num_experts: int):
        super().__init__(d_model, num_experts, k=1, normalize=False)


class NaiveGate(GShardGate):
    """top-2 without aux loss weighting (moe/gate/naive_gate.py)."""


class FusedMoEMLP(Layer):
    """Stacked-expert SwiGLU/GELU FFN: weights [E, H, FF] / [E, FF, H],
    E-dim sharded over the ``ep`` axis.  One einsum per projection keeps
    every expert on the MXU (the reference loops per-expert Linears)."""

    def __init__(self, num_experts: int, d_model: int, d_hidden: int,
                 activation: str = "gelu"):
        super().__init__()
        self.num_experts = num_experts
        self.activation = activation
        self.w_in = self.create_parameter(
            [num_experts, d_model, d_hidden], default_initializer=XavierNormal())
        self.w_gate = (self.create_parameter(
            [num_experts, d_model, d_hidden], default_initializer=XavierNormal())
            if activation == "swiglu" else None)
        self.w_out = self.create_parameter(
            [num_experts, d_hidden, d_model], default_initializer=XavierNormal())
        annotate_param(self.w_in, EP_AXIS, None, None)
        if self.w_gate is not None:
            annotate_param(self.w_gate, EP_AXIS, None, None)
        annotate_param(self.w_out, EP_AXIS, None, None)

    def forward(self, dispatched):  # [E, C, H]
        def f(x, w_in, w_out, *rest):
            h = jnp.einsum("ech,ehf->ecf", x, w_in.astype(x.dtype))
            if self.w_gate is not None:
                g = jnp.einsum("ech,ehf->ecf", x, rest[0].astype(x.dtype))
                h = jax.nn.silu(g) * h
            elif self.activation == "gelu":
                h = jax.nn.gelu(h)
            else:
                h = jax.nn.relu(h)
            return jnp.einsum("ecf,efh->ech", h, w_out.astype(x.dtype))

        args = [dispatched, self.w_in, self.w_out]
        if self.w_gate is not None:
            args.append(self.w_gate)
        return run_op("moe_experts", f, *args)


class MoELayer(Layer):
    """(``moe_layer.py:263`` analog) gate → dispatch einsum → expert-sharded
    FFN → combine einsum.  ``experts`` may be a :class:`FusedMoEMLP` (fast
    path) or a list of Layers (generic fallback, python loop over experts)."""

    def __init__(self, d_model: int, experts, gate: Optional[Layer] = None,
                 num_experts: Optional[int] = None, capacity_factor: float = 1.25,
                 moe_group=None, recompute_interval: int = 0):
        super().__init__()
        self.d_model = d_model
        if isinstance(experts, FusedMoEMLP):
            self.experts = experts
            self.num_experts = experts.num_experts
            self._fused = True
        else:
            from ..nn.container import LayerList

            self.experts = experts if isinstance(experts, LayerList) else LayerList(list(experts))
            self.num_experts = len(self.experts)
            self._fused = False
        self.gate = gate if gate is not None else GShardGate(d_model, self.num_experts)
        self.capacity_factor = capacity_factor
        self.aux_loss = None

    def forward(self, x):  # [B, S, H] or [T, H]
        orig_shape = x.shape
        hidden = orig_shape[-1]
        from .. import tensor as ops

        flat = ops.reshape(x, [-1, hidden])
        T = flat.shape[0]
        E = self.num_experts
        capacity = max(1, int(self.capacity_factor * self.gate.top_k * T / E))

        logits = self.gate.logits(flat)

        def gating(lv):
            combine, dispatch, aux = self.gate.gating(lv, capacity)
            return combine, aux

        combine, aux = run_op("moe_gating", gating, logits)
        self.aux_loss = aux
        self.gate.loss = aux

        def dispatch_fn(xv, cv):
            return jnp.einsum("tec,th->ech", (cv > 0).astype(xv.dtype), xv)

        dispatched = run_op("moe_dispatch", dispatch_fn, flat, combine)
        # E over ep → GSPMD all-to-all (global_scatter analog)
        dispatched = sharding_constraint(dispatched, EP_AXIS, None, None)

        if self._fused:
            expert_out = self.experts(dispatched)
        else:
            outs = []
            for e, expert in enumerate(self.experts):
                outs.append(expert(dispatched[e]))
            expert_out = ops.stack(outs, axis=0)
        expert_out = sharding_constraint(expert_out, EP_AXIS, None, None)

        def combine_fn(ov, cv):
            return jnp.einsum("ech,tec->th", ov, cv.astype(ov.dtype))

        out = run_op("moe_combine", combine_fn, expert_out, combine)
        return ops.reshape(out, orig_shape)


def global_scatter(x: Tensor, local_count, global_count, group=None) -> Tensor:
    """``distributed/utils/moe_utils.py:20`` analog — explicit all-to-all for
    shard_map code paths (GSPMD handles the jit path automatically)."""
    return run_op(
        "global_scatter",
        lambda v: jax.lax.all_to_all(v, EP_AXIS, split_axis=0, concat_axis=0),
        x,
    )


def global_gather(x: Tensor, local_count, global_count, group=None) -> Tensor:
    """``moe_utils.py:153`` analog (inverse all-to-all)."""
    return run_op(
        "global_gather",
        lambda v: jax.lax.all_to_all(v, EP_AXIS, split_axis=0, concat_axis=0),
        x,
    )
