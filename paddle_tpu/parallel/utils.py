"""Shared helpers for the hybrid-parallel strategy layer.

The reference wires parallelism through NCCL subgroups created per topology
axis (``python/paddle/distributed/fleet/base/topology.py:174``).  TPU-first,
the single source of truth is the global 5-axis ``jax.sharding.Mesh``
([dp, pp, sharding, sep, mp], ``paddle_tpu.distributed.topology``); strategy
layers steer GSPMD with ``with_sharding_constraint`` and parameter
``PartitionSpec`` annotations instead of issuing collectives by hand.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.dispatch import run_op
from ..core.tensor import Parameter, Tensor
from ..distributed import topology

# Set while tracing under shard_map (pipeline / ring-attention bodies):
# GSPMD sharding constraints are meaningless on per-shard views, so the
# constraint helpers become no-ops there.  THREAD-LOCAL: jax traces on
# the calling thread, and concurrent engine threads (a dp>1 fleet, or
# the numerics auditor's single-device shadow trace next to a replica
# tracing a first-seen bucket) must never see each other's manual
# window — a constraint silently no-oped into another thread's cached
# executable would mis-place that bucket forever.
_manual_mode = threading.local()


@contextlib.contextmanager
def manual_sharding_mode():
    _manual_mode.depth = getattr(_manual_mode, "depth", 0) + 1
    try:
        yield
    finally:
        _manual_mode.depth -= 1


def in_manual_mode() -> bool:
    return getattr(_manual_mode, "depth", 0) > 0


def axis_size(axis: str) -> int:
    """Size of a named mesh axis (1 if no mesh / axis absent)."""
    mesh = topology.get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return mesh.shape[axis]


def named_sharding(*spec) -> Optional[NamedSharding]:
    mesh = topology.get_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, PartitionSpec(*spec))


def _fit_spec(spec, shape, mesh) -> PartitionSpec:
    """Adapt a spec to an actual array: pad/truncate to rank, and drop axis
    entries whose degree doesn't divide the dim (XLA requires even tiling;
    the reference imposes no such global-batch constraint on layer forward)."""
    ndim = len(shape)
    entries = list(spec)
    if len(entries) > ndim:
        # keep dim0 (batch) + right-align the feature entries
        head, tail = entries[0], [e for e in entries[1:] if e is not None]
        entries = [head] + [None] * max(0, ndim - 1 - len(tail)) + tail
        entries = entries[:ndim]
    entries += [None] * (ndim - len(entries))
    fitted = []
    for dim, e in zip(shape, entries):
        if e is None:
            fitted.append(None)
            continue
        axes = e if isinstance(e, tuple) else (e,)
        degree = 1
        for a in axes:
            degree *= mesh.shape.get(a, 1)
        fitted.append(e if degree > 0 and dim % degree == 0 else None)
    return PartitionSpec(*fitted)


def sharding_constraint(x: Tensor, *spec) -> Tensor:
    """Steer GSPMD: constrain ``x``'s sharding to ``PartitionSpec(*spec)``.

    This is the TPU analog of the reference's explicit c_identity/c_concat/
    c_split comm ops (``fleet/layers/mpu/mp_ops.py``): instead of issuing the
    collective, we pin the layout and XLA inserts the (fused, ICI-scheduled)
    collective where needed.  No-op without a mesh or under shard_map.  The
    spec is rank-adapted: shorter specs pad with None, longer specs keep
    batch + right-aligned feature entries, and entries that don't evenly
    divide the dim are dropped.
    """
    mesh = topology.get_mesh()
    if mesh is None or in_manual_mode():
        return x if isinstance(x, Tensor) else Tensor(x)
    shape = tuple(x.shape) if isinstance(x, Tensor) else jax.numpy.shape(x)
    sh = NamedSharding(mesh, _fit_spec(spec, shape, mesh))
    return run_op(
        "sharding_constraint", lambda v: jax.lax.with_sharding_constraint(v, sh), x
    )


def annotate_param(p: Parameter, *spec) -> Parameter:
    """Attach a PartitionSpec annotation; applied lazily by
    :func:`apply_param_shardings` / the jit in_shardings builder."""
    p.dist_spec = PartitionSpec(*spec)
    return p


def param_spec(p: Tensor) -> PartitionSpec:
    spec = getattr(p, "dist_spec", None)
    return spec if spec is not None else PartitionSpec()


def apply_param_shardings(layer, mesh: Optional[Mesh] = None):
    """device_put every annotated parameter/buffer onto the mesh — the analog
    of fleet's broadcast-on-init (``fleet/model.py:32``), except placement is
    declarative and XLA moves only the local shard.

    Specs are rank/divisibility-fitted like :func:`sharding_constraint`
    (and the serving engine's explicit jit in_shardings): an annotated dim
    the mesh degree doesn't divide evenly is placed replicated instead of
    crashing deep inside ``device_put`` — e.g. a model built BEFORE the
    mesh existed (so the mp-layer constructor checks ran at degree 1)
    with an odd ``intermediate_size`` under mp=2.

    Under a trace (AOT lowering with init fused into the program, e.g.
    ``tools/aot_lower_8b.py``) a ``device_put`` annotation is dropped by the
    lowering, so traced values get ``with_sharding_constraint`` instead —
    the same GSPMD placement, expressed as a program annotation."""
    mesh = mesh or topology.get_mesh()
    if mesh is None:
        return layer

    def place(v, spec):
        spec = _fit_spec(spec, jax.numpy.shape(v), mesh)
        if isinstance(v, jax.core.Tracer):
            if in_manual_mode():
                # inside shard_map the value is a per-shard view — a
                # full-mesh constraint would be wrong (module contract)
                return v
            return jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, spec))
        return jax.device_put(v, NamedSharding(mesh, spec))

    for _, p in layer.named_parameters():
        p._value = place(p._value, param_spec(p))
    for _, b in layer.named_buffers():
        b._value = place(b._value, param_spec(b))
    return layer
