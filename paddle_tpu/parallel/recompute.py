"""Activation recomputation (gradient checkpointing).

Capability analog of ``python/paddle/distributed/fleet/recompute/recompute.py``
(PyLayer that stows inputs + RNG state and replays forward in backward).

TPU-first: ``jax.checkpoint`` (remat) does the replay *inside* the XLA
program — under ``to_static`` the recompute block's activations are dropped
from the live set and the compiler schedules the replay right before the
consuming backward ops, trading HBM for MXU FLOPs with zero host round
trips.  RNG state preservation is structural: dispatch traces the forward
once (dropout keys become trace constants), so the remat replay reuses
identical masks — no state stow/restore needed.
"""

from __future__ import annotations

from typing import Any, Callable, List

import jax

from ..core.dispatch import run_op
from ..core.tensor import Parameter, Tensor
from ..nn.layers import Layer


def _find_params(function: Callable) -> List[Parameter]:
    owner = function if isinstance(function, Layer) else getattr(function, "__self__", None)
    if isinstance(owner, Layer):
        return [p for p in owner.parameters() if p is not None and not p.stop_gradient]
    # closure-captured parameters (functools.partial or nested fns)
    seen = []
    for cell in getattr(function, "__closure__", None) or ():
        v = cell.cell_contents
        if isinstance(v, Layer):
            seen.extend(p for p in v.parameters() if p is not None and not p.stop_gradient)
        elif isinstance(v, Parameter) and not v.stop_gradient:
            seen.append(v)
    return seen


def recompute(function: Callable, *args, **kwargs) -> Any:
    """Run ``function(*args, **kwargs)``, rematerializing its activations in
    backward (``fleet.recompute.recompute`` analog).

    Differentiable state = positional Tensor args + the parameters of the
    Layer being called (the reference gets param grads because its backward
    replay runs on the live tape; here they must be explicit vjp inputs).
    """
    kwargs.pop("use_reentrant", None)
    kwargs.pop("preserve_rng_state", None)

    arg_tensors = [a for a in args if isinstance(a, Tensor) and not a.stop_gradient]
    kw_tensors = [v for v in kwargs.values()
                  if isinstance(v, Tensor) and not v.stop_gradient]
    params = _find_params(function)
    tensors = arg_tensors + kw_tensors + params
    if not tensors:
        return function(*args, **kwargs)

    def pure(*vals):
        saved = [t._value for t in tensors]
        for t, v in zip(tensors, vals):
            t._value = v
        try:
            out = function(*args, **kwargs)
            if isinstance(out, (list, tuple)):
                return type(out)(o._value if isinstance(o, Tensor) else o for o in out)
            return out._value if isinstance(out, Tensor) else out
        finally:
            for t, v in zip(tensors, saved):
                t._value = v

    return run_op("recompute", jax.checkpoint(pure), *tensors)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """``fleet.recompute.recompute_sequential`` analog: checkpoint a
    Sequential in ``segments`` chunks."""
    segments = (ctx or {}).get("segments", 1)
    layers = list(functions)
    seg = max(1, len(layers) // max(1, segments))
    out = args
    i = 0
    while i < len(layers):
        chunk = layers[i : i + seg]

        def block(*xs, _chunk=tuple(chunk)):
            cur = xs
            for l in _chunk:
                cur = l(*cur) if isinstance(cur, tuple) else l(cur)
                if not isinstance(cur, tuple):
                    cur = (cur,)
            return cur[0] if len(cur) == 1 else cur

        # explicit param plumbing: collect over the whole chunk
        class _ChunkOwner(Layer):
            def __init__(self, mods):
                super().__init__()
                for j, m in enumerate(mods):
                    setattr(self, f"m{j}", m)

            def forward(self, *xs):
                return block(*xs)

        owner = _ChunkOwner(chunk)
        res = recompute(owner, *(out if isinstance(out, tuple) else (out,)), **kwargs)
        out = res
        i += seg
    return out
