"""True 1F1B and depth-first interleaved-VPP pipeline schedules, SPMD-style.

Capability analog of the reference's runtime pipeline schedulers:
``fleet/meta_parallel/pipeline_parallel.py:440`` (``forward_backward_pipeline``,
1F1B) and ``:906`` (``PipelineParallelWithInterleave``, interleaved VPP).

TPU-first design: instead of an actor runtime exchanging per-microbatch NCCL
p2p messages, the WHOLE forward+backward schedule is one traced XLA program.

* The schedule itself is a static table built in Python
  (:func:`build_1f1b_schedule`): slot × device → {idle | fwd | bwd} with
  microbatch + chunk ids, constructed greedily with backward-priority and a
  per-virtual-stage in-flight cap (``pp·v − vstage``) — the classic 1F1B
  warmup/steady/cooldown emerges from the cap, and chunks interleave
  depth-first (deeper chunks scheduled first) for VPP.
* Execution is a ``shard_map`` + ``fori_loop`` over slots: forward ticks run
  ``stage_fn`` (by default under ``jax.vjp``, ring-buffering the pullback
  residuals so backward never re-runs the forward; with ``recompute=True``
  only stage *inputs* are buffered and backward recomputes, the reference's
  opt-in recompute); activations and cotangents ride two
  ``collective-permute`` rings over ICI.
* Activation memory is bounded: a ``[v, pp, microbatch]`` ring buffer per
  device — in-flight microbatches per stage never exceed the cap,
  **independent of the microbatch count** (GPipe holds all M).
* The loss head runs per-microbatch on the last virtual stage inside the
  schedule (that is what makes true 1F1B possible — backward starts while
  later microbatches are still being forwarded).

The public Tensor-level op (:func:`pipeline_train_1f1b`) wraps the schedule
in ``jax.custom_vjp``: forward returns the mean loss and stashes
(param-grads, input-grad); ``loss.backward()`` just scales and routes them —
the tape never re-differentiates the pipeline loop.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from ._compat import shard_map
from jax.sharding import PartitionSpec as P

from ..core.dispatch import mark_derived, mark_inputs, run_op
from ..core.tensor import Tensor
from ..distributed import topology
from .utils import manual_sharding_mode

PP_AXIS = "pp"

_IDLE, _FWD, _BWD = 0, 1, 2


class Schedule1F1B:
    """Static schedule tables (all numpy, [T, n]) + occupancy stats."""

    def __init__(self, opc, mb, ch, arr_f_mb, arr_f_ch, arr_c_mb, arr_c_ch,
                 peak_in_flight, n_stages, n_micro, v, buf_depth):
        self.opc = opc
        self.mb = mb
        self.ch = ch
        self.arr_f_mb = arr_f_mb
        self.arr_f_ch = arr_f_ch
        self.arr_c_mb = arr_c_mb
        self.arr_c_ch = arr_c_ch
        self.peak_in_flight = peak_in_flight  # per device, max buffered mbs
        self.n_stages = n_stages
        self.n_micro = n_micro
        self.v = v
        self.n_slots = opc.shape[0]
        # ring-buffer depth: >= the max per-VIRTUAL-STAGE occupancy of both
        # the activation and cotangent buffers — slot reuse (m % buf_depth)
        # is only safe when a vstage never holds more than buf_depth entries
        self.buf_depth = buf_depth


@functools.lru_cache(maxsize=64)
def build_1f1b_schedule(n_stages: int, n_micro: int, v: int = 1) -> Schedule1F1B:
    """Greedy 1F1B/VPP scheduler over ``n_stages·v`` virtual stages.

    Virtual stage ``vs`` lives on device ``vs % n_stages`` (depth-first chunk
    placement, ``PipelineParallelWithInterleave`` layout).  Backward has
    priority; forwards are capped at ``n_stages·v − vs`` in flight per
    virtual stage.  The LAST virtual stage schedules no forward op — its
    backward recomputes the stage forward together with the loss head.
    """
    n, nv = n_stages, n_stages * v
    f_slot = [[None] * n_micro for _ in range(nv)]
    b_slot = [[None] * n_micro for _ in range(nv)]
    next_f = [0] * nv
    next_b = [0] * nv

    def cap(vs):
        return max(1, nv - vs)

    rows = []
    t = 0
    t_max = 8 * nv * max(n_micro, n) + 64
    while sum(next_b) < nv * n_micro:
        if t > t_max:
            raise RuntimeError(
                f"1F1B scheduler deadlock: pp={n} micro={n_micro} v={v}")
        row = [(_IDLE, 0, 0)] * n
        busy = [False] * n
        # backward priority, deeper virtual stages first
        for vs in reversed(range(nv)):
            d = vs % n
            if busy[d] or next_b[vs] >= n_micro:
                continue
            m = next_b[vs]
            if vs == nv - 1:
                ready = (nv == 1) or (f_slot[nv - 2][m] is not None
                                      and f_slot[nv - 2][m] < t)
            else:
                ready = b_slot[vs + 1][m] is not None and b_slot[vs + 1][m] < t
            # a mid-stage backward also needs its own forward done
            if vs != nv - 1:
                ready = ready and f_slot[vs][m] is not None and f_slot[vs][m] < t
            if ready:
                row[d] = (_BWD, m, vs // n)
                b_slot[vs][m] = t
                next_b[vs] += 1
                busy[d] = True
        # forwards: deeper chunks first (depth-first interleave)
        for vs in reversed(range(nv - 1)):  # last vstage has no fwd op
            d = vs % n
            if busy[d] or next_f[vs] >= n_micro:
                continue
            m = next_f[vs]
            if m - next_b[vs] >= cap(vs):
                continue  # in-flight cap: the 1F1B memory bound
            ready = (vs == 0) or (f_slot[vs - 1][m] is not None
                                  and f_slot[vs - 1][m] < t)
            if ready:
                row[d] = (_FWD, m, vs // n)
                f_slot[vs][m] = t
                next_f[vs] += 1
                busy[d] = True
        rows.append(row)
        t += 1

    T = len(rows)
    opc = np.zeros((T, n), np.int32)
    mb = np.zeros((T, n), np.int32)
    ch = np.zeros((T, n), np.int32)
    for ti, row in enumerate(rows):
        for d, (c, m, k) in enumerate(row):
            opc[ti, d], mb[ti, d], ch[ti, d] = c, m, k

    # arrival tables: what lands on each ring at the START of slot t
    # (sent at the end of slot t-1)
    arr_f_mb = np.full((T, n), -1, np.int32)
    arr_f_ch = np.zeros((T, n), np.int32)
    arr_c_mb = np.full((T, n), -1, np.int32)
    arr_c_ch = np.zeros((T, n), np.int32)
    for ti in range(1, T):
        for d in range(n):
            pd = (d - 1) % n   # fwd ring source
            c, m, k = rows[ti - 1][pd]
            if c == _FWD:
                vs = k * n + pd
                if vs + 1 <= nv - 1 and (vs + 1) % n == d:
                    arr_f_mb[ti, d] = m
                    arr_f_ch[ti, d] = (vs + 1) // n
            nd = (d + 1) % n   # cotangent ring source
            c, m, k = rows[ti - 1][nd]
            if c == _BWD:
                vs = k * n + nd
                if vs - 1 >= 0 and (vs - 1) % n == d:
                    arr_c_mb[ti, d] = m
                    arr_c_ch[ti, d] = (vs - 1) // n
    # the last vstage's "forward" is a pure arrival (no op): its effective
    # f_slot is the arrival slot, needed for the occupancy accounting below
    for m in range(n_micro):
        if nv >= 2:
            f_slot[nv - 1][m] = f_slot[nv - 2][m] + 1 if f_slot[nv - 2][m] is not None else None

    # peak buffered microbatches per device (forwarded/arrived but not yet
    # backwarded, summed over that device's chunks)
    peak = [0] * n
    for d in range(n):
        for ti in range(T):
            held = 0
            for k in range(v):
                vs = k * n + d
                for m in range(n_micro):
                    fs = f_slot[vs][m]
                    bs = b_slot[vs][m]
                    if fs is not None and fs <= ti and (bs is None or bs > ti):
                        held += 1
            peak[d] = max(peak[d], held)

    # buffer depth: max per-vstage occupancy of (a) saved activations
    # (forward/arrival -> backward) and (b) buffered cotangents
    # (produced at b(m, vs+1) -> consumed at b(m, vs))
    depth = 1
    for vs in range(nv):
        for ti in range(T):
            held_a = sum(
                1 for m in range(n_micro)
                if f_slot[vs][m] is not None and f_slot[vs][m] <= ti
                and (b_slot[vs][m] is None or b_slot[vs][m] > ti))
            held_c = 0
            if vs < nv - 1:
                held_c = sum(
                    1 for m in range(n_micro)
                    if b_slot[vs + 1][m] is not None
                    and b_slot[vs + 1][m] <= ti
                    and (b_slot[vs][m] is None or b_slot[vs][m] > ti))
            depth = max(depth, held_a, held_c)
    # +1 guard: an arrival stored at the start of a slot can coexist with
    # the entry whose backward runs later in that same slot
    depth = min(depth + 1, n_micro)

    from ..observability import get_tracer

    get_tracer().instant("1f1b_schedule_built", cat="parallel",
                         stages=n_stages, n_micro=n_micro, v=v,
                         ticks=len(opc), buffer_depth=depth,
                         peak_in_flight=max(peak) if peak else 0)
    return Schedule1F1B(opc, mb, ch, arr_f_mb, arr_f_ch, arr_c_mb, arr_c_ch,
                        peak, n, n_micro, v, depth)


# --------------------------------------------------------------------------
# SPMD executor
# --------------------------------------------------------------------------

def pipeline_train_spmd(stage_fn: Callable, stage_params: Any,
                        head_fn: Callable, head_params: Any,
                        x: jnp.ndarray, targets: Any, n_microbatch: int,
                        v: int = 1, mesh=None, extra: Any = None,
                        axis: str = PP_AXIS, dp_axis: Optional[str] = "dp",
                        stage_has_aux: bool = False,
                        aux_weight: float = 0.0,
                        recompute: bool = False):
    """Run the full 1F1B train schedule; returns
    ``(mean_loss, dx, stage_grads, head_grads)``.

    ``stage_params``: pytree, leaves ``[n·v, ...]`` in device-major layout —
    row ``d·v + k`` holds virtual stage ``k·n + d`` (use
    :func:`stack_device_major`).  ``stage_fn(params_one_stage, act, extra)``
    is one virtual stage's forward; ``head_fn(head_params, act, target_mb)``
    returns that microbatch's scalar loss.  ``x``: ``[B, ...]`` pipeline
    input (post-embedding); ``targets``: ``[B, ...]`` labels.

    If the mesh has a ``dp`` axis that divides the microbatch size, each
    microbatch is additionally data-sharded over it (grads pmean'd across
    dp groups — pp×dp composition in one program).

    With ``stage_has_aux=True``, ``stage_fn`` returns ``(act, aux_scalar)``
    (e.g. MoE load-balance loss); every stage's aux joins the total loss
    weighted by ``aux_weight`` and is differentiated in that stage's
    backward tick.

    ``recompute=False`` (default) matches the reference's plain 1F1B
    (``pipeline_parallel.py:440``): forward ticks run ``jax.vjp`` once and
    stash the flattened pullback residuals in ring buffers; backward ticks
    rebuild the pullback and never re-run the stage forward — no duplicate
    forward FLOPs, activation memory still bounded by the in-flight cap.
    ``recompute=True`` buffers only stage INPUTS and re-runs the stage
    forward under ``jax.vjp`` at backward ticks — minimal memory, ~1/3
    extra FLOPs (the reference's opt-in ``fleet/recompute/recompute.py``).
    Choose via ``DistributedStrategy.recompute`` at the fleet level.
    """
    mesh = mesh or topology.get_mesh()
    if not stage_has_aux:
        _inner_stage = stage_fn

        def stage_fn(p, a, e):  # noqa: F811 — uniform (act, aux) contract
            return _inner_stage(p, a, e), jnp.zeros((), jnp.float32)
    n = mesh.shape[axis]
    sched = build_1f1b_schedule(n, n_microbatch, v)
    B = x.shape[0]
    assert B % n_microbatch == 0, f"batch {B} % microbatches {n_microbatch}"
    mb_sz = B // n_microbatch
    micro = x.reshape((n_microbatch, mb_sz) + x.shape[1:])
    tgt = jax.tree.map(
        lambda a: a.reshape((n_microbatch, mb_sz) + a.shape[1:]), targets)

    dp = mesh.shape.get(dp_axis, 1) if dp_axis else 1
    use_dp = dp > 1 and mb_sz % dp == 0
    mb_spec = P(None, dp_axis) if use_dp else P()

    # schedule tables as device constants
    OPC = jnp.asarray(sched.opc)
    MBT = jnp.asarray(sched.mb)
    CHT = jnp.asarray(sched.ch)
    AFM = jnp.asarray(sched.arr_f_mb)
    AFC = jnp.asarray(sched.arr_f_ch)
    ACM = jnp.asarray(sched.arr_c_mb)
    ACC = jnp.asarray(sched.arr_c_ch)

    param_specs = jax.tree.map(lambda _: P(axis), stage_params,
                               is_leaf=lambda l: not isinstance(l, (dict, list, tuple)))

    def body(params_local, head_local, micro_local, tgt_local, extra_local):
        idx = jax.lax.axis_index(axis)
        perm_f = [(j, (j + 1) % n) for j in range(n)]
        perm_c = [(j, (j - 1) % n) for j in range(n)]
        nv = n * v

        params_dev = jax.tree.map(lambda p: p, params_local)  # [v, ...] leaves

        def params_at(k):
            return jax.tree.map(
                lambda p: jax.lax.dynamic_index_in_dim(p, k, 0, keepdims=False),
                params_dev)

        act_sds, _ = jax.eval_shape(
            lambda p, a: stage_fn(p, a, extra_local),
            params_at(0), micro_local[0])
        A_shape, A_dtype = act_sds.shape, act_sds.dtype

        def _stage_vjp(p, a):
            return jax.vjp(lambda pp, aa: stage_fn(pp, aa, extra_local), p, a)

        if not recompute:
            # Residual structure of one stage's pullback: the vjp closure is
            # a pytree (jax Partial) whose leaves are the saved values.
            # Classify each leaf ONCE on an abstract trace:
            #   'param' — a passthrough of a stage parameter (identity with
            #     an input tracer): re-fetched from params at the backward
            #     tick, NEVER ring-buffered (buffering would multiply the
            #     per-device weight memory by ~buf_depth);
            #   'const' — a trace constant (e.g. host rope tables): captured
            #     here, re-embedded at backward;
            #   'buf'   — a genuine activation residual: ring-buffered.
            probe: dict = {}

            def _probe(p, a):
                (y, aux), pull = _stage_vjp(p, a)
                leaves, vjp_def = jax.tree.flatten(pull)
                pid2idx = {id(x): i for i, x in enumerate(jax.tree.leaves(p))}
                cls, consts = [], []
                for leaf in leaves:
                    if not isinstance(leaf, jax.core.Tracer):
                        cls.append(("const", len(consts)))
                        consts.append(leaf)
                    elif id(leaf) in pid2idx:
                        cls.append(("param", pid2idx[id(leaf)]))
                    else:
                        cls.append(("buf", None))
                probe.update(cls=cls, consts=consts, vjp_def=vjp_def)
                return aux, leaves

            p_sds = jax.tree.map(
                lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype), params_at(0))
            aux_sds, leaf_sds = jax.eval_shape(
                _probe, p_sds, jax.ShapeDtypeStruct(A_shape, A_dtype))
            res_cls, res_consts = probe["cls"], probe["consts"]
            vjp_def = probe["vjp_def"]
            buf_pos = [i for i, c in enumerate(res_cls) if c[0] == "buf"]
            res_sds = [leaf_sds[i] for i in buf_pos]
            aux_dtype = aux_sds.dtype
        else:
            res_sds, vjp_def, buf_pos, res_cls, res_consts = [], None, [], [], []
            aux_dtype = jnp.float32

        def _idx2(k, m, ndim):
            z = jnp.zeros((), jnp.int32)
            return ((jnp.asarray(k, jnp.int32),
                     jnp.asarray(m % sched.buf_depth, jnp.int32))
                    + (z,) * (ndim - 2))

        def buf_set(buf, k, m, val):
            return jax.lax.dynamic_update_slice(
                buf, val[None, None], _idx2(k, m, buf.ndim))

        def buf_get(buf, k, m):
            return jax.lax.dynamic_slice(
                buf, _idx2(k, m, buf.ndim),
                (1, 1) + buf.shape[2:])[0, 0]

        def tgt_at(m):
            return jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, m, 0, keepdims=False),
                tgt_local)

        zero_head_grads = jax.tree.map(jnp.zeros_like, head_local)

        def fwd_branch(op):
            carry, t, m, k = op
            abuf, cbuf, sf, sc, grads, hgrads, dx, loss, rstate = carry
            is_stage0 = (idx == 0) & (k == 0)
            inj = jax.lax.dynamic_index_in_dim(micro_local, m, 0,
                                               keepdims=False).astype(A_dtype)
            a_in = jnp.where(is_stage0, inj, buf_get(abuf, k, m))
            if recompute:
                y, _ = stage_fn(params_at(k), a_in, extra_local)
                abuf = buf_set(abuf, k, m, a_in)
            else:
                (y, aux), pull = _stage_vjp(params_at(k), a_in)
                leaves = jax.tree.leaves(pull)
                rbufs, auxbuf = rstate
                rbufs = tuple(
                    buf_set(b, k, m, leaves[i])
                    for b, i in zip(rbufs, buf_pos))
                auxbuf = buf_set(auxbuf, k, m, aux)
                rstate = (rbufs, auxbuf)
            return (abuf, cbuf, y, jnp.zeros(A_shape, A_dtype), grads,
                    hgrads, dx, loss, rstate)

        def bwd_branch(op):
            carry, t, m, k = op
            abuf, cbuf, sf, sc, grads, hgrads, dx, loss, rstate = carry
            a_in = buf_get(abuf, k, m)
            p_k = params_at(k)
            is_last = (idx == (nv - 1) % n) & (k == v - 1)

            def last_case(_):
                # the last vstage has no forward tick — its stage forward
                # runs fused here in BOTH modes (nothing is duplicated)
                def full(p, hp, a):
                    y, aux = stage_fn(p, a, extra_local)
                    return (head_fn(hp, y, tgt_at(m))
                            + aux_weight * aux.astype(jnp.float32))
                loss_m, pull = jax.vjp(full, p_k, head_local, a_in)
                dp, dh, da = pull(jnp.ones((), loss_m.dtype))
                return dp, dh, da.astype(A_dtype), loss_m

            def mid_case(_):
                g = buf_get(cbuf, k, m).astype(A_dtype)
                if recompute:
                    (_, aux), pull = jax.vjp(
                        lambda p, a: stage_fn(p, a, extra_local), p_k, a_in)
                else:
                    rbufs, auxbuf = rstate
                    p_leaves = jax.tree.leaves(p_k)
                    leaves, bi = [], 0
                    for kind, j in res_cls:
                        if kind == "param":
                            leaves.append(p_leaves[j])
                        elif kind == "const":
                            leaves.append(res_consts[j])
                        else:
                            leaves.append(buf_get(rbufs[bi], k, m))
                            bi += 1
                    pull = jax.tree.unflatten(vjp_def, leaves)
                    aux = buf_get(auxbuf, k, m)
                dp, da = pull((g, jnp.asarray(aux_weight, aux.dtype)))
                return (dp, zero_head_grads, da.astype(A_dtype),
                        aux_weight * aux.astype(jnp.float32))

            dp, dh, da, loss_m = jax.lax.cond(is_last, last_case, mid_case,
                                              None)
            grads = jax.tree.map(lambda g, d: g.at[k].add(d), grads, dp)
            hgrads = jax.tree.map(jnp.add, hgrads, dh)
            loss = loss + loss_m.astype(jnp.float32)
            is_stage0 = (idx == 0) & (k == 0)
            z = jnp.zeros((), jnp.int32)
            dx = jnp.where(
                is_stage0,
                jax.lax.dynamic_update_slice(
                    dx, da[None].astype(dx.dtype),
                    (jnp.asarray(m, jnp.int32),) + (z,) * (dx.ndim - 1)),
                dx)
            return (abuf, cbuf, jnp.zeros(A_shape, A_dtype), da, grads,
                    hgrads, dx, loss, rstate)

        def idle_branch(op):
            carry, t, m, k = op
            abuf, cbuf, sf, sc, grads, hgrads, dx, loss, rstate = carry
            z = jnp.zeros(A_shape, A_dtype)
            return (abuf, cbuf, z, z, grads, hgrads, dx, loss, rstate)

        def slot(t, carry):
            abuf, cbuf, send_f, send_c, grads, hgrads, dx, loss, rstate = carry
            # receive what was sent at the end of the previous slot
            recv_f = jax.lax.ppermute(send_f, axis, perm_f)
            recv_c = jax.lax.ppermute(send_c, axis, perm_c)
            afm = AFM[t, idx]
            afc = AFC[t, idx]
            cur = buf_get(abuf, afc, jnp.maximum(afm, 0))
            abuf = buf_set(abuf, afc, jnp.maximum(afm, 0),
                           jnp.where(afm >= 0, recv_f, cur))
            acm = ACM[t, idx]
            acc_ = ACC[t, idx]
            curc = buf_get(cbuf, acc_, jnp.maximum(acm, 0))
            cbuf = buf_set(cbuf, acc_, jnp.maximum(acm, 0),
                           jnp.where(acm >= 0, recv_c, curc))

            code = OPC[t, idx]
            m = MBT[t, idx]
            k = CHT[t, idx]
            carry2 = (abuf, cbuf, send_f, send_c, grads, hgrads, dx, loss,
                      rstate)
            return jax.lax.switch(code, [idle_branch, fwd_branch, bwd_branch],
                                  (carry2, t, m, k))

        abuf0 = jnp.zeros((v, sched.buf_depth) + A_shape, A_dtype)
        cbuf0 = jnp.zeros((v, sched.buf_depth) + A_shape, A_dtype)
        z = jnp.zeros(A_shape, A_dtype)
        grads0 = jax.tree.map(jnp.zeros_like, params_dev)
        dx0 = jnp.zeros((n_microbatch,) + micro_local.shape[1:], x.dtype)
        if recompute:
            rstate0 = ()
        else:
            rstate0 = (tuple(
                jnp.zeros((v, sched.buf_depth) + s.shape, s.dtype)
                for s in res_sds),
                jnp.zeros((v, sched.buf_depth), aux_dtype))
        init = (abuf0, cbuf0, z, z, grads0, zero_head_grads, dx0,
                jnp.zeros((), jnp.float32), rstate0)
        out = jax.lax.fori_loop(0, sched.n_slots, slot, init)
        _, _, _, _, grads, hgrads, dx, loss, _ = out
        # replicate results: loss/head/dx live on single stages.  The loss is
        # the MEAN over microbatches while each backward used cotangent 1.0,
        # so every gradient is scaled by 1/M to match d(mean)/dθ.
        inv_m = 1.0 / n_microbatch
        loss = jax.lax.psum(loss, axis) * inv_m
        hgrads = jax.tree.map(
            lambda a: jax.lax.psum(a, axis) * inv_m, hgrads)
        dx = jax.lax.psum(dx, axis) * inv_m
        grads = jax.tree.map(lambda a: a * inv_m, grads)
        if use_dp:
            # loss/grads are per-dp-group means; global = mean across groups
            loss = jax.lax.pmean(loss, dp_axis)
            grads = jax.tree.map(lambda a: jax.lax.pmean(a, dp_axis), grads)
            hgrads = jax.tree.map(lambda a: jax.lax.pmean(a, dp_axis), hgrads)
            dx = dx / dp  # stays batch-sharded; d(global mean)/d(local x)
        return loss, dx, grads, hgrads

    grad_specs = jax.tree.map(
        lambda _: P(axis), stage_params,
        is_leaf=lambda l: not isinstance(l, (dict, list, tuple)))
    tgt_specs = jax.tree.map(lambda _: mb_spec, targets)
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, P(), mb_spec, tgt_specs, P()),
        out_specs=(P(), mb_spec, grad_specs, P()),
        check_vma=False)
    with manual_sharding_mode():
        loss, dx, sgrads, hgrads = mapped(stage_params, head_params, micro,
                                          tgt, extra)
    dx = dx.reshape(x.shape)
    return loss, dx, sgrads, hgrads


# --------------------------------------------------------------------------
# Tensor-level op (tape integration)
# --------------------------------------------------------------------------

def pipeline_train_1f1b(layer, x: Tensor, targets: Tensor,
                        head_params: Sequence[Tensor],
                        head_apply: Callable, n_microbatch: int,
                        extra: Any = None, axis: str = PP_AXIS,
                        aux_weight: float = 0.0,
                        recompute: bool = False) -> Tensor:
    """Tensor-level 1F1B train step over a :class:`PipelineLayer`.

    Returns the mean loss; ``loss.backward()`` routes the schedule-computed
    gradients onto the stage parameters (via scatter hooks), the head
    parameters, and ``x`` (so embedding backward runs through the tape) —
    the pipeline loop itself is never re-differentiated (``jax.custom_vjp``
    with the grads as residuals).

    ``head_apply(head_values, act, tgt) -> scalar`` is the pure-JAX loss
    head run per microbatch on the last virtual stage (final norm + LM head
    + criterion for the Llama case).
    """
    mesh = topology.get_mesh()
    n = mesh.shape[axis]
    v = layer.num_virtual_stages
    assert layer.num_stages == n * v, (layer.num_stages, n, v)
    stage_layers = [layer.get_stage_layers(s) for s in range(layer.num_stages)]
    order = device_major_order(n, v)

    mark_inputs([p for ls in stage_layers for l in ls
                 for _, p in l.named_parameters()] + list(head_params))

    def state_of(ls):
        return [[p._value for _, p in l.named_parameters()] for l in ls]

    states = [state_of(stage_layers[vs]) for vs in order]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
    templates = stage_layers[0]

    def _layer_aux(l):
        """MoE load-balance loss left on the layer by its forward."""
        for holder in (l, getattr(l, "mlp", None)):
            al = getattr(holder, "aux_loss", None) if holder is not None else None
            if al is not None:
                return al._value if isinstance(al, Tensor) else al
        return None

    def stage_fn(params, act, _extra):
        cur = act
        aux = jnp.zeros((), jnp.float32)
        for li, l in enumerate(templates):
            saved = [p._value for _, p in l.named_parameters()]
            for (pn, p), vv in zip(l.named_parameters(), params[li]):
                p._value = vv
            try:
                out = l(Tensor(cur, stop_gradient=True))
                cur = out._value if isinstance(out, Tensor) else out
                al = _layer_aux(l)
                if al is not None:
                    aux = aux + al.astype(jnp.float32)
            finally:
                for (pn, p), vv in zip(l.named_parameters(), saved):
                    p._value = vv
        return cur, aux

    treedef = jax.tree.structure(stacked)
    n_head = len(head_params)

    def f(xv, *pvals, targets=None):
        head_vals = tuple(pvals[:n_head])
        stacked_tree = jax.tree.unflatten(treedef, list(pvals[n_head:]))

        @jax.custom_vjp
        def op(xv, hv, st):
            loss, _, _, _ = pipeline_train_spmd(
                stage_fn, st, head_apply, hv, xv, targets, n_microbatch,
                v=v, mesh=mesh, extra=extra, axis=axis,
                stage_has_aux=True, aux_weight=aux_weight,
                recompute=recompute)
            return loss

        def op_fwd(xv, hv, st):
            loss, dx, sg, hg = pipeline_train_spmd(
                stage_fn, st, head_apply, hv, xv, targets, n_microbatch,
                v=v, mesh=mesh, extra=extra, axis=axis,
                stage_has_aux=True, aux_weight=aux_weight,
                recompute=recompute)
            return loss, (dx, hg, sg)

        def op_bwd(res, g):
            dx, hg, sg = res
            return (dx * g, jax.tree.map(lambda a: a * g, hg),
                    jax.tree.map(lambda a: a * g, sg))

        op.defvjp(op_fwd, op_bwd)
        return op(xv, head_vals, stacked_tree)

    # stacked leaf -> the real Parameters it came from (device-major rows)
    leaves = jax.tree.leaves(stacked)
    param_groups = []
    for li, l in enumerate(templates):
        for pi in range(len(l.parameters())):
            param_groups.append(
                [list(stage_layers[vs][li].parameters())[pi] for vs in order])

    leaf_tensors = []
    for leaf, group in zip(leaves, param_groups):
        t = Tensor(leaf, stop_gradient=all(p.stop_gradient for p in group))

        def scatter_grad(g, _group=group):
            for r, p in enumerate(_group):
                gs = g._value[r]
                p.grad = (Tensor(gs) if p.grad is None
                          else Tensor(p.grad._value + gs))
            return g

        if not t.stop_gradient:
            t.register_hook(scatter_grad)
        leaf_tensors.append(t)

    mark_derived(leaf_tensors)
    return run_op("pipeline_1f1b", f, x, *head_params, *leaf_tensors,
                  targets=targets)


def _layer_sig(obj):
    """Structural signature of one pipeline item: type tree + param shapes +
    per-sublayer scalar config (epsilon, activation names, ...).  Only items
    with equal signatures may share one staged ``stage_fn`` — structural
    equality alone is NOT enough (Block(act='relu') vs Block(act='gelu')
    must not merge, since the schedule runs every stage through stage 0's
    template)."""
    from ..nn.layers import Layer

    if isinstance(obj, Layer):
        def cfg(l):
            return tuple(sorted(
                (k, v) for k, v in vars(l).items()
                if not k.startswith("_") and k != "training"
                and isinstance(v, (int, float, bool, str))))

        return (tuple((type(s).__name__, cfg(s))
                      for s in obj.sublayers(include_self=True)),
                tuple(tuple(p.shape) for _, p in obj.named_parameters()))
    # bare callables: only the SAME object repeated may merge
    return ("callable", id(obj))


class PipelineSegmentationError(RuntimeError):
    """The stack has no homogeneous block divisible into pp·v stages —
    callers fall back to the F-then-B microbatched schedule."""


class _BlockPipe:
    """Adapter exposing a homogeneous layer block with the
    ``num_stages``/``get_stage_layers`` interface of PipelineLayer."""

    def __init__(self, block, n, v):
        assert len(block) % (n * v) == 0
        self.num_virtual_stages = v
        self.num_stages = n * v
        per = len(block) // (n * v)
        self._stages = [block[s * per:(s + 1) * per]
                        for s in range(n * v)]

    def get_stage_layers(self, s):
        return self._stages[s]


def pipeline_train_1f1b_auto(pipe, inputs, labels, n_microbatch: int,
                             recompute: bool = False,
                             axis: str = PP_AXIS) -> Tensor:
    """True 1F1B for an arbitrary sequential stack (``LayerDesc`` case,
    ``pp_layers.py:261`` + ``fleet/model.py:32``).

    The stack is auto-segmented into [prefix | homogeneous block | suffix]:
    the longest run of structurally identical layers becomes the pipelined
    block (its length must divide by ``pp·v``); the prefix (e.g. embedding)
    runs on the autograd tape before the schedule, and the suffix (final
    norm / head) plus ``pipe.loss_fn`` run per-microbatch on the last
    stage inside the schedule — exactly how the Llama path treats
    embedding and LM head.  Raises with guidance when no such block exists
    (callers then use the F-then-B microbatched fallback)."""
    from ..distributed import topology as topo
    from ..nn.layers import Layer
    from .pipeline import SharedLayerDesc

    if pipe.loss_fn is None:
        raise RuntimeError("1F1B needs PipelineLayer(loss_fn=...)")
    mesh = topo.get_mesh()
    n = mesh.shape[axis]
    v = getattr(pipe, "num_virtual_stages", 1)
    items = list(pipe.run_order)
    descs = list(getattr(pipe, "_descs", items))
    # SharedLayerDesc items (tied weights, custom forward_func) never join
    # the staged block — position-unique signature keeps them in
    # prefix/suffix where the desc dispatch below handles them
    sigs = [("shared", i) if isinstance(d, SharedLayerDesc)
            else _layer_sig(o)
            for i, (o, d) in enumerate(zip(items, descs))]

    # longest contiguous run of one signature whose length divides pp·v
    best = None  # (len, start, end)
    i = 0
    while i < len(sigs):
        j = i
        while j < len(sigs) and sigs[j] == sigs[i]:
            j += 1
        run = j - i
        usable = run - run % (n * v)
        if usable >= n * v and (best is None or usable > best[0]):
            best = (usable, i, i + usable)
        i = j
    if best is None:
        raise PipelineSegmentationError(
            f"no homogeneous layer block divisible into {n * v} pipeline "
            "stages; use schedule_mode='F-then-B' for fully heterogeneous "
            "stacks")
    _, lo, hi = best

    def _apply(item, desc, x):
        # SharedLayerDesc dispatch matches PipelineLayer.forward
        if isinstance(desc, SharedLayerDesc) and desc.forward_func is not None:
            return desc.forward_func(item, x)
        return item(x)

    block = items[lo:hi]

    x = inputs
    for item, desc in zip(items[:lo], descs[:lo]):
        x = _apply(item, desc, x)

    suffix = list(zip(items[hi:], descs[hi:]))
    suffix_layers = [o for o, _ in suffix if isinstance(o, Layer)]
    head_layers = suffix_layers + (
        [pipe.loss_fn] if isinstance(pipe.loss_fn, Layer) else [])
    head_params = [p for l in head_layers for _, p in l.named_parameters()]

    def head_apply(head_values, act, tgt):
        flat = list(head_values)
        saved = []
        it = iter(flat)
        for l in head_layers:
            for _, p in l.named_parameters():
                saved.append((p, p._value))
                p._value = next(it)
        try:
            cur = Tensor(act, stop_gradient=True)
            for item, desc in suffix:
                cur = _apply(item, desc, cur)
            loss = pipe.loss_fn(cur, Tensor(tgt, stop_gradient=True))
            return loss._value if isinstance(loss, Tensor) else loss
        finally:
            for p, val in saved:
                p._value = val

    return pipeline_train_1f1b(
        _BlockPipe(block, n, v), x, labels, head_params, head_apply,
        n_microbatch, axis=axis, recompute=recompute)


def stack_device_major(per_vstage: Sequence, n: int, v: int):
    """Stack per-virtual-stage pytrees into device-major ``[n·v, ...]`` rows:
    row ``d·v + k`` ← virtual stage ``k·n + d`` (depth-first placement)."""
    order = [k * n + d for d in range(n) for k in range(v)]
    return jax.tree.map(lambda *xs: jnp.stack(xs),
                        *[per_vstage[i] for i in order])


def device_major_order(n: int, v: int) -> List[int]:
    return [k * n + d for d in range(n) for k in range(v)]
