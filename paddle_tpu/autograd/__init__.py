"""``paddle.autograd`` surface: backward, grad, PyLayer, functional jacobians.

Eager pieces ride the tape engine (core/autograd.py — RunBackward analog of
``fluid/eager/backward.cc:105``); higher-order derivatives are functional
transforms over pure functions (jax.jacfwd/jacrev), matching the capability of
the reference's ``paddle.incubate.autograd`` primitive system.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence

import jax
import jax.numpy as jnp

from ..core.autograd import Edge, GradNode, backward, grad, is_grad_enabled, no_grad  # noqa: F401
from ..core.dispatch import run_op
from ..core.tensor import Tensor, to_tensor, wrap_result


_saved_tensor_hooks: List = []  # active (pack, unpack) pairs, innermost last


class saved_tensors_hooks:
    """(``autograd/saved_tensors_hooks`` analog) context manager installing
    a ``pack(tensor) -> obj`` / ``unpack(obj) -> tensor`` pair around
    tensors saved for backward.

    TPU-first scope: applies to tensors saved through
    ``PyLayerContext.save_for_backward`` — the user-facing save point on
    this substrate (the built-in ops' residuals live inside XLA's fused
    program where host-side packing would force device→host syncs; use
    ``paddle.distributed.recompute``/``jax.checkpoint`` to trade their
    memory instead)."""

    def __init__(self, pack_hook: Callable, unpack_hook: Callable):
        self.pair = (pack_hook, unpack_hook)

    def __enter__(self):
        _saved_tensor_hooks.append(self.pair)
        return self

    def __exit__(self, *exc):
        _saved_tensor_hooks.remove(self.pair)
        return False


class PyLayerContext:
    """Context passed to PyLayer.forward/backward (paddle.autograd.PyLayerContext)."""

    def __init__(self):
        self._saved = ()
        self._packed = None
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        if _saved_tensor_hooks:
            pack, unpack = _saved_tensor_hooks[-1]
            self._packed = ([pack(t) for t in tensors], unpack)
            self._saved = ()
        else:
            self._packed = None
            self._saved = tensors

    def saved_tensor(self):
        if self._packed is not None:
            objs, unpack = self._packed
            return tuple(unpack(o) for o in objs)
        return self._saved

    saved_tensors = property(lambda self: self.saved_tensor())


class PyLayerMeta(type):
    def __call__(cls, *a, **k):
        raise RuntimeError("PyLayer is not instantiable; call .apply(...)")


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd op with user-defined forward/backward
    (``python/paddle/autograd/py_layer.py`` capability).

    The backward runs as eager ops (so it may itself contain framework calls);
    gradients route into the tape via a custom GradNode.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(out, (list, tuple))
        outs = [out] if single else list(out)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        requires = is_grad_enabled() and any(not t.stop_gradient for t in tensor_inputs)
        if not requires:
            return out

        edges = [Edge(t, t._grad_node, t._out_index) for t in tensor_inputs if not t.stop_gradient]
        diff_inputs = [t for t in tensor_inputs if not t.stop_gradient]
        out_avals = [jax.ShapeDtypeStruct(tuple(o.shape), o.dtype) for o in outs]

        def backward_fn(cts):
            ct_tensors = [Tensor(c, stop_gradient=True) for c in cts]
            with no_grad():
                gin = cls.backward(ctx, *ct_tensors)
            gin = [gin] if isinstance(gin, Tensor) or gin is None else list(gin)
            raw: List[Any] = []
            gi = iter(gin)
            for t in diff_inputs:
                g = next(gi, None)
                raw.append(None if g is None else (g._value if isinstance(g, Tensor) else jnp.asarray(g)))
            return tuple(raw)

        node = GradNode(f"PyLayer<{cls.__name__}>", backward_fn, edges, out_avals)
        wrapped = wrap_result(tuple(o._value for o in outs), stop_gradient=False, node=node)
        return wrapped[0] if single else type(out)(wrapped)


def _functionalize(func: Callable, xs: Sequence[Tensor]):
    def pure(*vals):
        ts = [Tensor(v, stop_gradient=False) for v in vals]
        out = func(*ts) if len(ts) > 1 else func(ts[0])
        if isinstance(out, (list, tuple)):
            return tuple(o._value for o in out)
        return out._value

    return pure


def jacobian(func: Callable = None, xs=None, is_batched=False, *, ys=None):
    """``paddle.autograd.jacobian`` (functional form): J of func at xs."""
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    pure = _functionalize(func, xs_list)
    jac = jax.jacrev(pure, argnums=tuple(range(len(xs_list))))(*[t._value for t in xs_list])
    if isinstance(jac, tuple) and single:
        jac = jac[0]
    return jax.tree.map(lambda a: Tensor(a), jac)


def hessian(func: Callable, xs, is_batched=False):
    """``paddle.autograd.hessian`` (functional form)."""
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    pure = _functionalize(func, xs_list)
    hess = jax.hessian(pure, argnums=tuple(range(len(xs_list))))(*[t._value for t in xs_list])
    if isinstance(hess, tuple) and single:
        hess = hess[0]
        if isinstance(hess, tuple):
            hess = hess[0]
    return jax.tree.map(lambda a: Tensor(a), hess)


def vjp(func: Callable, xs, v=None):
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    pure = _functionalize(func, xs_list)
    out, vjp_fn = jax.vjp(pure, *[t._value for t in xs_list])
    if v is None:
        v_raw = jnp.ones_like(out)
    else:
        v_raw = v._value if isinstance(v, Tensor) else jax.tree.map(lambda t: t._value, v)
    grads = vjp_fn(v_raw)
    grads_t = [Tensor(g) for g in grads]
    return Tensor(out), (grads_t[0] if single else grads_t)


def jvp(func: Callable, xs, v=None):
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    pure = _functionalize(func, xs_list)
    primals = [t._value for t in xs_list]
    if v is None:
        tangents = [jnp.ones_like(p) for p in primals]
    elif isinstance(v, Tensor):
        tangents = [v._value]
    else:
        tangents = [t._value for t in v]
    out, jv = jax.jvp(pure, tuple(primals), tuple(tangents))
    return Tensor(out), Tensor(jv)
