"""Profiler summary statistics (``profiler/profiler_statistic.py`` analog).

Two sortable per-op tables, mirroring the reference's ``summary()``:

* **host op stats** — wall time of every eager ``run_op`` dispatch while
  the profiler is active (the reference's CPU-side operator times).  On
  an async backend this measures dispatch + trace-time, not device
  execution — the honest host-side number.
* **device op stats** — per-op durations from the chrome trace the
  profiler captured (``jax.profiler`` XPlane export), grouped by op name
  (the reference's GPU kernel table; here XLA/TPU device lanes).
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
from typing import Dict, List, Optional


class OpStat:
    __slots__ = ("name", "calls", "total", "max", "min")

    def __init__(self, name: str):
        self.name = name
        self.calls = 0
        self.total = 0.0
        self.max = 0.0
        self.min = float("inf")

    def add(self, dt: float):
        self.calls += 1
        self.total += dt
        self.max = max(self.max, dt)
        self.min = min(self.min, dt)

    @property
    def avg(self) -> float:
        return self.total / self.calls if self.calls else 0.0


class HostOpRecorder:
    """Dispatch timing hook target (installed via dispatch._set_op_timer)."""

    def __init__(self):
        self.stats: Dict[str, OpStat] = {}

    def __call__(self, name: str, dt: float):
        name = str(name) if name else "<anonymous>"
        stat = self.stats.get(name)
        if stat is None:
            stat = self.stats[name] = OpStat(name)
        stat.add(dt)


def collect_device_stats(log_dir: Optional[str]) -> Dict[str, OpStat]:
    """Per-op device-lane durations from the newest captured trace.
    ``None`` (no trace captured by this profiler) yields no stats."""
    if log_dir is None:
        return {}
    runs = sorted(glob.glob(os.path.join(log_dir, "plugins", "profile",
                                         "*")))
    stats: Dict[str, OpStat] = {}
    if not runs:
        return stats
    events, pids = [], {}
    for path in glob.glob(os.path.join(runs[-1], "*.trace.json.gz")):
        try:
            data = json.load(gzip.open(path))
        except (OSError, ValueError):
            continue
        for e in data.get("traceEvents", []):
            if e.get("ph") == "M" and e.get("name") == "process_name":
                pids[e["pid"]] = e["args"].get("name", str(e["pid"]))
            elif e.get("ph") == "X":
                events.append(e)
    device_pids = {p for p, n in pids.items()
                   if "TPU" in n.upper() or "/device" in n.lower()}
    if not device_pids:
        device_pids = set(pids)
    for e in events:
        if e["pid"] not in device_pids:
            continue
        name = e.get("name", "?")
        stat = stats.get(name)
        if stat is None:
            stat = stats[name] = OpStat(name)
        stat.add(e.get("dur", 0) / 1e6)  # trace us -> seconds
    return stats


_UNIT = {"s": 1.0, "ms": 1e3, "us": 1e6}


def _sort_key(sorted_by) -> str:
    name = getattr(sorted_by, "name", str(sorted_by or "CPUTotal"))
    for suffix in ("Total", "Avg", "Max", "Min"):
        if name.endswith(suffix):
            return suffix.lower() if suffix != "Total" else "total"
    return "total"


def summary_table(stats: Dict[str, OpStat], title: str,
                  sorted_by=None, time_unit: str = "ms",
                  top: Optional[int] = None) -> str:
    """Render one sortable stats table (the reference's ``_build_table``)."""
    scale = _UNIT.get(time_unit, 1e3)
    key = _sort_key(sorted_by)
    rows = sorted(stats.values(), key=lambda s: getattr(s, key),
                  reverse=True)
    if top:
        rows = rows[:top]
    grand = sum(s.total for s in stats.values()) or 1.0
    name_w = max([len(s.name[:48]) for s in rows] + [len("Name"), 4])
    header = (f"{'Name':{name_w}s} {'Calls':>7s} "
              f"{'Total(' + time_unit + ')':>12s} "
              f"{'Avg(' + time_unit + ')':>12s} "
              f"{'Max(' + time_unit + ')':>12s} "
              f"{'Min(' + time_unit + ')':>12s} {'Ratio(%)':>9s}")
    bar = "-" * len(header)
    lines = [bar, title, bar, header, bar]
    for s in rows:
        lines.append(
            f"{s.name[:48]:{name_w}s} {s.calls:7d} "
            f"{s.total * scale:12.4f} {s.avg * scale:12.4f} "
            f"{s.max * scale:12.4f} "
            f"{(0.0 if s.min == float('inf') else s.min) * scale:12.4f} "
            f"{100.0 * s.total / grand:9.2f}")
    lines.append(bar)
    return "\n".join(lines)


def build_summary(host_stats: Dict[str, OpStat], log_dir: str,
                  step_times: List[float], sorted_by=None,
                  op_detail: bool = True, time_unit: str = "ms") -> str:
    parts = []
    if step_times:
        scale = _UNIT.get(time_unit, 1e3)
        n = len(step_times)
        parts.append(
            f"steps: {n}, avg {sum(step_times) / n * scale:.3f} "
            f"{time_unit}/step, min "
            f"{min(step_times) * scale:.3f}, max "
            f"{max(step_times) * scale:.3f}")
    if op_detail and host_stats:
        parts.append(summary_table(
            host_stats, "Host operator summary (eager dispatch wall time)",
            sorted_by, time_unit))
    dev = collect_device_stats(log_dir)
    if op_detail and dev:
        parts.append(summary_table(
            dev, "Device operator summary (trace device lanes)",
            sorted_by, time_unit, top=30))
    return "\n\n".join(parts) if parts else "no profiling data recorded"
