"""``paddle.profiler`` over jax.profiler / XPlane (N34 TPU mapping).

The reference profiler (``fluid/platform/profiler/``: HostTracer + CUPTI
CudaTracer -> chrome trace) maps onto ``jax.profiler`` which captures host +
TPU device timelines into a TensorBoard/XPlane trace (viewable in Perfetto).
``RecordEvent`` maps to ``jax.profiler.TraceAnnotation``.
"""

from __future__ import annotations

import contextlib
import os
import time
from enum import Enum
from typing import Callable, Iterable, Optional

import jax


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    def scheduler(step: int):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        total = closed + ready + record
        if repeat and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on-trace-ready handler directing output into ``dir_name``
    (created here, like ``export_protobuf`` always did)."""

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        prof._log_dir = dir_name

    return handler


class Profiler:
    """``paddle.profiler.Profiler`` analog (profiler/profiler.py:346)."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, **kwargs):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(record=scheduler[1] - scheduler[0], skip_first=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else None
        )
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._log_dir = os.environ.get("PADDLE_TPU_PROFILE_DIR", "/tmp/paddle_tpu_profile")
        self._step = 0
        self._active = False
        self._step_times = []
        self._last_t = None

    def start(self):
        from ..core import dispatch as _dispatch
        from ..observability import get_tracer, trace_dispatch
        from .statistic import HostOpRecorder

        if self._on_trace_ready:
            # handlers configure the output dir (export_chrome_tracing /
            # export_protobuf set _log_dir) — must happen BEFORE the trace
            # starts or they would point at an already-written trace
            self._on_trace_ready(self)
        # a re-start() without stop() must not leak the previous pair of
        # bus subscriptions (the old single-slot hook replaced them)
        self._unsubscribe()
        self._host_recorder = HostOpRecorder()
        # op-bus subscription: coexists with ServingMetrics / user
        # subscribers instead of owning the old single-slot hook
        self._remove_timer = _dispatch.add_op_timer(self._host_recorder)
        # host spans: every dispatched op lands in the process span
        # tracer, the source for export(path, format="json")
        self._tracer = get_tracer()
        self._remove_spans = trace_dispatch(self._tracer)
        self._t_start = time.perf_counter()
        self._t_stop = None
        if not self._timer_only:
            jax.profiler.start_trace(self._log_dir)
            self._active = True
        self._last_t = time.perf_counter()

    def _unsubscribe(self):
        for attr in ("_remove_timer", "_remove_spans"):
            remover = getattr(self, attr, None)
            if remover is not None:
                remover()
                setattr(self, attr, None)

    def stop(self):
        self._unsubscribe()
        self._t_stop = time.perf_counter()
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._captured = True  # THIS profiler wrote a trace run

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_t is not None:
            self._step_times.append(now - self._last_t)
        self._last_t = now
        self._step += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return "no steps recorded"
        avg = sum(self._step_times[-10:]) / len(self._step_times[-10:])
        return f"avg step time {avg * 1000:.2f} ms"

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Sortable per-op statistics tables
        (``profiler_statistic.py`` analog): host operator dispatch times
        + device-lane op times from the captured trace.  Prints AND
        returns the report."""
        from .statistic import build_summary

        stats = getattr(self, "_host_recorder", None)
        # only read trace dirs THIS profiler wrote — the shared default
        # log dir may hold a stale/foreign run's device table
        log_dir = (self._log_dir if getattr(self, "_captured", False)
                   else None)
        report = build_summary(
            stats.stats if stats else {}, log_dir,
            self._step_times, sorted_by=sorted_by, op_detail=op_detail,
            time_unit=time_unit)
        print(report)
        return report

    def export(self, path: str, format: str = "json"):
        """Write this profiling session's host spans as chrome
        trace-event JSON to ``path`` (loadable with
        :func:`load_profiler_result`, viewable in Perfetto/chrome).
        Previously a print-only stub.  Any device-side XPlane trace still
        lives under ``self._log_dir`` for TensorBoard."""
        if format != "json":
            raise ValueError(
                f"unsupported export format {format!r}: host spans export "
                "as chrome trace-event 'json'; the device XPlane protobuf "
                f"is under {self._log_dir}")
        from ..observability.export import export_chrome_trace

        tracer = getattr(self, "_tracer", None)
        if tracer is None:
            from ..observability import get_tracer

            tracer = get_tracer()
        # only THIS session's window: the shared process tracer may hold
        # spans from before start() / after stop()
        t0 = getattr(self, "_t_start", 0.0)
        t1 = getattr(self, "_t_stop", None) or float("inf")
        spans = [s for s in tracer.spans()
                 if s.start + s.duration >= t0 and s.start <= t1]
        export_chrome_trace(spans, path, epoch_offset=tracer.epoch_offset)
        if self._active or getattr(self, "_captured", False):
            print(f"host spans -> {path}; XPlane/TensorBoard trace under "
                  f"{self._log_dir}")
        return path

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class RecordEvent:
    """Trace annotation (host_tracer.h:26 RecordEvent analog)."""

    def __init__(self, name: str, event_type=None):
        self._ann = jax.profiler.TraceAnnotation(name)

    def begin(self):
        self._ann.__enter__()

    def end(self):
        self._ann.__exit__(None, None, None)

    def __enter__(self):
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(None, None, None)
        return False


def load_profiler_result(filename: str):
    """Read an exported chrome trace-event JSON back into a
    :class:`~paddle_tpu.observability.ProfilerResult` (flat events +
    reconstructed span tree).  Previously a ``NotImplementedError``
    stub; XPlane trace dirs remain TensorBoard/Perfetto territory."""
    from ..observability.export import load_profiler_result as _load

    return _load(filename)


@contextlib.contextmanager
def benchmark():
    t0 = time.perf_counter()
    yield
    jax.effects_barrier()
    print(f"benchmark: {time.perf_counter() - t0:.4f}s")


class SortedKeys(Enum):
    """(``profiler/profiler_statistic.py`` SortedKeys) summary sort keys."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(Enum):
    """(``profiler/profiler.py`` SummaryView) summary table kinds."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(dir_name: str, worker_name=None):
    """(``profiler.py`` export_protobuf) on-trace-ready handler directing
    the raw XPlane protobuf output (jax.profiler's native format, the
    artifact TensorBoard ingests) into ``dir_name``."""

    def handler(prof):
        import os

        os.makedirs(dir_name, exist_ok=True)
        prof._log_dir = dir_name

    return handler
