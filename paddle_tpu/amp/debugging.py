"""Numerics debugging (``python/paddle/amp/debugging.py:339`` check_numerics
analog + FLAGS_check_nan_inf plumbing — SURVEY.md §5 'race detection').
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core import flags
from ..core.tensor import Tensor, to_tensor


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3


# op name -> count of AMP low-precision dispatches (FLAGS_low_precision_op_list)
_low_precision_ops: dict = {}


def low_precision_op_list() -> dict:
    """Ops AMP ran in low precision since the flag was enabled
    (``paddle.amp.debugging.collect_operator_stats`` capability over
    ``FLAGS low_precision_op_list``)."""
    return dict(_low_precision_ops)


def clear_low_precision_op_list():
    _low_precision_ops.clear()


class TensorCheckerConfig:
    """Per-op skip config (amp/debugging.py:157 analog)."""

    def __init__(self, enable=True, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None, skipped_op_list=None,
                 debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list


def enable_operator_stats_collection():
    flags.set_flags({"eager_log_ops": True})


def disable_operator_stats_collection():
    flags.set_flags({"eager_log_ops": False})


def enable_tensor_checker(config: Optional[TensorCheckerConfig] = None):
    if config is None or config.enable:
        flags.set_flags({"check_nan_inf": True})
        if config is not None and config.debug_mode != DebugMode.CHECK_NAN_INF_AND_ABORT:
            flags.set_flags({"check_nan_inf_level": 1})


def disable_tensor_checker():
    flags.set_flags({"check_nan_inf": False})


def check_numerics(tensor, op_type="", var_name="", debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Scan one tensor for NaN/Inf; returns (num_nan, num_inf, num_zero)."""
    v = tensor._host_read()
    if not np.issubdtype(v.dtype, np.floating):
        return to_tensor(0), to_tensor(0), to_tensor(int((v == 0).sum()))
    n_nan = int(np.isnan(v).sum())
    n_inf = int(np.isinf(v).sum())
    n_zero = int((v == 0).sum())
    if (n_nan or n_inf) and debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
        raise FloatingPointError(
            f"check_numerics: op={op_type} var={var_name} nan={n_nan} inf={n_inf}"
        )
    return to_tensor(n_nan), to_tensor(n_inf), to_tensor(n_zero)
