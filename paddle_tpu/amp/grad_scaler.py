"""Loss scaling for fp16 AMP (``python/paddle/amp/grad_scaler.py:573`` analog).

On TPU the recommended dtype is bf16 (no scaling needed) — the scaler is then
an API-compatible passthrough; with fp16 it implements dynamic loss scaling
identical in behavior to the reference (growth/backoff on found-inf).
"""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class GradScaler:
    def __init__(
        self,
        enable: bool = True,
        init_loss_scaling: float = 2.0**15,
        incr_ratio: float = 2.0,
        decr_ratio: float = 0.5,
        incr_every_n_steps: int = 1000,
        decr_every_n_nan_or_inf: int = 2,
        use_dynamic_loss_scaling: bool = True,
    ):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, loss: Tensor) -> Tensor:
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._all_params():
            if p.grad is not None:
                g = p.grad._value * inv
                if not bool(np.isfinite(np.asarray(g)).all()):
                    found = True
                p.grad = Tensor(g, stop_gradient=True)
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._update()

    def minimize(self, optimizer, loss):
        self.step(optimizer)

    def update(self):
        pass  # paddle's GradScaler.update is called inside step here

    def _update(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        from ..core.tensor import to_tensor

        return to_tensor(self._scale)

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every,
            "decr_every_n_nan_or_inf": self._decr_every,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
            "use_dynamic_loss_scaling": self._dynamic,
        }

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)
