from .auto_cast import amp_guard, auto_cast, decorate, white_list, black_list  # noqa: F401
from .grad_scaler import GradScaler  # noqa: F401
from . import debugging  # noqa: F401


def _dtype_supported(dtype) -> bool:
    """Probe the ACTIVE backend with a tiny computation — name lists would
    misreport PJRT plugin platforms (e.g. a tunneled TPU shows up under
    the plugin's own platform name)."""
    import jax
    import jax.numpy as jnp

    try:
        (jnp.zeros((), dtype) + jnp.zeros((), dtype)).block_until_ready()
        return True
    except Exception:
        return False


def is_bfloat16_supported(device=None):
    """(``amp/__init__.py`` is_bfloat16_supported) — bf16 is the native
    matmul dtype on TPU; probed live on whatever backend is active."""
    import jax.numpy as jnp

    return _dtype_supported(jnp.bfloat16)


def is_float16_supported(device=None):
    """(``amp/__init__.py`` is_float16_supported) — probed live (fp16
    works on GPU/CPU; TPU accepts fp16 arrays, matmul is bf16-first)."""
    import jax.numpy as jnp

    return _dtype_supported(jnp.float16)
