"""Automatic mixed precision (``python/paddle/amp/auto_cast.py:729`` analog).

TPU-first: bf16 is the native fast dtype (MXU takes bf16 inputs with f32
accumulation), so AMP O1 means "cast MXU-bound op inputs to bf16"; O2 casts
whole layers with f32 master weights kept by the optimizer.  No loss scaling
is needed for bf16 (GradScaler is API-compatible and enabled only for fp16).
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional, Set

import jax.numpy as jnp

from ..core import dispatch as _dispatch
from ..core import dtype as dtype_mod

# Default op lists — capability analog of the reference's O1 white/black lists
# (python/paddle/amp/amp_lists.py).
white_list: Set[str] = {
    "matmul", "mm", "bmm", "einsum", "conv2d", "conv1d", "conv3d",
    "conv2d_transpose", "addmm", "attention", "flash_attention", "linear",
}
black_list: Set[str] = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp", "softmax_with_cross_entropy",
    "cross_entropy", "mean", "sum", "norm", "softmax", "log_softmax",
    "layer_norm", "rms_norm", "batch_norm", "cumsum", "pow",
}


class _AmpState(threading.local):
    def __init__(self):
        self.stack = []

    def enabled(self):
        return bool(self.stack) and self.stack[-1]["enable"]

    def current(self):
        return self.stack[-1] if self.stack else None

    def cast_args(self, op_name, args):
        from ..core.tensor import Tensor

        cfg = self.current()
        if cfg is None or not cfg["enable"]:
            return args
        level = cfg["level"]
        target = cfg["dtype"]
        base = op_name.split("/")[-1]
        if level == "O2":
            do_cast = base not in cfg["black"]
        else:
            do_cast = base in cfg["white"] and base not in cfg["black"]
        if not do_cast:
            return args
        from ..core import flags

        if flags.flag("low_precision_op_list"):
            from . import debugging

            debugging._low_precision_ops[base] = (
                debugging._low_precision_ops.get(base, 0) + 1)
        out = []
        for a in args:
            if isinstance(a, Tensor) and a.dtype == dtype_mod.float32:
                out.append(_fast_cast(a, target))
            else:
                out.append(a)
        return tuple(out)


def _fast_cast(t, target):
    """Cast without re-entering the AMP hook (avoids recursion), but on-tape."""
    from ..core.dispatch import run_op

    state = _state.stack
    _state.stack = []
    try:
        return run_op("amp_cast", lambda x: x.astype(target), t)
    finally:
        _state.stack = state


_state = _AmpState()
_dispatch._register_amp_state(_state)


class auto_cast:
    """``paddle.amp.auto_cast`` context manager."""

    def __init__(
        self,
        enable: bool = True,
        custom_white_list: Optional[Iterable[str]] = None,
        custom_black_list: Optional[Iterable[str]] = None,
        level: str = "O1",
        dtype: str = "bfloat16",
        use_promote: bool = True,
    ):
        if level not in ("O0", "O1", "O2", "OD"):
            raise ValueError(f"level must be O0/OD/O1/O2, got {level}")
        self.cfg = {
            "enable": enable and level != "O0",
            "level": level,
            "dtype": dtype_mod.convert_dtype(dtype),
            "white": set(white_list) | set(custom_white_list or ()),
            "black": set(black_list) | set(custom_black_list or ()),
        }

    def __enter__(self):
        _state.stack.append(self.cfg)
        return self

    def __exit__(self, *exc):
        _state.stack.pop()
        return False


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """``paddle.amp.decorate``: O2 casts model params to the AMP dtype; master
    weights (f32) live in the optimizer (mirrors reference master-weight path)."""
    from ..nn.layers import Layer

    target = dtype_mod.convert_dtype(dtype)
    single = isinstance(models, Layer)
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            for p in m.parameters():
                if p.dtype == dtype_mod.float32:
                    p._value = p._value.astype(target)
    if optimizers is None:
        return models if single else model_list
    opt_single = not isinstance(optimizers, (list, tuple))
    opt_list = [optimizers] if opt_single else list(optimizers)
    for o in opt_list:
        o._use_master_weights = master_weight if master_weight is not None else (level == "O2")
    return (models if single else model_list), (optimizers if opt_single else opt_list)
