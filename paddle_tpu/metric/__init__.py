"""``paddle.metric`` (Accuracy/Precision/Recall/Auc — SURVEY.md §5 metrics)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, to_tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        p = (pred._host_read() if isinstance(pred, Tensor) else np.asarray(pred))
        l = (label._host_read() if isinstance(label, Tensor) else np.asarray(label))
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l.squeeze(-1)
        idx = np.argsort(-p, axis=-1)[..., : self.maxk]
        correct = idx == l[..., None]
        return to_tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = (correct._host_read() if isinstance(correct, Tensor) else np.asarray(correct))
        for i, k in enumerate(self.topk):
            hit = c[..., :k].sum(-1).mean()
            self.total[i] += float(c[..., :k].sum())
            self.count[i] += int(np.prod(c.shape[:-1]))
        accs = [self.total[i] / max(self.count[i], 1) for i in range(len(self.topk))]
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        accs = [self.total[i] / max(self.count[i], 1) for i in range(len(self.topk))]
        return accs[0] if len(accs) == 1 else accs

    def name(self):
        return [f"{self._name}_top{k}" if k > 1 else self._name for k in self.topk] \
            if len(self.topk) > 1 else [self._name]


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (preds._host_read() if isinstance(preds, Tensor) else np.asarray(preds))
        l = (labels._host_read() if isinstance(labels, Tensor) else np.asarray(labels))
        pred_pos = (p > 0.5).astype(np.int64).reshape(-1)
        l = l.reshape(-1)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fp += int(((pred_pos == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (preds._host_read() if isinstance(preds, Tensor) else np.asarray(preds))
        l = (labels._host_read() if isinstance(labels, Tensor) else np.asarray(labels))
        pred_pos = (p > 0.5).astype(np.int64).reshape(-1)
        l = l.reshape(-1)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fn += int(((pred_pos == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = (preds._host_read() if isinstance(preds, Tensor) else np.asarray(preds))
        l = (labels._host_read() if isinstance(labels, Tensor) else np.asarray(labels)).reshape(-1)
        if p.ndim == 2:
            p = p[:, 1]
        bins = np.clip((p * self.num_thresholds).astype(int), 0, self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            area += self._stat_pos[i] * (neg + self._stat_neg[i] / 2)
            pos += self._stat_pos[i]
            neg += self._stat_neg[i]
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    p = input._host_read()
    l = label._host_read()
    if l.ndim == 2 and l.shape[-1] == 1:
        l = l.squeeze(-1)
    idx = np.argsort(-p, axis=-1)[..., :k]
    hit = (idx == l[..., None]).any(-1).mean()
    return to_tensor(np.float32(hit))
