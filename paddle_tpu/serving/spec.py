"""Speculative decoding — self-speculative n-gram draft/verify (ISSUE 18).

Decode was one token per model step.  This module drafts k candidate
tokens per decode-resident request on the host (zero-dependency n-gram
proposer — a real draft model slots in behind the same interface later)
and the engine packs them as a short **verify chunk**
``[last_token, d1..dk]`` into the unified ragged program (PR 10): a
verify row IS a prefill-chunk-shaped row of already-chosen tokens, per
Ragged Paged Attention (PAPERS.md #1), so there is **no new program
family and no new bucket axis** — the packed token count stays inside
the same ``max(max_tokens_per_step, decode rows)`` bucket bound, and an
AOT artifact saved for the plain engine serves the spec engine with
zero retraces.

Verification is **exact-match against the in-trace sampler's targets**
(``ops/sampling.py``): position j of a verify row yields target token
T_j — the token the plain one-token-per-step path would have sampled at
that output position, because the logits prefix AND the
``(seed, draw_index)`` key are identical.  The longest
``d_{j+1} == T_j`` prefix is accepted, tokens ``T_0..T_a`` all emit in
ONE engine step, and the KV slots past the last consumed position roll
back via :meth:`~paddle_tpu.serving.kv_manager.KVCacheManager.truncate`
(the preemption-recompute slot discipline, aimed at a length).  Hence
the crisp contract the bench gates: spec-on is **token-identical** to
spec-off (greedy and seeded sampling alike) with **strictly fewer
engine steps** on a decode-heavy stream.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Optional

# pre-registered on the engine's registry by :class:`SpecDecoder` so the
# series exist from the first scrape (documented in README's metrics
# table; check_metrics_docs pins this module):
METRIC_NAMES = (
    "serving_spec_draft_tokens_total",     # drafts packed into verify rows
    "serving_spec_accepted_tokens_total",  # drafts that matched their target
    "serving_spec_verify_rows_total",      # decode rows upgraded to verify
    "serving_spec_accept_ratio",           # accepted/drafted, cumulative
    "serving_spec_accept_length",          # accepted-run length per verify row
)

# accepted-run length buckets: k is small (draft budget), so unit bins
_ACCEPT_BUCKETS = (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0)


@dataclass
class SpecConfig:
    """Speculative-decoding knobs (``EngineConfig.spec``)."""

    enabled: bool = True
    k: int = 4           # max draft tokens per request per step
    ngram: int = 3       # longest suffix n-gram the proposer matches
    min_ngram: int = 1   # shortest match worth proposing from
    window: int = 256    # proposer lookback cap (host-cost bound): only
                         # the most recent ``window`` context tokens are
                         # scanned for a match

    def __post_init__(self):
        if self.k < 0:
            raise ValueError(f"SpecConfig.k must be >= 0, got {self.k}")
        if self.min_ngram < 1 or self.ngram < self.min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= ngram, got min_ngram="
                f"{self.min_ngram}, ngram={self.ngram}")
        if self.window < self.ngram + 1:
            raise ValueError(
                f"SpecConfig.window={self.window} cannot cover an "
                f"ngram={self.ngram} match plus a draft token")

    def manifest_dict(self) -> Dict[str, int]:
        """The wire/manifest identity of this config (ISSUE 18 fleet
        satellite): workers hash it into their handshake so replicas
        running different spec deployments refuse each other."""
        return {"enabled": bool(self.enabled), "k": int(self.k),
                "ngram": int(self.ngram),
                "min_ngram": int(self.min_ngram),
                "window": int(self.window)}

    def manifest_json(self) -> str:
        return json.dumps(self.manifest_dict(), sort_keys=True)


class NgramProposer:
    """Draft proposer with zero model cost: find the most recent earlier
    occurrence of the context's longest suffix n-gram and propose the
    tokens that followed it.  Stateless — every call re-derives from the
    context, so preemption/recompute cannot desynchronize it.  Returns
    ``[]`` whenever there is nothing defensible to propose (no match,
    ``k == 0``, context too short) — the row stays a plain decode row.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 window: int = 256):
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self.window = int(window)

    def propose(self, context: List[int], k: int) -> List[int]:
        if k <= 0:
            return []
        ctx = [int(t) for t in context[-self.window:]]
        n = len(ctx)
        for m in range(min(self.max_ngram, n - 1), self.min_ngram - 1, -1):
            suffix = ctx[n - m:]
            # most recent earlier occurrence whose continuation exists
            for i in range(n - m - 1, -1, -1):
                if ctx[i:i + m] == suffix:
                    follow = ctx[i + m:i + m + k]
                    if follow:
                        return follow
        return []


class SpecDecoder:
    """Per-engine speculative-decode driver: proposes drafts inside the
    scheduler's leftover token budget, upgrades decode rows to verify
    rows (allocating their draft KV slots), and owns the accept-ratio /
    accept-length telemetry.  The engine does the packing, emission and
    rollback — this object never touches device state."""

    def __init__(self, config: SpecConfig, registry=None,
                 labels: Optional[Dict[str, str]] = None):
        self.config = config
        self.proposer = NgramProposer(config.ngram, config.min_ngram,
                                      config.window)
        self.drafted_total = 0
        self.accepted_total = 0
        lb = labels or {}
        self._m_drafted = self._m_accepted = None
        self._m_rows = self._m_ratio = self._m_len = None
        if registry is not None:
            self._m_drafted = registry.counter(
                "serving_spec_draft_tokens_total",
                help="draft tokens packed into verify rows", **lb)
            self._m_accepted = registry.counter(
                "serving_spec_accepted_tokens_total",
                help="draft tokens that matched their sampled target", **lb)
            self._m_rows = registry.counter(
                "serving_spec_verify_rows_total",
                help="decode rows upgraded to draft/verify rows", **lb)
            self._m_ratio = registry.gauge(
                "serving_spec_accept_ratio",
                help="cumulative accepted/drafted draft-token ratio", **lb)
            self._m_len = registry.histogram(
                "serving_spec_accept_length",
                help="accepted-run length per verify row (in draft tokens)",
                buckets=_ACCEPT_BUCKETS, **lb)

    # --- planning (engine's _unified_exec, pre-launch) ----------------------
    def plan_drafts(self, kv, rows: List[Dict], budget: int) -> int:
        """Upgrade decode rows to verify rows in-place, spending at most
        ``budget`` draft tokens.  Per row: propose up to k drafts from
        the request's full context, allocate the draft KV slots
        all-or-nothing (`spec_draft` cause), and rewrite the row as the
        ``[last_token, d1..dk]`` chunk.  A row with no proposal, no
        remaining length headroom, or no allocatable slots stays a plain
        decode row.  Returns the number of draft tokens packed."""
        packed = 0
        for row in rows:
            if row["kind"] != "decode":
                continue
            left = budget - packed
            if left <= 0:
                break
            req = row["req"]
            # never draft past the request's own length budget: the step
            # emits at least one token, so only max_new - out - 1 more
            # CAN be consumed — also keeps the verify row's kv length
            # strictly inside the plain path's max_seq_len (AOT cap)
            headroom = (req.sampling.max_new_tokens
                        - len(req.output_tokens) - 1)
            k = min(self.config.k, left, headroom)
            if k <= 0:
                continue
            drafts = self.proposer.propose(
                req.prompt_ids + req.output_tokens, k)
            if not drafts:
                continue
            # +1 covers the decode slot's own position already held; the
            # extra blocks cover positions p+1..p+k (all-or-nothing)
            if not kv.allocate(req.request_id, 1 + len(drafts),
                               cause="spec_draft"):
                continue  # pool pressure: plain decode, not an error
            row["kind"] = "verify"
            row["drafts"] = [int(d) for d in drafts]
            row["tokens"] = [req.last_token] + row["drafts"]
            row["n"] = 1 + len(drafts)
            packed += len(drafts)
            self.drafted_total += len(drafts)
            if self._m_drafted is not None:
                self._m_drafted.inc(len(drafts))
                self._m_rows.inc()
        return packed

    # --- accounting (engine's _unified_exec, post-launch) -------------------
    def record(self, drafted: int, accepted: int) -> None:
        self.accepted_total += accepted
        if self._m_accepted is not None:
            self._m_accepted.inc(accepted)
            self._m_len.observe(accepted)
            if self.drafted_total:
                self._m_ratio.set(self.accepted_total
                                  / self.drafted_total)

    @property
    def accept_ratio(self) -> float:
        return (self.accepted_total / self.drafted_total
                if self.drafted_total else 0.0)
