"""Cross-process serving fleet (ISSUE 16 tentpole (c) + (d)).

The in-process fleet's router and supervisor (PRs 6/12) already contain
the hard parts of a serving control plane — prefix-affinity routing,
atomic handle-ownership triage, backoff/quarantine healing, exactly-once
chaos bookkeeping.  This module makes them run over PROCESS-isolated
replicas **without forking any of that logic**: the factory handed to
:meth:`FleetRouter.build` returns a :class:`WorkerEngineProxy` that
presents the exact ``EngineCore`` surface the router, the supervisor,
and the stock :class:`~paddle_tpu.serving.fleet.EngineReplica` loop
drive — but every call crosses the wire (``serving/wire.py``) to a
``python -m paddle_tpu.serving.worker`` process.

The translation table:

============================  =========================================
in-process mechanism           cross-process equivalent
============================  =========================================
engine construction            worker process spawn (``--aot-path``
                               boots zero-trace off the SHARED artifact)
``engine_step_raise``          worker reports ``step_error`` and exits;
                               ``kill -9`` produces the same death shape
thread-liveness                heartbeat timeout on the control
                               connection (``scheduler.has_work()``
                               raises :class:`WorkerDied` once marked)
shared-registry metrics        per-step worker registry dump, merged
                               under the existing ``replica="i"`` labels
supervisor ``_rebuild``        same code path: the factory closes the
                               old proxy (killing its process) and
                               spawns a replacement worker
============================  =========================================

Because the supervisor's triage/rebuild state machine is untouched, the
PR 11 chaos contract transfers: ``kill -9`` a worker mid-stream →
reroute, respawn onto the shared artifact, zero lost requests, greedy
token identity, exactly one ``engine_death`` flight bundle.

Tentpole (d), the actuator layer the ROADMAP names: the signals
(``serving_fleet_cache_imbalance``, PR 12) and the rule engine (PR 13)
were DONE — this module adds what acts on them.
:class:`FleetAutoscaler` maps AlertEngine rule firings (goodput burn,
pool exhaustion, restart churn) to bounded scale-up/drain actions on the
process pool via a pure, replay-deterministic :class:`ScaleDecider`;
:class:`CacheRebalancer` turns the imbalance gauge into consistent-hash
vnode re-weighting (:meth:`FleetRouter.reweight_ring`).
"""

from __future__ import annotations

import json
import os
import queue
import socket
import subprocess
import sys
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass, replace as _dc_replace
from typing import Dict, List, Optional, Tuple

from ..observability import distrib
from ..observability import lifecycle as _lc
from ..observability.audit import AuditConfig
from ..observability.metrics import MetricsRegistry
from . import wire
from .engine import EngineConfig
from .fleet import EngineReplica, FleetConfig, FleetRouter, _key_int
from .metrics import ServingMetrics
from .request import FinishReason, SamplingParams
from .resilience import FleetSupervisor, SupervisorConfig
from .wire import CACHE_PREFIX, READY_PREFIX

# metric names this module owns (tools/check_metrics_docs lints that
# each appears in README's metrics table)
METRIC_NAMES = (
    "serving_fleet_scale_events_total",
    "serving_fleet_worker_respawns_total",
    "serving_fleet_heartbeat_timeouts_total",
    "serving_fleet_ring_reweights_total",
    "serving_fleet_prefix_migrations_total",
    "serving_fleet_active_workers",
)

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


class WorkerDied(RuntimeError):
    """The replica's worker process is gone (socket death, heartbeat
    timeout, reported step failure, or kill -9).  Raised into the stock
    ``EngineReplica`` loop so the EXISTING death path runs: flight
    bundle, supervisor triage, re-dispatch, respawn."""


class _MirrorRequest:
    """Router-side mirror of one in-flight request on a worker: the
    object :meth:`WorkerEngineProxy.add_request` returns, presenting the
    fields the replica loop, the supervisor's triage
    (``req.output_tokens`` emptiness = re-dispatchable) and the HTTP
    handle surface read.  Token frames append to ``output_tokens``;
    ``step_done``'s finished map closes it."""

    __slots__ = ("request_id", "prompt_ids", "output_tokens", "finished",
                 "finish_reason", "first_token_time", "arrival_time")

    def __init__(self, request_id, prompt_ids: List[int]):
        self.request_id = request_id
        self.prompt_ids = list(prompt_ids)
        self.output_tokens: List[int] = []
        self.finished = False
        self.finish_reason: Optional[FinishReason] = None
        # first-token boundary marker (ISSUE 20): the router's
        # prefill→decode migration sweep triggers on this going
        # non-None, exactly like the in-process Request field
        self.first_token_time: Optional[float] = None
        self.arrival_time: float = time.perf_counter()


class AotManifestHandle:
    """Manifest-only stand-in for a loaded AOT artifact, shared by every
    proxy.  The router process never deserializes the programs (only the
    workers execute them); it needs just (a) ONE object identity so the
    fleet's same-artifact gate holds across proxies, and (b) the
    ``model_hash`` the wire handshake pins — a router and a worker
    booted off different artifacts refuse each other at connect time."""

    def __init__(self, path: str, manifest: Dict):
        self.path = path
        self.manifest = manifest
        self.load_seconds = 0.0

    @classmethod
    def load(cls, path: str) -> "AotManifestHandle":
        with open(os.path.join(path, "manifest.json")) as f:
            return cls(path, json.load(f))

    @property
    def model_hash(self) -> str:
        return self.manifest["model_hash"]

    @property
    def program_count(self) -> int:
        return len(self.manifest.get("programs", []))

    def mark_load_observed(self, registry) -> bool:
        return False  # no disk load happened router-side

    def describe(self) -> Dict:
        m = self.manifest
        return {
            "path": self.path, "programs": self.program_count,
            "mp": m.get("mp"), "dtype": m.get("dtype"),
            "num_blocks": m.get("num_blocks"),
            "block_size": m.get("block_size"),
            "max_seq_len": m.get("max_seq_len"),
            "model_hash": str(m.get("model_hash", ""))[:16],
            "jax_version": m.get("jax_version"),
            "load_seconds": 0.0,
        }


@dataclass
class ProcessFleetConfig:
    """Knobs for a process-isolated fleet.  Engine-shape fields mirror
    the toy-engine factory in ``serving/server.py`` — the SAME spec is
    sent to every worker (``--spec``) and templates the proxies' gate
    attributes, so the router's homogeneity gates hold by construction."""

    dp: int = 2
    layers: int = 2
    num_blocks: int = 64
    block_size: int = 4
    max_num_seqs: int = 4
    max_prefill_tokens_per_step: Optional[int] = 8
    max_tokens_per_step: Optional[int] = None
    unified: bool = False
    # multi-chip workers (ISSUE 18 fleet satellite): each worker process
    # builds an mp-way mesh before its engine (on CPU the spawn injects
    # XLA_FLAGS=--xla_force_host_platform_device_count so the child sees
    # enough devices); the mp degree rides the wire handshake as part of
    # the deployment identity — a drifted worker answers deploy_mismatch
    mp: int = 1
    # speculative decoding (ISSUE 18): JSON-able SpecConfig kwargs dict
    # forwarded to every worker (requires unified + max_tokens_per_step);
    # its manifest_dict() also rides the handshake deployment identity
    spec: Optional[Dict] = None
    # device-resident decode bursts (ISSUE 19): forwarded to every
    # worker engine; the step_done emission batch already carries
    # multi-token rows, so a burst costs one wire round-trip
    burst_steps: int = 0
    # prefill/decode disaggregation (ISSUE 20): per-index replica roles
    # (length dp, e.g. ["prefill", "decode"] or serving.fleet.parse_roles
    # output).  None = every worker unified.  Each worker's role rides
    # its --spec AND its handshake deployment identity, so a drifted
    # worker answers deploy_mismatch at connect time.
    roles: Optional[List[str]] = None
    audit_enabled: bool = False
    audit_sample_every: int = 1
    seed: int = 0
    aot_path: Optional[str] = None     # shared artifact every worker
                                       # boots from (zero-trace, PR 14)
    compile_cache: Optional[str] = None  # JAX persistent compilation
    # cache dir: N sibling workers compile each program once machine-wide
    warm_boot: bool = False            # workers execute every AOT
    # program once at boot (first request wave pays zero lazy compiles)
    heartbeat_interval_s: float = 0.25
    heartbeat_timeout_s: float = 2.0   # silent control conn -> dead
    boot_timeout_s: float = 180.0
    # ISSUE 17 cross-process tracing: workers run their engines with
    # lifecycle events ON and stream sequence-numbered deltas back; the
    # router merges them into its ONE tracker and mirrors them per
    # worker so a kill -9 post-mortem still has the engine's last events
    telemetry: bool = True
    decode_event_sample: int = 8       # forwarded to the worker engine
    mirror_ring_events: int = 512      # host-side per-worker mirror
    stderr_tail_lines: int = 100       # per-worker stderr tail ring
    clock_window: int = 64             # NTP-style min-RTT filter window
    python: str = sys.executable
    fleet: Optional[FleetConfig] = None  # router knobs (fault plan,
                                         # alert rules, flight dir, ...)


class WorkerHandle:
    """One spawned worker process: ready-line parse, log pump, teardown.

    The worker prints ``PADDLE_TPU_WORKER_READY port=...`` once
    listening; everything before it is boot logging (captured — the
    compile-cache line in particular is how the cross-process
    compile-reuse satellite observes a sibling's cache hits)."""

    def __init__(self, proc: subprocess.Popen, index: int,
                 stderr_tail_lines: int = 100):
        self.proc = proc
        self.index = index
        self.pid = proc.pid
        self.port: Optional[int] = None
        self.aot_hash: Optional[str] = None
        self.boot_s = 0.0
        self.compile_cache: Optional[Dict] = None  # parsed cache line
        self.log_tail: deque = deque(maxlen=200)
        # bounded stderr tail (ISSUE 17 satellite): a worker that dies
        # in C++/XLA land leaves its last words HERE — the engine_death
        # / crash_loop flight bundles embed this ring
        self.stderr_tail: deque = deque(
            maxlen=max(10, int(stderr_tail_lines)))
        self._pump: Optional[threading.Thread] = None
        self._pump_err: Optional[threading.Thread] = None

    @classmethod
    def spawn(cls, cfg: ProcessFleetConfig, index: int,
              spec: Dict) -> "WorkerHandle":
        cmd = [cfg.python, "-m", "paddle_tpu.serving.worker",
               "--replica", str(index), "--spec", json.dumps(spec)]
        if cfg.aot_path:
            cmd += ["--aot-path", cfg.aot_path]
        if cfg.compile_cache:
            cmd += ["--compile-cache", cfg.compile_cache]
        if cfg.warm_boot:
            cmd += ["--warm"]
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH")
            else "")
        if cfg.mp > 1 and "--xla_force_host_platform_device_count" \
                not in env.get("XLA_FLAGS", ""):
            # mp>1 on the forced-host-device CPU backend: the CHILD
            # process must see >= mp devices before jax initializes —
            # injecting here (not in the worker) keeps the worker module
            # backend-agnostic.  Real TPU workers already have their
            # chips; the guard leaves an operator's explicit flag alone.
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                " --xla_force_host_platform_device_count"
                                f"={cfg.mp}").strip()
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True,
                                env=env)
        h = cls(proc, index, stderr_tail_lines=cfg.stderr_tail_lines)
        # stderr pump starts BEFORE the ready-line wait: JAX boot
        # warnings can fill the stderr pipe and deadlock a worker that
        # never reaches its ready line if nobody drains it
        h._pump_err = threading.Thread(target=h._pump_stderr,
                                       daemon=True,
                                       name=f"worker-stderr-{index}")
        h._pump_err.start()
        # readline has no timeout: a watchdog timer kills a hung boot so
        # the read loop sees EOF instead of blocking forever
        killer = threading.Timer(cfg.boot_timeout_s, h._boot_timeout)
        killer.daemon = True
        killer.start()
        try:
            for line in proc.stdout:
                line = line.rstrip("\n")
                h.log_tail.append(line)
                if line.startswith(CACHE_PREFIX):
                    kv = dict(p.split("=", 1) for p in line.split()[1:])
                    h.compile_cache = {
                        "dir": kv.get("dir"),
                        "entries_before": int(kv.get("entries_before", 0)),
                        "entries_after": int(kv.get("entries_after", 0)),
                    }
                elif line.startswith(READY_PREFIX):
                    kv = dict(p.split("=", 1) for p in line.split()[1:])
                    h.port = int(kv["port"])
                    h.aot_hash = (None if kv.get("aot_hash") in
                                  (None, "None") else kv["aot_hash"])
                    h.boot_s = float(kv.get("boot_s", 0.0))
                    break
        finally:
            killer.cancel()
        if h.port is None:
            h.stop(grace_s=0.5)
            tail = "\n".join(list(h.log_tail) + list(h.stderr_tail))
            raise WorkerDied(
                f"worker {index} (pid {h.pid}) exited/hung before its "
                f"ready line; log tail:\n{tail}")
        h._pump = threading.Thread(target=h._pump_output, daemon=True,
                                   name=f"worker-log-{index}")
        h._pump.start()
        return h

    def _boot_timeout(self) -> None:
        try:
            self.proc.kill()
        except OSError:
            pass  # swallow-ok: the worker already exited; the read loop sees EOF either way

    def _pump_output(self) -> None:
        try:
            for line in self.proc.stdout:
                self.log_tail.append(line.rstrip("\n"))
        except (OSError, ValueError):
            pass  # swallow-ok: stdout closed during teardown; the tail captured what there was
        finally:
            try:
                self.proc.stdout.close()
            except OSError:
                pass  # swallow-ok: double-close during teardown

    def _pump_stderr(self) -> None:
        try:
            for line in self.proc.stderr:
                self.stderr_tail.append(line.rstrip("\n"))
        except (OSError, ValueError):
            pass  # swallow-ok: stderr closed during teardown; the tail captured what there was
        finally:
            try:
                self.proc.stderr.close()
            except OSError:
                pass  # swallow-ok: double-close during teardown

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def stop(self, grace_s: float = 2.0) -> None:
        """Terminate (SIGTERM, then SIGKILL past the grace)."""
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(grace_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(10)
        if self._pump is not None:
            self._pump.join(1.0)
        if self._pump_err is not None:
            self._pump_err.join(1.0)


class _SchedulerProxy:
    """The two members the replica loop and the fleet gauges read.
    ``has_work`` doubles as the death surface: the replica loop polls it
    every ≤20 ms, so raising here once the heartbeat marks the worker
    dead routes an IDLE worker's death through the standard
    engine-thread death path within one poll interval."""

    def __init__(self, proxy: "WorkerEngineProxy"):
        self._p = proxy

    def has_work(self) -> bool:
        p = self._p
        if p._closed:
            return False  # orderly teardown: let the loop drain out
        if p._dead.is_set():
            raise WorkerDied(
                f"worker {p.index} (pid {p.pid}) is dead: "
                f"{p._death_detail}")
        return p._has_work

    @property
    def queue_depth(self) -> int:
        return self._p._queue_depth


class _KvProxy:
    def __init__(self, proxy: "WorkerEngineProxy"):
        self._p = proxy
        self.num_blocks = proxy.num_blocks

    def occupancy(self) -> float:
        # cached from the last step reply: registry collect hooks call
        # this and must NEVER block on the wire
        return self._p._occupancy


class _AuditProxy:
    """Mirrors the ``NumericsAuditor`` surface the router/supervisor/
    HTTP layers read.  ``cfg`` is the fleet-shared template (the
    router's same-config gate compares these by value); ``degraded`` is
    cached from step replies so the supervisor's quarantine scan stays
    wire-free; ``snapshot`` fetches live detail over the control
    connection."""

    def __init__(self, proxy: "WorkerEngineProxy", cfg: AuditConfig):
        self._p = proxy
        self.cfg = cfg
        self.enabled = bool(getattr(cfg, "enabled", False))
        self._flight = None
        self._flight_replica: Optional[str] = None

    @property
    def degraded(self) -> bool:
        return self._p._degraded

    @property
    def status(self) -> str:
        return "degraded" if self.degraded else "ok"

    def snapshot(self) -> Dict:
        data = self._p.debug_fetch("audit")
        if not isinstance(data, dict):
            return {"enabled": self.enabled, "status": "restarting"}
        return data

    def bind_flight(self, recorder, replica: Optional[str] = None) -> None:
        # divergence .npz repros live worker-side; the binding is kept
        # so the fleet wiring sequence is identical either way
        self._flight = recorder
        self._flight_replica = replica


class _StepProfProxy:
    def __init__(self, proxy: "WorkerEngineProxy"):
        self._p = proxy
        self.enabled = bool(proxy.engine_config.step_profile)
        self.max_capture_steps = 512  # advertised bound; arm refuses

    def records(self) -> List[Dict]:
        data = self._p.debug_fetch("records", [])
        return data if isinstance(data, list) else []

    def compile_table(self) -> List[Dict]:
        data = self._p.debug_fetch("compile_table", [])
        return data if isinstance(data, list) else []

    def compile_totals(self) -> Dict:
        data = self._p.debug_fetch("compile_totals", {})
        return data if isinstance(data, dict) else {}

    def aot_snapshot(self) -> Dict:
        data = self._p.debug_fetch("aot", {})
        return data if isinstance(data, dict) else {}

    def arm_capture(self, steps: int):
        # RuntimeError -> HTTP 400 on /v1/debug/profile: a capture
        # window needs the in-process profiler object
        raise RuntimeError(
            "step capture is not available over the process wire "
            "(replica runs out-of-process); use an in-process fleet "
            "(--dp without --workers) to capture traces")

    def cancel_capture(self) -> None:
        return None


class _CacheStatProxy:
    def __init__(self, proxy: "WorkerEngineProxy"):
        self._p = proxy
        self.enabled = bool(proxy.engine_config.cache_stats)

    def snapshot(self) -> Dict:
        data = self._p.debug_fetch("cache")
        if not isinstance(data, dict):
            return {"enabled": self.enabled, "status": "restarting"}
        return data

    def timeline(self) -> List[Dict]:
        data = self._p.debug_fetch("cache_timeline", [])
        return data if isinstance(data, list) else []


class WorkerEngineProxy:
    """The ``EngineCore`` surface, served by a worker process.

    The stock :class:`~paddle_tpu.serving.fleet.EngineReplica` thread
    drives ``add_request``/``abort_request``/``step``/``requests`` over
    the dedicated *engine* connection (strictly serial — it is the only
    user).  Heartbeats and HTTP debug handlers share the *control*
    connection under a lock.  State the router reads on hot/collect
    paths (``has_work``, ``queue_depth``, ``occupancy``, ``degraded``,
    ``step_seq``) is cached from step replies — never fetched.

    Metrics: ``metrics`` is a REAL :class:`ServingMetrics` on the shared
    router registry under ``replica=str(index)`` labels (pre-registering
    the full series family exactly like an in-process replica, which is
    also what satisfies the router's distinct-labels gate).  Each
    ``step_done`` carries the worker's full registry dump; a
    :class:`~paddle_tpu.serving.wire.RegistryMerger` folds the
    replica-labeled rows in delta-monotonically, so counters survive
    worker respawns without regressing."""

    def __init__(self, shared: "_SharedState", index: int,
                 live: bool = True):
        self._shared = shared
        cfg = shared.cfg
        self.index = index
        # --- fleet-gate surface (shared template objects) -------------------
        self.engine_config = shared.engine_cfg_for(index)
        self.block_size = cfg.block_size
        self.num_blocks = cfg.num_blocks
        self.mp = int(cfg.mp)
        self.metrics = ServingMetrics(registry=shared.registry,
                                      labels={"replica": str(index)})
        # host-side span tracer: the HTTP frontend wraps every request
        # in `engine.tracer.span(...)` — those are frontend spans, so
        # the proxy serves the host process tracer (the worker keeps
        # its own engine tracer in-process)
        self.tracer = self.metrics.tracer
        self.audit = _AuditProxy(self, shared.template_audit)
        self.aot_artifact = shared.aot_handle
        self.stepprof = _StepProfProxy(self)
        self.cachestat = _CacheStatProxy(self)
        self.kv = _KvProxy(self)
        self.scheduler = _SchedulerProxy(self)
        self.requests: Dict[object, _MirrorRequest] = {}  # rid ->
        # mirror; bounded by the replica admission cap, evicted on finish
        self.lifecycle = None
        self._replica_label = str(index)
        self._history = None
        self._router_fi = None
        # --- cached worker state (updated from step replies) ----------------
        self.step_seq = 0
        self._has_work = False
        self._queue_depth = 0
        self._occupancy = 0.0
        self._degraded = False
        # --- process/wire state ---------------------------------------------
        self.worker: Optional[WorkerHandle] = None
        self.is_live = False     # a process was spawned (vs parked)
        self._engine_conn: Optional[wire.Connection] = None
        self._control_conn: Optional[wire.Connection] = None
        self._control_lock = threading.RLock()
        self._dead = threading.Event()
        self._death_detail = ""
        self._closed = False
        self._merger: Optional[wire.RegistryMerger] = None
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_fail_c = shared.registry.counter(
            "serving_fleet_heartbeat_timeouts_total",
            "worker heartbeats that failed/timed out, marking the "
            "replica dead", replica=str(index))
        # --- cross-process telemetry (ISSUE 17) -----------------------------
        self._telemetry = bool(cfg.telemetry)
        self.clock = distrib.ClockSync(window=cfg.clock_window)
        self.mirror = distrib.MirrorRing(capacity=cfg.mirror_ring_events)
        self.wire_stats = distrib.WireStats(
            registry=shared.registry, labels={"replica": str(index)})
        # summary() prints this replica's host/wire/engine share table
        self.metrics.attach_wire_stats(self.wire_stats)
        self._delta: Optional[distrib.DeltaMerger] = None  # per spawn
        self._dropped_seen = 0
        self._c_streamed = shared.registry.counter(
            "serving_distrib_events_streamed_total",
            "worker lifecycle events streamed over the wire and merged "
            "into the router tracker", replica=str(index))
        self._c_dropped = shared.registry.counter(
            "serving_distrib_events_dropped_total",
            "telemetry events dropped (worker outbox or host mirror "
            "ring full)", replica=str(index))
        self._g_clock_off = shared.registry.gauge(
            "serving_distrib_clock_offset_seconds",
            "estimated worker-minus-router monotonic clock offset "
            "(min-RTT NTP sample)", replica=str(index))
        self._g_clock_rtt = shared.registry.gauge(
            "serving_distrib_clock_rtt_seconds",
            "round-trip time of the best clock-sync sample",
            replica=str(index))
        if live:
            self.spawn()

    @property
    def pid(self) -> Optional[int]:
        return self.worker.pid if self.worker is not None else None

    # --- process lifecycle --------------------------------------------------
    def spawn(self) -> None:
        shared = self._shared
        cfg = shared.cfg
        expect = (shared.aot_handle.model_hash
                  if shared.aot_handle is not None else None)
        self.worker = WorkerHandle.spawn(cfg, self.index,
                                         shared.worker_spec(self.index))
        if self.worker.aot_hash != expect:
            got = self.worker.aot_hash
            self.worker.stop(grace_s=0.5)
            raise WorkerDied(
                f"worker {self.index} booted artifact hash {got!r} but "
                f"the fleet shares {expect!r} — artifact drift between "
                "router and worker")
        labels = {"replica": str(self.index)}
        deploy = shared.deploy(self.index)
        self._engine_conn = wire.connect(
            "127.0.0.1", self.worker.port, role="engine",
            aot_hash=expect, registry=shared.registry, labels=labels,
            side="router", deploy=deploy)
        self._control_conn = wire.connect(
            "127.0.0.1", self.worker.port, role="control",
            aot_hash=expect, registry=shared.registry, labels=labels,
            side="router", deploy=deploy)
        # fresh merger per incarnation: its delta baselines reset with
        # the new worker's (zeroed) counters, so shared-registry totals
        # only ever move forward across respawns
        self._merger = wire.RegistryMerger(shared.registry,
                                           str(self.index))
        # fresh delta merger per incarnation: the new worker's outbox
        # restarts its sequence numbers at 0, so the applied-seq
        # intervals must reset with it (idempotency is per incarnation).
        # The lifecycle is read through a getter because the router
        # calls set_lifecycle AFTER the factory returns.
        self._delta = distrib.DeltaMerger(
            str(self.index), self.worker.pid, self.clock, self.mirror,
            lambda: self.lifecycle)
        self.is_live = True
        if self._router_fi is not None:
            self._send_fault_plan()
        self._hb_thread = threading.Thread(
            target=self._hb_loop, daemon=True,
            name=f"worker-heartbeat-{self.index}")
        self._hb_thread.start()

    def close(self, graceful: bool = True) -> None:
        """Tear the worker down.  Idempotent; never raises."""
        if self._closed:
            return
        self._closed = True
        self._dead.set()  # stops the heartbeat; has_work answers False
        if graceful and self._control_conn is not None \
                and self.worker is not None and self.worker.alive:
            try:
                with self._control_lock:
                    self._control_conn.settimeout(2.0)
                    self._control_conn.request({"type": "shutdown"})
            except (socket.timeout, OSError, wire.WireError):
                pass  # swallow-ok: best-effort graceful stop; SIGTERM/SIGKILL below is the guarantee
        for conn in (self._engine_conn, self._control_conn):
            if conn is not None:
                conn.close()
        if self.worker is not None:
            self.worker.stop()

    def _mark_dead(self, detail: str) -> None:
        if self._dead.is_set():
            return
        self._death_detail = detail
        self._dead.set()
        self._shared.update_gauge()

    def _hb_loop(self) -> None:
        cfg = self._shared.cfg
        conn = self._control_conn
        while not self._dead.is_set() and not self._closed:
            try:
                t0 = time.perf_counter()
                with self._control_lock:
                    conn.settimeout(cfg.heartbeat_timeout_s)
                    reply = conn.request({"type": "health", "t0": t0})
                t3 = time.perf_counter()
                if reply.get("type") != "health_ok":
                    raise WorkerDied(f"bad health reply: {reply!r}")
                # each heartbeat doubles as an NTP-style clock probe
                # (t0/t3 router clock, t1/t2 echoed worker clock)
                t1, t2 = reply.get("t1"), reply.get("t2")
                if reply.get("t0") == t0 and t1 is not None \
                        and t2 is not None:
                    self.clock.observe(t0, float(t1), float(t2), t3)
                    self._g_clock_off.set(self.clock.offset)
                    self._g_clock_rtt.set(self.clock.rtt)
                self._absorb_telemetry(reply)
            except (socket.timeout, wire.WireError, WorkerDied,
                    OSError) as e:
                if self._closed or self._dead.is_set():
                    return
                self._hb_fail_c.inc()
                self._mark_dead(
                    f"heartbeat failed after "
                    f"{cfg.heartbeat_timeout_s}s: {e}")
                return
            self._dead.wait(cfg.heartbeat_interval_s)

    def _require_live(self) -> None:
        if self._dead.is_set() or self._engine_conn is None:
            raise WorkerDied(
                f"worker {self.index} is not serving "
                f"({self._death_detail or 'never spawned (parked)'})")

    # --- EngineCore surface: wiring hooks -----------------------------------
    def set_lifecycle(self, tracker, replica: Optional[str] = None) -> None:
        self.lifecycle = tracker
        if replica is not None:
            self._replica_label = str(replica)

    def _lc(self, rid, name: str, **attrs) -> None:
        if self.lifecycle is None \
                or not self.engine_config.lifecycle_events:
            return
        if self._telemetry:
            # telemetry streaming replaces the router-synthesized
            # enqueued/finish stand-ins with the worker engine's REAL
            # events (correct engine-side timestamps, full attrs)
            return
        self.lifecycle.event(rid, name, replica=self._replica_label,
                             **attrs)

    def set_history(self, history) -> None:
        if self.engine_config.history:
            self._history = history

    def set_fault_injector(self, injector) -> None:
        self._router_fi = injector
        if self.is_live and not self._dead.is_set():
            self._send_fault_plan()

    def _send_fault_plan(self) -> None:
        fi = self._router_fi
        frame: Dict = {"type": "set_fault", "plan": None}
        if fi is not None:
            frame["plan"] = fi.plan.to_obj()
            # transfer the exactly-once bookkeeping: entries already
            # fired by a previous incarnation must not re-fire in the
            # respawned worker
            frame["fired"] = fi.snapshot()["fired_plan_indexes"]
        try:
            with self._control_lock:
                self._control_conn.settimeout(10.0)
                reply = self._control_conn.request(frame)
        except (socket.timeout, wire.WireError) as e:
            self._mark_dead(f"fault-plan push failed: {e}")
            raise WorkerDied(
                f"worker {self.index} died during fault-plan push: {e}"
            ) from e
        if reply.get("type") != "ok":
            raise WorkerDied(
                f"worker {self.index} rejected the fault plan: {reply!r}")

    def bind_aot(self, artifact, record_load: bool = False) -> None:
        from .aot import AotError

        if artifact is self.aot_artifact:
            return
        raise AotError(
            "a process fleet shares ONE manifest handle; rebinding a "
            "different artifact object onto a worker proxy is always "
            "router/worker drift")

    # --- EngineCore surface: request path (engine thread only) --------------
    def add_request(self, prompt_ids, sampling: Optional[SamplingParams]
                    = None, request_id=None, priority: int = 0,
                    trace_id: Optional[str] = None, prefix_hashes=None,
                    slo_ms: Optional[float] = None,
                    resume_tokens: Optional[List[int]] = None
                    ) -> _MirrorRequest:
        self._require_live()
        sp = sampling if sampling is not None else SamplingParams()
        frame = {
            "type": "submit", "rid": request_id,
            "prompt_ids": [int(t) for t in prompt_ids],
            "sampling": {
                "max_new_tokens": sp.max_new_tokens,
                "temperature": sp.temperature, "top_k": sp.top_k,
                "top_p": sp.top_p,
                "eos_token_id": sp.eos_token_id, "seed": sp.seed},
            "priority": priority, "trace_id": trace_id,
            "prefix_hashes": ([h.hex() for h in prefix_hashes]
                              if prefix_hashes else None),
            "slo_ms": slo_ms,
            "resume_tokens": ([int(t) for t in resume_tokens]
                              if resume_tokens else None),
        }
        try:
            reply = self._engine_conn.request(frame)
        except wire.WireError as e:
            self._mark_dead(f"submit failed: {e}")
            raise WorkerDied(
                f"worker {self.index} died during submit: {e}") from e
        if reply.get("type") != "submit_ok":
            self._mark_dead(f"submit rejected: {reply!r}")
            raise WorkerDied(
                f"worker {self.index} refused submit: {reply!r}")
        self._absorb_telemetry(reply)
        mirror = _MirrorRequest(request_id, frame["prompt_ids"])
        if resume_tokens:
            # migrated request (ISSUE 20): the mirror's stream includes
            # the donor-side tokens — the worker only emits FRESH ones
            mirror.output_tokens.extend(int(t) for t in resume_tokens)
        self.requests[request_id] = mirror
        self._has_work = True
        self._lc(request_id, _lc.EV_ENQUEUED, trace_id=trace_id,
                 prompt_tokens=len(mirror.prompt_ids))
        return mirror

    def abort_request(self, request_id,
                      reason: FinishReason = FinishReason.ABORT) -> bool:
        m = self.requests.get(request_id)
        if m is None:
            return False
        ok = True
        if not self._dead.is_set() and self._engine_conn is not None:
            try:
                reply = self._engine_conn.request(
                    {"type": "abort", "rid": request_id,
                     "reason": reason.value})
                ok = bool(reply.get("ok"))
                self._absorb_telemetry(reply)
            except wire.WireError as e:
                # dead worker: the request dies with it — finish the
                # mirror locally so no handle waits on a ghost
                self._mark_dead(f"abort failed: {e}")
        if ok:
            m.finished = True
            m.finish_reason = reason
            self.requests.pop(request_id, None)
            self._lc(request_id, _lc.EV_FINISH, reason=reason.value)
        return ok

    # --- KV hand-off (ISSUE 20; engine thread only) -------------------------
    def _kv_export(self, req_frame: Dict):
        """Send one ``kv_export`` request frame and reassemble the
        streamed ``kv_run_begin``/``kv_run_chunk`` reply.  ``None`` when
        the worker answers empty/refusal (the caller re-prefills);
        :class:`WorkerDied` on wire death."""
        from . import handoff

        self._require_live()
        conn = self._engine_conn
        try:
            conn.send(req_frame)
            begin = conn.recv()
            t = begin.get("type")
            if t in ("kv_export_ok", "error"):
                return None  # untransferable / typed refusal: re-prefill
            if t != "kv_run_begin":
                self._mark_dead(f"protocol desync on kv export: {t!r}")
                raise WorkerDied(
                    f"worker {self.index} protocol desync: got {t!r} "
                    "during a kv export")
            declared = max(0, min(int(begin.get("chunks", 0) or 0), 4096))
            chunks = [conn.recv() for _ in range(declared)]
        except wire.WireError as e:
            self._mark_dead(f"kv export failed: {e}")
            raise WorkerDied(
                f"worker {self.index} died during kv export: {e}") from e
        return handoff.run_from_frames(begin, chunks)

    def export_kv_run(self, request_id):
        """Fetch the worker-side KV run for ``request_id``; ``None``
        when nothing is transferable."""
        return self._kv_export({"type": "kv_export", "rid": request_id})

    def export_prefix_chain(self, chain_hash, max_blocks=None):
        """Fetch the worker-side cached prefix chain addressed by its
        deepest digest (hot-prefix migration); ``None`` on a broken
        chain or refusal."""
        return self._kv_export({
            "type": "kv_export", "chain": bytes(chain_hash).hex(),
            "max_blocks": max_blocks})

    def hot_prefixes(self, top_k=None):
        """Worker-side heat-table-hot prefixes with full chain digests
        (see :meth:`EngineCore.hot_prefixes`)."""
        self._require_live()
        try:
            reply = self._engine_conn.request(
                {"type": "hot_prefixes", "k": top_k})
        except wire.WireError as e:
            self._mark_dead(f"hot_prefixes failed: {e}")
            raise WorkerDied(
                f"worker {self.index} died listing hot prefixes: {e}"
            ) from e
        if reply.get("type") != "hot_prefixes_ok":
            return []
        return list(reply.get("rows") or [])

    def import_kv_run(self, run):
        """Stream a KV run to the worker as block-stream frames and
        admit it.  Mirrors ``EngineCore.import_kv_run``: placed-count on
        success, ``None`` on a capacity refusal,
        :class:`~paddle_tpu.serving.handoff.HandoffError` when the
        worker answers a typed refusal (the caller degrades to
        re-prefill), :class:`WorkerDied` on wire death."""
        from . import handoff

        self._require_live()
        conn = self._engine_conn
        try:
            for frame in handoff.run_to_frames(run):
                conn.send(frame)
            reply = conn.recv()
        except wire.WireError as e:
            self._mark_dead(f"kv import failed: {e}")
            raise WorkerDied(
                f"worker {self.index} died during kv import: {e}") from e
        t = reply.get("type")
        if t == "kv_import_ok":
            placed = reply.get("placed")
            return None if placed is None else int(placed)
        if t == "error":
            raise handoff.HandoffError(
                f"worker {self.index} refused the kv run "
                f"({reply.get('code')}): {reply.get('detail')}")
        self._mark_dead(f"protocol desync on kv import: {t!r}")
        raise WorkerDied(
            f"worker {self.index} protocol desync: got {t!r} during a "
            "kv import")

    def detach_request(self, request_id) -> bool:
        """Drop ``request_id`` from the worker WITHOUT a finish event
        (its hashed prompt blocks park warm) — the donor half of a
        hand-off.  The mirror is popped so no step reply resurrects
        it."""
        m = self.requests.pop(request_id, None)
        self._require_live()
        try:
            reply = self._engine_conn.request(
                {"type": "kv_detach", "rid": request_id})
        except wire.WireError as e:
            self._mark_dead(f"kv detach failed: {e}")
            raise WorkerDied(
                f"worker {self.index} died during kv detach: {e}") from e
        return bool(reply.get("ok")) and m is not None

    def step(self) -> Dict:
        """One worker engine step, one wire round-trip: the ``step_done``
        frame carries the step's full emission batch (``emitted``:
        rid -> [tokens] — a decode burst ships all N tokens per row in
        this one frame) plus state + metrics dump; absorb it, tick the
        shared history.  Legacy per-token ``token`` frames are still
        absorbed for mixed-version fleets.  Any wire failure or
        worker-reported step error surfaces as :class:`WorkerDied` — the
        stock replica death path."""
        self._require_live()
        conn = self._engine_conn
        try:
            t0 = time.perf_counter()
            conn.send({"type": "step"})
            while True:
                frame = conn.recv()
                t = frame.get("type")
                if t == "token":
                    m = self.requests.get(frame["rid"])
                    if m is not None:
                        m.output_tokens.append(int(frame["token"]))
                        if m.first_token_time is None:
                            m.first_token_time = time.perf_counter()
                elif t == "step_done":
                    t3 = time.perf_counter()
                    self._absorb_wire(frame, t0, t3)
                    self._absorb_step(frame)
                    if frame.get("stepped") and self._history is not None:
                        self._history.on_step(self.step_seq)
                    return {}
                elif t == "step_error":
                    # the worker reported its own engine failure (e.g.
                    # an injected engine_step_raise) and is exiting;
                    # absorb the final metrics/fired bookkeeping first
                    self._absorb_metrics(frame)
                    self._mark_dead("worker engine step failed")
                    raise WorkerDied(
                        f"worker {self.index} engine step failed:\n"
                        f"{frame.get('error', '')}")
                else:
                    self._mark_dead(
                        f"protocol desync mid-step: {t!r}")
                    raise WorkerDied(
                        f"worker {self.index} protocol desync: got "
                        f"{t!r} during a step")
        except wire.WireError as e:
            # includes the kill -9 signature: EOF mid-frame (truncated)
            self._mark_dead(f"step wire failure: {e}")
            raise WorkerDied(
                f"worker {self.index} (pid {self.pid}) died mid-step: "
                f"{e}") from e

    def _absorb_metrics(self, frame: Dict) -> None:
        rows = frame.get("metrics")
        if rows and self._merger is not None:
            self._merger.merge(rows)
        fired = frame.get("fired") or []
        if fired and self._router_fi is not None:
            self._router_fi.mark_fired(fired)
        self._absorb_telemetry(frame)

    def _absorb_telemetry(self, frame: Dict) -> None:
        """Merge a piggybacked lifecycle-event delta (idempotent across
        replay/reorder — see :class:`distrib.DeltaMerger`) and keep the
        streamed/dropped counters in step."""
        if self._delta is None:
            return
        delta = frame.get("telemetry")
        if delta:
            applied = self._delta.merge(delta)
            if applied:
                self._c_streamed.inc(applied)
        dropped = self._delta.worker_dropped + self.mirror.dropped
        if dropped > self._dropped_seen:
            self._c_dropped.inc(dropped - self._dropped_seen)
            self._dropped_seen = dropped

    def _absorb_wire(self, frame: Dict, t0: float, t3: float) -> None:
        """Fold one step round-trip's timestamps into the wire-latency
        attribution and the clock estimator (a step IS a valid NTP
        probe: the RTT formula subtracts worker processing time)."""
        stamps = frame.get("t")
        if not stamps:
            return
        try:
            recv, reply = float(stamps["recv"]), float(stamps["reply"])
        except (KeyError, TypeError, ValueError):
            return  # swallow-ok: stamps are an OPTIONAL protocol field — an old/partial worker reply just skips wire attribution for this step
        self.clock.observe(t0, recv, reply, t3)
        rec = frame.get("step_record")
        program = None
        if isinstance(rec, dict):
            progs = rec.get("programs") or ()
            program = ",".join(p.get("program", "?")
                               for p in progs) or None
        self.wire_stats.observe(t0, t3, stamps, program=program)
        if isinstance(rec, dict):
            # mirror the step record next to the lifecycle events: the
            # engine_death bundle shows what the worker was computing
            self.mirror.append({
                "name": "step_record",
                "ts": self.clock.to_router(reply),
                "record": rec,
            })

    def distrib_state(self) -> Dict:
        """Per-worker cross-process telemetry snapshot: the flight
        recorder embeds this (via ``bind_distrib``) into post-mortem
        bundles, and ``/v1/debug/wire`` serves it live."""
        return {
            "pid": self.pid,
            "telemetry": self._telemetry,
            "clock": self.clock.snapshot(),
            "merge": (self._delta.snapshot()
                      if self._delta is not None else None),
            "mirror": self.mirror.snapshot(),
            "stderr_tail": (list(self.worker.stderr_tail)
                            if self.worker is not None else []),
            "wire": self.wire_stats.report(),
        }

    def _absorb_step(self, frame: Dict) -> None:
        self._absorb_metrics(frame)
        self.step_seq = int(frame.get("step_seq", self.step_seq))
        self._has_work = bool(frame.get("has_work", False))
        self._queue_depth = int(frame.get("queue_depth", 0))
        self._occupancy = float(frame.get("occupancy", 0.0))
        self._degraded = bool(frame.get("degraded", False))
        # emission batch BEFORE the finished map: a finishing request's
        # EV_FINISH token count must include this step's (burst) tokens
        for rid, toks in (frame.get("emitted") or {}).items():
            m = self.requests.get(rid)
            if m is not None:
                m.output_tokens.extend(int(t) for t in toks)
                if m.first_token_time is None and toks:
                    # first-token boundary (ISSUE 20): the migration
                    # sweep keys off this, same as in-process Request
                    m.first_token_time = time.perf_counter()
        for rid, reason in (frame.get("finished") or {}).items():
            m = self.requests.pop(rid, None)
            if m is None:
                continue
            m.finish_reason = (FinishReason(reason) if reason else None)
            m.finished = True
            self._lc(rid, _lc.EV_FINISH, reason=reason,
                     tokens=len(m.output_tokens))

    # --- control-plane fetches (any thread) ---------------------------------
    def debug_fetch(self, what: str, default=None):
        """Fetch a debug snapshot over the control connection; returns
        ``default`` when the worker is dead/parked (debug surfaces
        degrade to 'restarting' rows instead of erroring)."""
        if self._dead.is_set() or self._control_conn is None:
            return default
        try:
            with self._control_lock:
                self._control_conn.settimeout(10.0)
                reply = self._control_conn.request(
                    {"type": "debug", "what": what})
        except (socket.timeout, wire.WireError) as e:
            self._mark_dead(f"debug fetch {what!r} failed: {e}")
            return default
        if reply.get("type") != "debug_ok":
            return default
        return reply.get("data", default)


class _SharedState:
    """Everything the per-index factory closes over: the config, the
    shared registry, the template gate objects, the artifact handle, and
    the live proxy map (index → proxy) through which old workers are
    reaped when the supervisor respawns an index."""

    def __init__(self, cfg: ProcessFleetConfig,
                 registry: MetricsRegistry):
        self.cfg = cfg
        self.registry = registry
        # ONE template per fleet: the router's homogeneity gates compare
        # these across proxies (audit cfg by value, engine knobs by
        # field), and ONE artifact handle pins the same-artifact gate
        self.template_audit = (
            AuditConfig(enabled=True,
                        sample_every=max(1, cfg.audit_sample_every))
            if cfg.audit_enabled else AuditConfig())
        self.template_engine_cfg = EngineConfig(
            num_blocks=cfg.num_blocks, block_size=cfg.block_size,
            unified_step=cfg.unified,
            burst_steps=cfg.burst_steps,
            mp=(cfg.mp if cfg.mp > 1 else None),
            spec=self.spec_config(),
            audit=(self.template_audit if cfg.audit_enabled else None))
        if cfg.roles is not None and len(cfg.roles) != cfg.dp:
            raise ValueError(
                f"ProcessFleetConfig.roles has {len(cfg.roles)} "
                f"entrie(s) for dp={cfg.dp}; give one role per replica "
                "index (serving.fleet.parse_roles builds the list)")
        self.aot_handle: Optional[AotManifestHandle] = None
        self.active: Dict[int, WorkerEngineProxy] = {}  # index ->
        # current proxy; bounded by dp
        self.lock = threading.RLock()
        self.initial_live = cfg.dp
        self.built = False  # set once FleetRouter.build returns: later
        # factory calls are supervisor respawns / scale-ups — always live
        self._respawn_c = registry.counter(
            "serving_fleet_worker_respawns_total",
            "worker processes replaced (supervisor respawn or "
            "autoscaler churn)")
        self._g_active = registry.gauge(
            "serving_fleet_active_workers",
            "live (spawned, not dead/closed) worker processes")

    def spec_config(self):
        """The fleet's :class:`~paddle_tpu.serving.spec.SpecConfig`, or
        ``None`` when spec decoding is off.  Built from the SAME kwargs
        dict each worker receives, so the router's deployment identity
        and every worker's engine-derived one agree by construction."""
        if not self.cfg.spec:
            return None
        from .spec import SpecConfig

        sc = SpecConfig(**self.cfg.spec)
        return sc if sc.enabled else None

    def role_for(self, index: int) -> str:
        """Replica ``index``'s role (ISSUE 20): ``unified`` unless the
        fleet config assigns specialists."""
        if self.cfg.roles is None:
            return "unified"
        return str(self.cfg.roles[index])

    def engine_cfg_for(self, index: int) -> EngineConfig:
        """The proxy's gate-surface EngineConfig: the shared template,
        with the per-index role folded in (roles are deliberately NOT a
        homogeneity gate, so per-index copies are safe — audit/spec/aot
        members stay the SAME objects the gates compare)."""
        role = self.role_for(index)
        if role == "unified":
            return self.template_engine_cfg
        return _dc_replace(self.template_engine_cfg, role=role)

    def deploy(self, index: Optional[int] = None) -> Dict:
        """Deployment identity presented in every wire handshake
        (ISSUE 18 fleet satellite): mesh-slice shape + spec config +
        (ISSUE 20) the replica's role."""
        sc = self.spec_config()
        return {"mp": int(self.cfg.mp),
                "spec": (sc.manifest_dict() if sc is not None else None),
                "role": (self.role_for(index)
                         if index is not None else "unified")}

    def worker_spec(self, index: Optional[int] = None) -> Dict:
        cfg = self.cfg
        spec = {"role": self.role_for(index)} if index is not None else {}
        return {
            **spec,
            "layers": cfg.layers, "num_blocks": cfg.num_blocks,
            "block_size": cfg.block_size,
            "max_num_seqs": cfg.max_num_seqs,
            "max_prefill_tokens_per_step":
                cfg.max_prefill_tokens_per_step,
            "max_tokens_per_step": cfg.max_tokens_per_step,
            "mp": cfg.mp, "spec": cfg.spec,
            "burst_steps": cfg.burst_steps,
            "unified_step": cfg.unified, "seed": cfg.seed,
            "audit_enabled": cfg.audit_enabled,
            "audit_sample_every": cfg.audit_sample_every,
            # telemetry streaming (ISSUE 17): workers run their engines
            # with lifecycle events ON and stream deltas back; the
            # router still owns the ONE merged timeline and the ONE
            # history store ("history" stays False).  telemetry=False
            # restores the old dark-worker behavior.
            "lifecycle_events": bool(cfg.telemetry),
            "decode_event_sample": cfg.decode_event_sample,
            "telemetry": bool(cfg.telemetry),
            "history": False,
        }

    def factory(self, index: int, registry) -> WorkerEngineProxy:
        """The ``engine_factory(i, registry)`` handed to
        :meth:`FleetRouter.build` — and therefore the SAME callable the
        supervisor's ``_rebuild`` and the autoscaler's provisioning use.
        Replacing an index closes (kills) the previous incarnation's
        process first: respawn == in-process engine reconstruction."""
        with self.lock:
            old = self.active.pop(index, None)
            live = True if self.built else index < self.initial_live
        if old is not None:
            old.close(graceful=False)
            if old.is_live:
                self._respawn_c.inc()
        proxy = WorkerEngineProxy(self, index, live=live)
        with self.lock:
            self.active[index] = proxy
        self.update_gauge()
        return proxy

    def update_gauge(self) -> None:
        with self.lock:
            n = sum(1 for p in self.active.values()
                    if p.is_live and not p._closed
                    and not p._dead.is_set())
        self._g_active.set(n)

    def close_all(self) -> None:
        with self.lock:
            proxies = list(self.active.values())
        for p in proxies:
            p.close()
        self.update_gauge()


class ProcessFleet:
    """A process-isolated dp fleet: the stock :class:`FleetRouter` (and
    optional :class:`FleetSupervisor`) over :class:`WorkerEngineProxy`
    replicas.  ``initial_replicas < dp`` parks the tail indexes (no
    process, no engine thread — routed around via ``healthy=False`` and
    skipped by the supervisor via ``thread is None``) as the
    autoscaler's headroom."""

    def __init__(self, config: Optional[ProcessFleetConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 initial_replicas: Optional[int] = None):
        self.cfg = config or ProcessFleetConfig()
        self.registry = (registry if registry is not None
                         else MetricsRegistry(max_series=4096))
        self.shared = _SharedState(self.cfg, self.registry)
        if self.cfg.aot_path:
            self.shared.aot_handle = AotManifestHandle.load(
                self.cfg.aot_path)
        self.shared.initial_live = (
            self.cfg.dp if initial_replicas is None
            else max(1, min(int(initial_replicas), self.cfg.dp)))
        try:
            self.router = FleetRouter.build(
                self.shared.factory, dp=self.cfg.dp,
                config=self.cfg.fleet or FleetConfig(),
                registry=self.registry)
        except BaseException:
            self.shared.close_all()  # no orphan worker processes
            raise
        self.shared.built = True
        # flight bundles embed the per-worker telemetry mirrors/stderr
        # tails; a closure over shared.active reads the CURRENT proxies,
        # so supervisor respawns need no rebind — and at engine_death
        # time the DEAD proxy is still the active entry, so its mirror
        # (the dead worker's last events) is exactly what gets dumped
        self.router.flight.bind_distrib(self._distrib_state)
        self.supervisor: Optional[FleetSupervisor] = None
        self.autoscaler: Optional["FleetAutoscaler"] = None
        self.rebalancer: Optional["CacheRebalancer"] = None

    def _distrib_state(self) -> Dict:
        with self.shared.lock:
            proxies = dict(self.shared.active)
        return {str(i): p.distrib_state() for i, p in proxies.items()}

    # --- lifecycle ----------------------------------------------------------
    def supervise(self, config: Optional[SupervisorConfig] = None
                  ) -> FleetSupervisor:
        self.supervisor = FleetSupervisor(self.router, config=config)
        return self.supervisor

    def start(self, notify=None) -> "ProcessFleet":
        """Start the live replicas' engine threads (parked replicas stay
        threadless — that is what keeps them out of routing and out of
        the supervisor's healing scan) and the supervisor if attached."""
        if notify is not None:
            self.router._notify_cb = notify
        for r in self.router.replicas:
            proxy = self.shared.active.get(r.index)
            if proxy is not None and proxy.is_live and r.thread is None:
                r.start()
        if self.supervisor is not None:
            self.supervisor.start()
        self.router.sample_gauges()
        return self

    def stop(self, join_timeout: float = 10.0) -> None:
        for actor in (self.autoscaler, self.rebalancer):
            if actor is not None:
                actor.close()
        self.router.stop(join_timeout)
        self.shared.close_all()

    def shutdown(self, drain_timeout: Optional[float] = None) -> None:
        for actor in (self.autoscaler, self.rebalancer):
            if actor is not None:
                actor.close()
        self.router.shutdown(drain_timeout)
        self.shared.close_all()

    # --- actuators ----------------------------------------------------------
    def enable_autoscaler(self, config: Optional["AutoscalerConfig"]
                          = None) -> "FleetAutoscaler":
        self.autoscaler = FleetAutoscaler(self, config=config)
        return self.autoscaler

    def enable_rebalancer(self, config: Optional["RebalancerConfig"]
                          = None) -> "CacheRebalancer":
        self.rebalancer = CacheRebalancer(self.router, config=config,
                                          registry=self.registry)
        return self.rebalancer

    # --- inspection (tests/bench) -------------------------------------------
    def proxy(self, index: int) -> Optional[WorkerEngineProxy]:
        return self.shared.active.get(index)

    def worker_pid(self, index: int) -> Optional[int]:
        p = self.shared.active.get(index)
        return p.pid if p is not None else None

    def live_replica_count(self) -> int:
        return sum(1 for r in self.router.replicas
                   if r.thread is not None)


@dataclass
class AutoscalerConfig:
    """Bounds and pacing for the SLO-driven autoscaling actuator.
    Cooldowns are measured in HISTORY SAMPLE indexes, not wall time —
    the decision function consumes only ``(sample_index, firing)``
    pairs, which is what makes a recorded run replayable bit-for-bit
    under the frozen rule set."""

    min_replicas: int = 1
    max_replicas: int = 0  # 0 = the fleet's dp (index space is fixed)
    scale_up_rules: Tuple[str, ...] = (
        "goodput_burn", "pool_exhaustion", "restart_churn")
    cooldown_samples: int = 25   # min samples between any two actions
    calm_samples: int = 100      # firing-free samples after a breach
                                 # before draining back down


class ScaleDecider:
    """The pure decision core: feed ``(sample_index, firing-rule set)``
    pairs in order, get ``"up"`` / ``"down"`` / ``None`` out.  No
    clocks, no fleet reads, no randomness — state is the tracked replica
    count and two sample indexes, so replaying a recorded input stream
    through a fresh instance reproduces the decision sequence exactly."""

    def __init__(self, cfg: AutoscalerConfig, start_replicas: int,
                 min_replicas: int, max_replicas: int):
        self.cfg = cfg
        self.replicas = int(start_replicas)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self._last_action: Optional[int] = None
        self._last_breach: Optional[int] = None
        self.decisions: deque = deque(maxlen=256)

    def decide(self, sample_idx: int, firing) -> Optional[str]:
        firing = frozenset(firing)
        breach = any(r in firing for r in self.cfg.scale_up_rules)
        if breach:
            self._last_breach = sample_idx
        cooled = (self._last_action is None
                  or sample_idx - self._last_action
                  >= self.cfg.cooldown_samples)
        direction = None
        if breach and cooled and self.replicas < self.max_replicas:
            direction = "up"
            self.replicas += 1
        elif (not firing and cooled
              and self.replicas > self.min_replicas
              and self._last_breach is not None
              and sample_idx - self._last_breach
              >= self.cfg.calm_samples):
            direction = "down"
            self.replicas -= 1
        if direction is not None:
            self._last_action = sample_idx
            self.decisions.append({
                "sample": sample_idx, "direction": direction,
                "firing": sorted(firing), "replicas": self.replicas})
        return direction


class FleetAutoscaler:
    """Tentpole (d): AlertEngine firings → bounded scale actions on the
    process pool.

    Wiring: a history listener registered AFTER the router's AlertEngine
    (listener order is registration order, so each sample's rule states
    are already updated when we read them).  The listener runs on an
    engine thread, so it only *decides* (pure, fast); actuation —
    spawning/draining worker processes — happens on a dedicated actuator
    thread.  Scale-up provisions the lowest parked index with the exact
    wiring sequence ``FleetSupervisor._rebuild`` uses (minus the restart
    accounting: provisioning is not failure triage); scale-down stops
    the highest live index only when it has zero in-flight work, closing
    the submit race under the router's submit lock."""

    def __init__(self, fleet: ProcessFleet,
                 config: Optional[AutoscalerConfig] = None):
        router = fleet.router
        if router.history is None or router.alerts is None:
            raise ValueError(
                "the autoscaler consumes alert-rule firings: build the "
                "fleet with EngineConfig.history=True (the default) so "
                "the router carries a HistoryStore + AlertEngine")
        self.fleet = fleet
        self.cfg = config or AutoscalerConfig()
        self.min_replicas = max(1, self.cfg.min_replicas)
        self.max_replicas = (self.cfg.max_replicas or router.dp)
        self.max_replicas = min(self.max_replicas, router.dp)
        self.start_replicas = fleet.live_replica_count()
        self.decider = ScaleDecider(self.cfg, self.start_replicas,
                                    self.min_replicas, self.max_replicas)
        self.inputs: deque = deque(maxlen=512)  # (idx, firing) replay log
        reg = router.registry
        self._scale_c = {
            d: reg.counter("serving_fleet_scale_events_total",
                           "autoscaler actions applied to the process "
                           "pool", direction=d)
            for d in ("up", "down")}
        self._q: "queue.Queue" = queue.Queue(maxsize=8)
        self._stop_ev = threading.Event()
        self._thread = threading.Thread(target=self._actuate_loop,
                                        daemon=True,
                                        name="fleet-autoscaler")
        self._thread.start()
        self._remove = router.history.add_listener(self._on_sample)

    def close(self) -> None:
        self._remove()
        self._stop_ev.set()
        self._thread.join(5.0)

    # --- decision (engine thread; must stay wire-free) ----------------------
    def _on_sample(self, sample_idx: int, step: int) -> None:
        firing = tuple(sorted(
            self.fleet.router.alerts.snapshot()["firing"]))
        self.inputs.append((sample_idx, firing))
        direction = self.decider.decide(sample_idx, firing)
        if direction is not None:
            try:
                self._q.put_nowait(direction)
            except queue.Full:
                pass  # swallow-ok: an action backlog this deep means the actuator is already reshaping the pool; the next sample re-decides

    def replay(self, inputs=None) -> List[Optional[str]]:
        """Re-run the frozen decision function over recorded
        ``(sample_index, firing)`` inputs (default: this instance's own
        log).  Equality with the live decision sequence is the
        replay-determinism contract the tests assert."""
        d = ScaleDecider(self.cfg, self.start_replicas,
                         self.min_replicas, self.max_replicas)
        return [d.decide(i, f)
                for i, f in (self.inputs if inputs is None else inputs)]

    # --- actuation (dedicated thread) ---------------------------------------
    def _actuate_loop(self) -> None:
        while not self._stop_ev.is_set():
            try:
                direction = self._q.get(timeout=0.1)
            except queue.Empty:
                continue  # swallow-ok: Empty IS the stop-flag poll cadence
            try:
                if direction == "up":
                    self._scale_up()
                else:
                    self._scale_down()
            except Exception:
                sys.stderr.write("[autoscaler] action failed:\n"
                                 + traceback.format_exc())

    def _scale_up(self) -> None:
        router = self.fleet.router
        sup = router.supervisor
        excluded = sup.excluded if sup is not None else set()
        target = None
        for i, r in enumerate(router.replicas):
            if r.thread is None and i not in excluded:
                target = i
                break
        if target is None:
            return  # nothing parked: already at the pool's edge
        self._provision(target)
        self._scale_c["up"].inc()
        router.lifecycle.event(
            None, "scale_event", direction="up", replica=str(target),
            replicas=self.fleet.live_replica_count())
        sys.stderr.write(f"[autoscaler] scaled up: provisioned replica "
                         f"{target}\n")

    def _provision(self, index: int) -> None:
        """Bring a parked index live: factory (spawns the worker) + the
        same rewiring sequence ``FleetSupervisor._rebuild`` performs —
        shared tracker, flight, history, per-index fault injector —
        WITHOUT the restart counters/lifecycle (this is provisioning,
        not failure recovery; ``serving_replica_restarts_total`` must
        not count scale-ups)."""
        router = self.fleet.router
        eng = router._engine_factory(index, router.registry)
        eng.set_lifecycle(router.lifecycle, replica=str(index))
        eng.audit.bind_flight(router.flight, replica=str(index))
        if router.history is not None:
            eng.set_history(router.history)
        fi = router.fault_injectors.get(index)
        if fi is not None:
            eng.set_fault_injector(fi)
        new = EngineReplica(index, eng, router.cfg.max_queue,
                            notify=router._notify,
                            on_finish=router._release)
        new.flight = router.flight
        sup = router.supervisor
        if sup is not None:
            sup._adopt(new)
        router.engines[index] = eng
        router.replicas[index] = new
        router.flight.bind_step_profilers(
            {str(r.index): r.engine.stepprof for r in router.replicas})
        router.flight.bind_cache_trackers(
            {str(r.index): r.engine.cachestat for r in router.replicas})
        router.flight.reset_once("engine_death", str(index))
        new.start()
        router.sample_gauges()

    def _scale_down(self) -> None:
        router = self.fleet.router
        # highest live index with no in-flight work; the submit lock
        # closes the race where a router thread admits onto the replica
        # between the idle check and request_stop
        for r in reversed(router.replicas):
            if r.thread is None:
                continue
            with router._submit_lock:
                if r.in_flight:
                    continue
                r.request_stop()
            r.join(10.0)
            r.thread = None  # parked again: invisible to routing and
            # to the supervisor's healing scan, reclaimable by scale-up
            proxy = self.fleet.shared.active.get(r.index)
            if proxy is not None:
                proxy.close()
            self._scale_c["down"].inc()
            router.lifecycle.event(
                None, "scale_event", direction="down",
                replica=str(r.index),
                replicas=self.fleet.live_replica_count())
            router.sample_gauges()
            self.fleet.shared.update_gauge()
            sys.stderr.write(f"[autoscaler] scaled down: drained "
                             f"replica {r.index}\n")
            return
        sys.stderr.write("[autoscaler] scale-down skipped: every live "
                         "replica busy or at the floor\n")


@dataclass
class RebalancerConfig:
    """Cache-aware vnode re-weighting knobs."""

    threshold: float = 0.15        # act only past this imbalance
    min_interval_samples: int = 50  # history samples between reweights
    min_weight: float = 0.25
    max_weight: float = 4.0
    # hot-prefix migration (ISSUE 20): after a reweight, heat-table-hot
    # prefix chains whose ring key now routes AWAY from the replica
    # holding them warm are copied to the new target over the hand-off
    # block streams, so the first affinity-routed request there hits
    # the prefix cache instead of recomputing
    migrate_prefixes: bool = True
    migrate_top_k: int = 4          # hot chains considered per donor
    migrate_max_blocks: int = 16    # block budget per donor per reweight


class CacheRebalancer:
    """The first cache-aware rebalancing ACTUATOR (tentpole (d)): PR 12
    built the signal (``serving_fleet_cache_imbalance``), this closes
    the loop.  On each history sample past the threshold, per-replica
    vnode weights are set inversely to cached-token ratio — a COLD
    replica (low ratio) gets more ring points, so new affinity keys
    migrate toward it and warm it up, narrowing the gap instead of
    letting placement luck compound.  Works over any
    :class:`FleetRouter` — in-process or :class:`ProcessFleet`."""

    def __init__(self, router: FleetRouter,
                 config: Optional[RebalancerConfig] = None,
                 registry: Optional[MetricsRegistry] = None):
        if router.history is None:
            raise ValueError(
                "the rebalancer rides history samples: build the fleet "
                "with EngineConfig.history=True (the default)")
        self.router = router
        self.cfg = config or RebalancerConfig()
        reg = registry if registry is not None else router.registry
        self._c = reg.counter(
            "serving_fleet_ring_reweights_total",
            "cache-aware consistent-hash vnode reweights applied")
        self._mig_c = reg.counter(
            "serving_fleet_prefix_migrations_total",
            "heat-table-hot prefix chains copied to their post-reweight "
            "ring target over the hand-off block streams")
        self._last: Optional[int] = None
        self.last_weights: Optional[Dict[int, float]] = None
        self._remove = router.history.add_listener(self._on_sample)

    def close(self) -> None:
        self._remove()

    def _on_sample(self, sample_idx: int, step: int) -> None:
        cfg = self.cfg
        if self._last is not None \
                and sample_idx - self._last < cfg.min_interval_samples:
            return
        router = self.router
        imbalance = router.cache_imbalance()
        if imbalance is None or imbalance < cfg.threshold:
            return
        ratios = router.cached_token_ratios()
        vals = [v for v in ratios.values() if v is not None]
        if len(vals) < 2:
            return
        mean = sum(vals) / len(vals)
        weights: Dict[int, float] = {}
        for key, ratio in ratios.items():
            if ratio is None:
                continue
            w = 1.0 + (mean - ratio)  # cold (below mean) -> heavier
            weights[int(key)] = min(cfg.max_weight,
                                    max(cfg.min_weight, w))
        router.reweight_ring(weights)
        self._c.inc()
        router.lifecycle.event(
            None, "ring_reweighted", imbalance=round(imbalance, 4),
            weights={str(k): round(w, 3) for k, w in weights.items()})
        self._last = sample_idx
        self.last_weights = weights
        self._migrate_hot_prefixes()

    # --- hot-prefix migration (ISSUE 20) ------------------------------------
    def _migrate_hot_prefixes(self) -> None:
        """Schedule one bounded hot-prefix sweep per healthy replica.
        All pool and wire work rides the replicas' own engine threads
        (:meth:`EngineReplica.post`): the heat walk and export run on
        the donor's thread, the import on the recipient's — the
        rebalancer thread only enqueues."""
        if not self.cfg.migrate_prefixes:
            return
        for donor in list(self.router.replicas):
            if donor.healthy:
                donor.post(lambda d=donor: self._donor_sweep(d))

    def _donor_sweep(self, donor: EngineReplica) -> None:
        """On ``donor``'s engine thread: walk its heat table hot-first
        and export any chain whose ring key now routes elsewhere, within
        the per-donor block budget.  Prefix hits matter at PREFILL, so
        ring targets are computed over the same prefill/unified pool
        admissions route through."""
        cfg, router = self.cfg, self.router
        rows = donor.engine.hot_prefixes(cfg.migrate_top_k)
        budget = cfg.migrate_max_blocks
        pool = [r for r in router.replicas
                if r.healthy and r.role in ("prefill", "unified")] \
            or [r for r in router.replicas if r.healthy]
        for row in rows:
            if budget <= 0:
                break
            lead = row.get("lead")
            if not lead:
                continue
            key_depth = min(router.cfg.affinity_blocks, len(lead))
            key = _key_int([bytes.fromhex(lead[key_depth - 1])])
            target = router._ring_target(key, pool)
            if target is None or target is donor:
                continue
            run = donor.engine.export_prefix_chain(
                bytes.fromhex(str(row["chain"])), max_blocks=budget)
            if not run or not run.get("blocks"):
                continue
            budget -= len(run["blocks"])
            if not target.post(
                    lambda t=target, d=donor, r=run:
                    self._import_migrated(d, t, r)):
                budget += len(run["blocks"])  # recipient queue full

    def _import_migrated(self, donor: EngineReplica,
                         target: EngineReplica, run: Dict) -> None:
        """On ``target``'s engine thread: admit one migrated prefix run
        (content-verified, atomic).  A refusal or typed error just
        degrades to recompute-on-miss — posted tasks are best-effort."""
        try:
            placed = target.engine.import_kv_run(run)
        except Exception:
            return  # swallow-ok: a refused/failed import degrades to recompute-on-miss at the target; the donor copy is untouched
        if placed:
            self._mig_c.inc()
            self.router.lifecycle.event(
                None, "prefix_migrated", src=str(donor.index),
                dst=str(target.index), blocks=len(run["blocks"]),
                placed=int(placed))
