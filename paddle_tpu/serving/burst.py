"""Decode-burst host surface (ISSUE 19).

The device side is :func:`paddle_tpu.ops.decode_burst.run_burst` — one
compiled program chaining up to N decode steps.  This module owns the
host half: the eligibility predicate (WHEN the engine may burst), the
length clamp (HOW FAR it may burst), and the burst metric series.

Eligibility is deliberately conservative — a burst launches only when
the running set is a decode-only resident cohort and the whole horizon
is pre-decided, so every scheduler contract (admission, preemption,
spec drafting) stays a host decision at burst boundaries:

* ``burst_steps >= 2`` configured (1-step bursts are just decode with
  extra padding);
* no prefill work pending: the plan carries no chunks AND the waiting
  queue is empty AND no running request still needs prefill (a chunk
  the budget deferred this step must not be starved for N steps);
* spec decoding off — the n-gram proposer drafts from the freshest
  host-side token history every step, so a resident burst would decode
  exactly the tokens the proposer exists to skip;
* at least 2 decode rows' worth of headroom after the clamp.

The clamp (``clamp_burst``) is the launch-side half of the ONE headroom
accessor ``KVCacheManager.burst_capacity`` — the scheduler computed
``plan.burst_capacity`` from it after reserving this step's decode
slots, so by construction the burst can never hit pool exhaustion or a
``max_new_tokens`` boundary it cannot represent mid-flight.
"""

from __future__ import annotations

# pre-registered by the engine at construction so the series exist from
# the first scrape (tools/check_metrics_docs lints README coverage;
# tools/check_bounded_metrics pins this module's growth discipline)
METRIC_NAMES = (
    "serving_burst_launches_total",
    "serving_burst_tokens_total",
    "serving_burst_length",
    "serving_host_roundtrips_total",
)

# a burst length is clamped to config.burst_steps, itself bounded by the
# AOT lattice — power-of-two-ish buckets keep the histogram aligned
# with the burst-length bucket axis
_LENGTH_BUCKETS = (2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)


def register_metrics(registry, labels=None):
    """Create the burst series on ``registry`` (get-or-create, so dp
    replicas sharing a registry share per-label series).  ``labels``
    must carry the engine's replica label in fleets: the cross-process
    :class:`~paddle_tpu.serving.wire.RegistryMerger` merges ONLY rows
    labeled with the owning replica."""
    lb = labels or {}
    return {
        "launches": registry.counter(
            "serving_burst_launches_total",
            help="device-resident decode bursts launched", **lb),
        "tokens": registry.counter(
            "serving_burst_tokens_total",
            help="tokens emitted by burst launches (all rows)", **lb),
        "length": registry.histogram(
            "serving_burst_length",
            help="clamped burst length N per launch (decode steps "
                 "covered by one host round-trip)",
            buckets=_LENGTH_BUCKETS, **lb),
        "roundtrips": registry.counter(
            "serving_host_roundtrips_total",
            help="host->device step-program launches (a burst counts "
                 "once; the saving vs per-step decode is this series' "
                 "slope)", **lb),
    }


def clamp_burst(burst_steps: int, decodes, capacity: int) -> int:
    """The host-side burst-length clamp:
    ``N = min(config.burst_steps, min per-row remaining max_new,
    pool headroom per row)`` — every term a quantity the host already
    owns, so the device loop needs no in-trace max_new/pool masking.

    Returns 0 when no burst is worth launching (``N < 2``)."""
    if burst_steps < 2 or not decodes:
        return 0
    remaining = min(r.sampling.max_new_tokens - len(r.output_tokens)
                    for r in decodes)
    n = min(int(burst_steps), int(remaining), int(capacity))
    return n if n >= 2 else 0


def burst_eligible(scheduler, plan, decodes, spec) -> bool:
    """True when this step's running set is a decode-only resident
    cohort (see module docstring) — the gate the tests hold to 'burst
    provably never launched when spec drafting or prefill work is
    pending'."""
    if spec is not None or not decodes:
        return False
    if plan.prefills or scheduler.waiting:
        return False
    # a running request the chunk budget deferred this step still needs
    # prefill — bursting the decode cohort would starve it for N steps
    return not any(scheduler._needs_prefill(r) for r in scheduler.running)
