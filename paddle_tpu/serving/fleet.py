"""``paddle_tpu.serving.fleet`` — data-parallel serving fleet (ISSUE 6).

The HTTP frontend (PR 3) drives exactly ONE engine thread; the north
star is heavy traffic, so this module adds the horizontal layer the
ROADMAP names: a :class:`FleetRouter` that owns N :class:`EngineCore`
replicas — each on its own engine thread with its own ``BlockPool`` /
prefix cache and its own bounded submit/abort queues (the PR 3 bridge
pattern, instantiated per replica) — behind one routing decision:

**Prefix-affinity consistent-hash routing.**  The router chain-hashes
the request's leading full prompt blocks (the SAME
``h_i = sha256(h_{i-1} || block_tokens_i)`` chain the prefix cache of
PR 4 registers — :func:`~paddle_tpu.ops.paged_attention.prefix_chain_hashes`)
and maps the last digest onto a consistent-hash ring of replica vnodes.
Identical prefixes therefore deterministically land on the SAME replica,
whose prefix cache is warm — multiplying the PR 4 cached-token ratio
instead of diluting it round-robin — while distinct prefixes spread
uniformly.  The hashes are handed down with the request
(``Request.prefix_hashes``) so the replica's admission probe does not
re-hash the same blocks.  Consistent hashing (vnodes + clockwise walk)
means a dead replica only remaps ITS keys; everyone else's affinity is
untouched.

**Least-loaded fallback + per-replica admission.**  When the affinity
target is saturated (per-replica in-flight cap) or unhealthy (engine
thread dead), the request falls back to the least-loaded eligible
replica (``serving_fleet_fallback_routed_total`` vs
``serving_fleet_affinity_hit_total``).  Admission is per replica: a
request is rejected (:class:`FleetSaturated` → HTTP 429) only when
EVERY eligible replica is at its cap, and refused
(:class:`FleetDown` → HTTP 503) only when the whole fleet is down or
draining.

**Per-replica health + fleet drain.**  A replica whose engine thread
died is excluded from routing (its in-flight handles are marked done and
its engine requests aborted, so no handler hangs); the fleet keeps
serving on the survivors.  ``shutdown()`` drains fleet-wide: stop
admission instantly, let in-flight work finish to the deadline, abort
stragglers through their OWNING replica, stop every engine thread —
leaving zero pool occupancy on every replica (tested).

**Self-healing (ISSUE 12).**  With a
:class:`~paddle_tpu.serving.resilience.FleetSupervisor` attached, a
dead replica's handles are CLAIMED by the supervisor instead of being
terminally marked (``EngineReplica.supervised``): recoverable requests
re-dispatch through normal routing and the replica is rebuilt on the
same index; watchdog-stalled or quarantined replicas carry
``unhealthy`` (the ``healthy`` property is what routing consults).
``FleetConfig.fault_plan`` threads a deterministic
:class:`~paddle_tpu.serving.faultinject.FaultPlan` through every
replica's engine so the whole failure surface is injectable in tests.

**Observability.**  All replicas share ONE
:class:`~paddle_tpu.observability.MetricsRegistry`: each engine's
``serving_*`` series carries a ``replica="i"`` label
(``EngineCore(metrics_labels=...)``), and the router adds the
``serving_fleet_*`` family — replica occupancy / queue / in-flight
gauges, alive gauges, and the affinity-hit vs fallback-routed counters.

Threading model (N engine threads, lock-free bridges)::

    handler / caller threads          engine thread i (owns replica i)
    ────────────────────────          ───────────────────────────────
    router.submit(handle) ──ring──▶   replica.submit_q (bounded)
      · owner[rid] = replica i          drain → EngineCore.add_request
    router.abort(rid) ──owner map─▶   replica.abort_q (bounded)
    read handle.req.output_tokens     step(); evict finished handles
                                      (owner map entry evicted too)

The request→replica **owner map** is how an abort/timeout/disconnect
reaches the replica that actually holds the request's blocks; entries
are evicted when the request finishes, so the map is bounded by the sum
of per-replica admission caps.

Everything is CPU-provable with host threads: dp=2 greedy output is
token-identical to dp=1 (each replica keeps the established
batch-composition-independence contract), per-replica jit trace counts
stay within the single-engine bucket bound, and a full-fleet drain
leaves every pool empty — ``tests/test_serving_fleet.py``.
"""

from __future__ import annotations

import bisect
import hashlib
import itertools
import queue
import threading
import time
import traceback
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from ..observability import lifecycle as _lc
from ..observability.alerts import AlertEngine, AlertRuleSet
from ..observability.flight import FlightConfig, FlightRecorder
from ..observability.history import HistoryConfig, HistoryStore
from ..observability.lifecycle import LifecycleTracker
from ..observability.metrics import MetricsRegistry
from ..ops.paged_attention import prefix_chain_hashes
from .engine import EngineCore
from .faultinject import FaultInjector, FaultPlan
from .handoff import register_handoff_metrics
from .request import FinishReason, SamplingParams

# pre-registered metric names this module owns (tools/check_metrics_docs
# lints that each appears in README's metrics table)
METRIC_NAMES = (
    "serving_fleet_replicas",
    "serving_fleet_replicas_alive",
    "serving_fleet_in_flight",
    "serving_fleet_affinity_hit_total",
    "serving_fleet_fallback_routed_total",
    "serving_fleet_replica_alive",
    "serving_fleet_replica_in_flight",
    "serving_fleet_replica_occupancy",
    "serving_fleet_replica_queue_depth",
    # ISSUE 13: max − min per-replica cached-token ratio, sampled per
    # scrape — the cache-aware rebalancing trigger signal
    "serving_fleet_cache_imbalance",
)


class FleetSaturated(RuntimeError):
    """Every eligible replica rejected the request (per-replica
    admission caps all hit) — the HTTP frontend answers 429."""


class FleetDown(RuntimeError):
    """No live replica to route to (all engine threads dead, or the
    fleet is draining) — the HTTP frontend answers 503."""


@dataclass
class FleetConfig:
    """Router-level knobs (per-replica engine knobs ride
    :class:`~paddle_tpu.serving.EngineConfig` in the factory)."""

    max_queue: int = 64       # per-replica in-flight admission cap
    affinity_blocks: int = 2  # leading FULL prompt blocks hashed into the
                              # affinity key: requests sharing at least
                              # this much prefix co-locate.  Shorter
                              # prompts hash the full blocks they have;
                              # prompts under one block have no key and
                              # route least-loaded.
    vnodes: int = 16          # ring points per replica (smoother spread
                              # + smaller remap slice on replica death)
    drain_timeout_s: float = 5.0  # shutdown(): grace for in-flight work
    # flight recorder (ISSUE 8): None keeps the bounded per-replica
    # event rings (cheap, always on) but writes no post-mortem bundles;
    # a directory enables atomic bundle dumps on anomaly triggers
    flight_dir: Optional[str] = None
    flight: Optional[FlightRecorder] = None  # pre-built recorder wins
                                             # over flight_dir
    # deterministic fault injection (ISSUE 12): a frozen FaultPlan
    # schedules named faults by (replica, engine-step); the router
    # builds one FaultInjector per replica index (surviving supervisor
    # rebuilds, so each plan entry fires exactly once per chaos run)
    fault_plan: Optional[FaultPlan] = None
    # metrics history + alerting (ISSUE 14): None = defaults.  The
    # router builds ONE HistoryStore + AlertEngine over the shared
    # registry when the engines' EngineConfig.history gate is on
    # (refused when heterogeneous); alert_rules=None evaluates the
    # default serving rule set (pool exhaustion, goodput burn, compile
    # storms, restart/quarantine churn, ...)
    history: Optional[HistoryConfig] = None
    alert_rules: Optional[AlertRuleSet] = None
    # prefill/decode disaggregation (ISSUE 20): the EXPECTED per-replica
    # role list (``["prefill", "decode", ...]`` — parse_roles builds it
    # from the ``--roles prefill:N,decode:M`` CLI form).  Roles live on
    # each engine's EngineConfig.role; this field is the deployment
    # assertion — a mismatch against the engines actually built fails
    # loudly at router construction instead of silently mis-routing.
    # None = accept whatever the engines declare (all-unified legacy).
    roles: Optional[Sequence[str]] = None


def parse_roles(spec: str) -> List[str]:
    """Parse the ``--roles`` CLI form: ``"prefill:1,decode:2"`` →
    ``["prefill", "decode", "decode"]`` (replica index order follows the
    spec left to right).  Accepts ``unified`` counts too."""
    out: List[str] = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        name, _, count = part.partition(":")
        name = name.strip()
        if name not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"unknown role {name!r} in --roles (expected "
                "unified|prefill|decode)")
        try:
            n = int(count) if count.strip() else 1
        except ValueError:
            raise ValueError(f"bad replica count in --roles part {part!r}")
        if n < 0:
            raise ValueError(f"negative replica count in --roles {part!r}")
        out.extend([name] * n)
    if not out:
        raise ValueError(f"--roles {spec!r} names no replicas")
    return out


def _build_ring(dp: int, vnodes: int,
                weights: Optional[Dict[int, float]] = None) -> List:
    """Consistent-hash ring: ``vnodes`` points per replica, sorted by
    the 64-bit prefix of each vnode's SHA-256.  ``weights`` (ISSUE 16,
    the cache-aware rebalancing actuator) scales a replica's vnode count
    — weight 2.0 doubles the key space routed to it, 0.5 halves it;
    every replica keeps at least one vnode so it never silently leaves
    the ring.  Vnode hashes depend only on ``(replica, j)``, so
    reweighting MOVES no surviving vnode: only the added/removed points
    remap keys."""
    weights = weights or {}
    return sorted(
        (int.from_bytes(hashlib.sha256(
            f"paddle_tpu.fleet.replica.{i}.{j}".encode()).digest()[:8],
            "big"), i)
        for i in range(dp)
        for j in range(max(1, int(round(max(1, vnodes)
                                        * weights.get(i, 1.0))))))


def _key_int(hashes: List[bytes]) -> int:
    """Ring position of an affinity key: the 64-bit prefix of the
    deepest leading-block chain hash."""
    return int.from_bytes(hashes[-1][:8], "big")


def _ring_walk(ring: List, ring_keys: List[int], key_int: int,
               eligible: set) -> Optional[int]:
    """First ring point clockwise of ``key_int`` owned by an eligible
    replica index.  Skipping ineligible vnodes (instead of rebuilding
    the ring) is what makes the hash consistent: a dead replica only
    remaps ITS keys."""
    n = len(ring)
    start = bisect.bisect_left(ring_keys, key_int)
    for step in range(n):
        _, idx = ring[(start + step) % n]
        if idx in eligible:
            return idx
    return None


def affinity_replica_index(prompt_ids, dp: int, block_size: int,
                           affinity_blocks: Optional[int] = None,
                           vnodes: Optional[int] = None) -> Optional[int]:
    """Pure routing preview (no engines): the replica index a prompt's
    affinity key maps to on a healthy dp-replica ring, or ``None`` when
    the prompt has no full block (those route least-loaded).  Benchmarks
    and capacity planning use this to predict placement; it shares the
    chain hash, ring construction, and walk with
    :meth:`FleetRouter.submit`.  The defaults mirror ``FleetConfig()`` —
    for a fleet built with non-default knobs pass them explicitly, or
    use :meth:`FleetRouter.predict_replica`, which reads the live
    config."""
    cfg = FleetConfig()
    if affinity_blocks is None:
        affinity_blocks = cfg.affinity_blocks
    if vnodes is None:
        vnodes = cfg.vnodes
    hashes = prefix_chain_hashes(prompt_ids, block_size,
                                 max_blocks=affinity_blocks)
    if not hashes:
        return None
    ring = _build_ring(dp, vnodes)
    return _ring_walk(ring, [k for k, _ in ring], _key_int(hashes),
                      set(range(dp)))


class SubmitHandle:
    """One in-flight request as the router, the owning replica's engine
    thread, and the caller all see it.  ``req`` is the engine-side
    :class:`~paddle_tpu.serving.Request` once the replica admits it;
    ``done`` covers the admission-less terminal paths (cancelled before
    admission, or the owning engine thread died).  ``event`` is an
    optional waker the HTTP frontend attaches (an ``asyncio.Event`` set
    via ``call_soon_threadsafe``); direct callers poll instead."""

    __slots__ = ("rid", "prompt_ids", "sampling", "priority",
                 "prefix_hashes", "req", "done", "cancel_reason", "event",
                 "replica", "slo_ms", "retryable", "kv_run",
                 "resume_tokens", "arrival")

    def __init__(self, rid, prompt_ids: List[int],
                 sampling: Optional[SamplingParams] = None,
                 priority: int = 0, event=None,
                 slo_ms: Optional[float] = None,
                 retryable: bool = False):
        self.rid = rid
        self.prompt_ids = [int(t) for t in prompt_ids]
        self.sampling = sampling or SamplingParams()
        self.priority = priority
        self.slo_ms = slo_ms
        # ISSUE 12: opt-in transparent retry-from-scratch when the
        # owning replica dies mid-stream — greedy recompute regenerates
        # the already-delivered tokens identically, so the supervisor
        # may re-dispatch instead of failing with replica_failed
        self.retryable = bool(retryable)
        self.prefix_hashes: Optional[List[bytes]] = None  # router-stamped
        self.req = None                  # engine Request, set by engine thread
        self.done = False                # terminal without admission
        self.cancel_reason: Optional[FinishReason] = None
        self.event = event
        self.replica: Optional["EngineReplica"] = None
        # prefill→decode migration state (ISSUE 20), router-stamped at
        # the hand-off: the exported KV run the recipient imports before
        # re-admission, the already-emitted tokens that seed the new
        # engine Request, and the original arrival stamp (so e2e latency
        # spans the WHOLE request, not just its post-migration life)
        self.kv_run = None
        self.resume_tokens: Optional[List[int]] = None
        self.arrival: Optional[float] = None

    @property
    def finished(self) -> bool:
        return self.done or (self.req is not None and self.req.finished)

    @property
    def output_tokens(self) -> List[int]:
        return list(self.req.output_tokens) if self.req is not None else []

    @property
    def finish_reason(self) -> Optional[str]:
        if self.req is not None and self.req.finish_reason is not None:
            return self.req.finish_reason.value
        if self.done:
            return (self.cancel_reason.value if self.cancel_reason
                    else FinishReason.ABORT.value)
        return None


class EngineReplica:
    """One :class:`EngineCore` + its engine thread + the PR 3
    bounded-queue bridge, instantiated per fleet replica.

    The engine is NOT thread-safe and its jitted steps block, so each
    replica runs its own background thread; callers talk to it only
    through the bounded ``submit_q`` / ``abort_q`` and the append-only
    per-request state (safe under the GIL).  The replica's ``handles``
    dict (rid → handle) is its in-flight set: admission counts it,
    engine death marks every entry done, and the engine thread evicts
    entries as their requests finish (also evicting the router's
    owner-map entry — bounded maps, no long-server leak)."""

    def __init__(self, index: int, engine: EngineCore, max_queue: int,
                 notify: Callable[["EngineReplica"], None],
                 on_finish: Callable[[object, "EngineReplica"], None]):
        self.index = index
        self.engine = engine
        self.max_queue = max(1, max_queue)
        self.submit_q: "queue.Queue" = queue.Queue(maxsize=self.max_queue)
        # aborts are bounded by in-flight requests; 2x leaves room for
        # drain-time aborts racing handler-deadline aborts
        self.abort_q: "queue.Queue" = queue.Queue(
            maxsize=2 * self.max_queue + 8)
        self.wake = threading.Event()
        self.handles: Dict[object, SubmitHandle] = {}  # rid -> handle;
        # bounded by max_queue (try_submit refuses past the cap) and
        # evicted on finish by the engine thread
        # engine-thread task inbox (ISSUE 20): callables other threads
        # post() to run ON this replica's engine thread — the pool and
        # device tensors are engine-thread-only, so cross-replica work
        # (hot-prefix migration exports/imports) rides this queue
        # instead of touching the engine from a foreign thread
        self.task_q: "queue.Queue" = queue.Queue(maxsize=64)
        self.thread: Optional[threading.Thread] = None
        self.error: Optional[str] = None
        self.flight: Optional[FlightRecorder] = None  # router-stamped
        self._stop = False
        # --- self-healing surface (ISSUE 12) -------------------------------
        # supervised: a FleetSupervisor owns this replica's failure
        # handling — on death the handle set is LEFT IN PLACE for the
        # supervisor to claim (re-dispatch / replica_failed triage)
        # instead of being terminally marked here
        self.supervised = False
        # unhealthy: excluded from routing while the engine thread is
        # still alive (watchdog stall, quarantine); `healthy` is the
        # routing eligibility the router consults
        self.unhealthy = False
        self.watchdog = None          # StepWatchdog, supervisor-armed
        self.steps_done = 0           # completed eng.step() calls — the
        # stall detector's progress signal (GIL-atomic increments)
        self.stall = None             # (steps_done, t) stamped by the
        # watchdog's on-fire handler; cleared when progress resumes
        # notify/on_finish are scoped to THIS replica: the frontend
        # wakes only the handlers whose requests this replica owns (so
        # wakeup work per step stays per-replica instead of dp x
        # fleet-wide), and an owner-map eviction names its replica so a
        # stale eviction can never drop another replica's entry
        self._notify = lambda: notify(self)
        self._on_finish = lambda rid: on_finish(rid, self)

    # --- caller-side surface ------------------------------------------------
    @property
    def alive(self) -> bool:
        return (self.thread is not None and self.thread.is_alive()
                and self.error is None)

    @property
    def healthy(self) -> bool:
        """Routing eligibility: a live engine thread that is neither
        watchdog-stalled nor quarantined (ISSUE 12)."""
        return self.alive and not self.unhealthy

    @property
    def role(self) -> str:
        """The replica's disaggregation role (ISSUE 20): ``prefill`` /
        ``decode`` specialist or ``unified`` (the default).  Read from
        the engine's config so supervisor rebuilds (same factory, same
        config) keep the role automatically."""
        cfg = getattr(self.engine, "engine_config", None)
        return getattr(cfg, "role", "unified") or "unified"

    def post(self, fn: Callable[[], None]) -> bool:
        """Enqueue ``fn`` to run on this replica's engine thread (next
        loop iteration).  False when the bounded inbox is full — posted
        work is best-effort by contract (callers re-post or drop)."""
        try:
            self.task_q.put_nowait(fn)
        except queue.Full:  # swallow-ok: surfaced as the False return —
            # the documented best-effort contract (callers re-post or
            # drop and count on their side)
            return False
        self.wake.set()
        return True

    @property
    def in_flight(self) -> int:
        return len(self.handles)

    def start(self) -> None:
        self.thread = threading.Thread(
            target=self._loop, name=f"serving-engine-{self.index}",
            daemon=True)
        self.thread.start()

    def try_submit(self, handle: SubmitHandle) -> bool:
        """Admit ``handle`` onto this replica, or refuse (cap hit /
        dead).  The handle enters ``handles`` BEFORE the queue so the
        in-flight count can never undercount a queued request."""
        if not self.healthy or self._stop \
                or self.in_flight >= self.max_queue:
            return False
        self.handles[handle.rid] = handle
        try:
            self.submit_q.put_nowait(handle)
        except queue.Full:
            if self.handles.pop(handle.rid, None) is None:
                # a death sweep claimed the handle while it was briefly
                # visible: it is being terminated, not reroutable
                return True
            return False
        self.wake.set()
        if not self.alive:
            # the engine thread died between the liveness check and the
            # hand-off.  Ownership rule: whoever POPS the handle from
            # ``handles`` owns its fate (dict.pop is the atomic claim).
            # If WE win the pop, the terminal sweep can never touch this
            # handle again, so reclaiming + refusing is safe and the
            # router retries elsewhere.  If the sweep won, it marks the
            # handle done (terminal, like death right after admission) —
            # report it submitted.
            if self.handles.pop(handle.rid, None) is not None:
                return False
        return True

    def request_abort(self, rid, reason: FinishReason) -> None:
        h = self.handles.get(rid)
        if h is not None and h.cancel_reason is None:
            h.cancel_reason = reason
        try:
            self.abort_q.put_nowait((rid, reason))
        except queue.Full:
            pass  # swallow-ok: sized to 2x the in-flight bound; a drop only delays cleanup until the next abort/drain sweep
        self.wake.set()

    def request_stop(self) -> None:
        self._stop = True
        self.wake.set()

    def join(self, timeout: float = 10.0) -> None:
        if self.thread is not None:
            self.thread.join(timeout)

    # --- engine thread ------------------------------------------------------
    def _loop(self) -> None:
        eng = self.engine
        try:
            while True:
                self._drain_submissions()
                self._drain_aborts()
                self._drain_tasks()
                self._evict_finished()
                if self._stop and not eng.scheduler.has_work():
                    break
                if eng.scheduler.has_work():
                    # local read: FleetSupervisor.close() nulls the
                    # attribute from its own thread while we step
                    wd = self.watchdog
                    if wd is not None:
                        # supervisor-armed step watchdog (ISSUE 12): a
                        # wedged step marks this replica unhealthy the
                        # moment the section expires
                        with wd.watch(f"engine-step-r{self.index}"):
                            eng.step()
                    else:
                        eng.step()
                    self.steps_done += 1
                    self._notify()
                else:
                    self.wake.wait(timeout=0.02)
                    self.wake.clear()
        except Exception:
            # fail loudly but leave no handler hanging and no block held
            err = traceback.format_exc()
            if self.flight is not None:
                # post-mortem BEFORE the aborts below: the bundle then
                # captures the dying requests' timelines while they are
                # still in flight, plus the last-K events of THIS
                # replica's ring (fired once per replica).  Written
                # BEFORE ``self.error`` flips ``alive`` False, so a
                # watcher that observes the death always finds the
                # bundle already on disk — never a dead replica whose
                # post-mortem is still being serialized.
                try:
                    self.flight.trigger("engine_death",
                                        replica=str(self.index),
                                        detail=err)
                except Exception:
                    pass  # swallow-ok: telemetry must never mask the death handling
            self.error = err
            if not (self.supervised and not self._stop):
                # unsupervised (or draining) death: abort everything so
                # no block is held.  Under a supervisor the engine is
                # torn down wholesale and its in-flight requests are
                # triaged for RE-DISPATCH — an abort here would finish
                # them out from under the supervisor's claim.
                for req in list(eng.requests.values()):
                    eng.abort_request(req.request_id)
        finally:
            if self.supervised and self.error is not None \
                    and not self._stop:
                # supervised death (ISSUE 12): leave the handle set in
                # place — the FleetSupervisor claims it (dict.pop is
                # the atomic ownership rule) and re-dispatches or fails
                # each request; marking them done here would lose the
                # queued-but-unstarted work a self-healing fleet must
                # preserve
                pass
            else:
                for rid, h in list(self.handles.items()):
                    if self.handles.pop(rid, None) is None:
                        # a racing try_submit reclaimed it (atomic pop
                        # wins ownership): it is being re-routed — not
                        # ours to end
                        continue
                    h.done = True
                    if h.req is None:
                        # never admitted: the engine's finish path will
                        # not close this timeline — do it here so it
                        # moves to the tracker's bounded recent ring
                        eng._lc(rid, _lc.EV_FINISH, reason="abort",
                                error="engine thread exited before "
                                      "admission")
                    self._on_finish(rid)
            self._notify()

    def _drain_submissions(self) -> None:
        while True:
            try:
                h = self.submit_q.get_nowait()
            except queue.Empty:
                return  # swallow-ok: Empty IS the loop exit condition, not a fault
            if self.handles.get(h.rid) is not h:
                # the supervisor claimed this handle off a stalled/dying
                # incarnation of this replica (ISSUE 12) — it has been
                # re-dispatched elsewhere and is no longer ours to admit
                # OR terminate (presence in ``handles`` is the ownership
                # rule)
                continue
            if h.cancel_reason is not None or self._stop:
                # deadline fired (or drain began) before admission: the
                # request never enters the scheduler (timeline closed
                # here — no engine finish path will ever see it)
                h.done = True
                self.engine._lc(
                    h.rid, _lc.EV_FINISH,
                    reason=(h.cancel_reason.value if h.cancel_reason
                            else FinishReason.TIMEOUT.value))
                self._notify()
                continue
            if h.kv_run is not None:
                # prefill→decode migration (ISSUE 20): admit the donor's
                # exported KV into this pool BEFORE re-admission, so the
                # scheduler's prefix probe finds the whole computed
                # prompt cached.  Best-effort by contract: a refused or
                # failed import degrades to re-prefill — the prompt
                # tokens always travel with the handle.
                try:
                    self.engine.import_kv_run(h.kv_run)
                except Exception:
                    pass  # swallow-ok: import failure degrades to re-prefill; losing the request here would be the real bug
                h.kv_run = None
            req = self.engine.add_request(
                h.prompt_ids, sampling=h.sampling, request_id=h.rid,
                priority=h.priority, trace_id=str(h.rid),
                prefix_hashes=h.prefix_hashes, slo_ms=h.slo_ms,
                resume_tokens=h.resume_tokens)
            if h.arrival is not None:
                # the migrated request's e2e span starts at its ORIGINAL
                # arrival, not at re-admission (perf_counter is
                # CLOCK_MONOTONIC machine-wide, so the stamp transfers
                # across localhost worker processes too)
                req.arrival_time = h.arrival
                h.arrival = None
            h.resume_tokens = None
            h.req = req

    def _drain_tasks(self) -> None:
        """Run posted engine-thread tasks (ISSUE 20 hot-prefix
        migration).  Best-effort: a failing task must not kill the
        engine thread that serves live traffic."""
        while True:
            try:
                fn = self.task_q.get_nowait()
            except queue.Empty:
                return  # swallow-ok: Empty IS the loop exit condition, not a fault
            try:
                fn()
            except Exception:
                pass  # swallow-ok: posted tasks are best-effort cache work; a failure must never tear down the serving thread

    def _drain_aborts(self) -> None:
        did = False
        while True:
            try:
                rid, reason = self.abort_q.get_nowait()
            except queue.Empty:
                break  # swallow-ok: Empty IS the loop exit condition, not a fault
            if self.engine.abort_request(rid, reason):
                did = True
            else:
                h = self.handles.get(rid)
                if h is not None and h.req is None:
                    h.done = True
                    self.engine._lc(rid, _lc.EV_FINISH,
                                    reason=reason.value)
                    did = True
        if did:
            self._notify()

    def _evict_finished(self) -> None:
        """Drop finished requests from the in-flight set (and the
        router's owner map) — this is what keeps both maps bounded and
        what the satellite bugfix relies on: an abort can only be routed
        while the request is actually live on this replica."""
        for rid, h in list(self.handles.items()):
            if h.done or (h.req is not None and h.req.finished):
                self.handles.pop(rid, None)
                self._on_finish(rid)


class FleetRouter:
    """N engine replicas behind one prefix-affinity routing decision.

    Construction: pass pre-built engines (``FleetRouter(engines)``) or
    use :meth:`build` with an ``engine_factory(i, registry)`` that
    constructs replica ``i``'s :class:`EngineCore` on the shared
    registry (conventionally with ``metrics_labels={"replica": str(i)}``
    so /metrics separates the replicas).  Each replica needs its OWN
    model instance: the engine swaps parameter values during its traced
    step, so two engine threads must never share module objects.

    ``start()`` spawns the engine threads; ``submit()`` routes;
    ``shutdown()`` drains the whole fleet.  :meth:`from_engine` wraps a
    single engine as a fleet of one — the dp=1 compatibility path the
    HTTP frontend uses when handed a bare ``EngineCore``."""

    def __init__(self, engines: Sequence[EngineCore],
                 config: Optional[FleetConfig] = None,
                 registry: Optional[MetricsRegistry] = None):
        if not engines:
            raise ValueError("a fleet needs at least one engine replica")
        self.cfg = config or FleetConfig()
        self.engines: List[EngineCore] = list(engines)
        bs = {e.block_size for e in self.engines}
        if len(bs) != 1:
            raise ValueError(
                f"all replicas must share one block_size (affinity hashes "
                f"are computed once, fleet-wide); got {sorted(bs)}")
        self.block_size = self.engines[0].block_size
        mps = {e.mp for e in self.engines}
        if len(mps) != 1:
            raise ValueError(f"replicas disagree on mp degree: {sorted(mps)}")
        self.mp = self.engines[0].mp
        self._notify_cb: Callable[[Optional[EngineReplica]], None] = \
            lambda replica=None: None
        if len(self.engines) > 1:
            # replicas sharing one registry MUST carry distinct metric
            # labels — identical (name, labels) keys get-or-create the
            # SAME series, so every "per-replica" counter would silently
            # double-count fleet totals
            seen: Dict[int, set] = {}
            for e in self.engines:
                lbls = tuple(sorted(e.metrics.labels.items()))
                reg_seen = seen.setdefault(id(e.metrics.registry), set())
                if lbls in reg_seen:
                    raise ValueError(
                        "replicas sharing a metrics registry need "
                        "distinct metrics_labels (e.g. EngineCore("
                        "metrics_labels={'replica': str(i)})); duplicate "
                        f"label set {dict(lbls)}")
                reg_seen.add(lbls)
        self.registry = (registry if registry is not None
                         else self.engines[0].metrics.registry)
        # --- request-lifecycle tracing + flight recorder (ISSUE 8) ----------
        # ONE tracker for the whole fleet: the router's routing events
        # (caller thread) and each replica's execution events (engine
        # thread) land in the same per-request timeline, keyed by rid —
        # the router's duplicate-rid admission check guarantees
        # uniqueness across replicas.  Replicas are rebound before any
        # request exists, with their ring/ trigger identity pinned to
        # the replica INDEX (metrics labels are free-form and need not
        # match it).  The engines' lifecycle knobs must agree — the
        # router's own events ride the same tracker, so a per-replica
        # disagreement would silently half-apply (e.g. a gated-off
        # engine never closing timelines the router opened).
        gates = {e.engine_config.lifecycle_events for e in self.engines}
        samples = {e.engine_config.decode_event_sample
                   for e in self.engines}
        if len(gates) != 1 or len(samples) != 1:
            raise ValueError(
                "replicas disagree on lifecycle config: "
                f"lifecycle_events={sorted(gates)}, "
                f"decode_event_sample={sorted(samples)} — the fleet "
                "shares ONE tracker, so every replica must use the "
                "same EngineConfig knobs")
        cstats = {e.engine_config.cache_stats for e in self.engines}
        if len(cstats) != 1:
            # same failure shape as the gates below: /v1/debug/cache
            # reports fleet-wide, so a half-tracked fleet would read as
            # "replica i has no cache pressure"
            raise ValueError(
                f"replicas disagree on cache_stats={sorted(cstats)}; "
                "the cache debug surface reports fleet-wide, so every "
                "replica must use the same EngineConfig knob")
        sprof = {e.engine_config.step_profile for e in self.engines}
        if len(sprof) != 1:
            # same failure shape as the lifecycle gate: a half-profiled
            # fleet would read as "replica i never retraced / never
            # padded" on /v1/debug/compiles and in flight bundles
            raise ValueError(
                f"replicas disagree on step_profile={sorted(sprof)}; "
                "the debug surfaces report fleet-wide, so every "
                "replica must use the same EngineConfig knob")
        audits = {e.audit.cfg for e in self.engines}
        if len(audits) != 1:
            # a half-audited fleet would read as "replica i never
            # diverged" on /v1/debug/audit and silently skip the oracle
            # on some replicas — refuse heterogeneous audit configs
            raise ValueError(
                "replicas disagree on audit config "
                f"({sorted(repr(a) for a in audits)}); the audit "
                "surface reports fleet-wide, so every replica must use "
                "the same EngineConfig.audit")
        arts = {id(e.aot_artifact) for e in self.engines}
        if len(arts) != 1:
            # the compile-once contract (ISSUE 15) is per ARTIFACT
            # OBJECT: each loaded Exported caches its compiled
            # executable, so per-replica loads would compile every
            # program dp times (and a mixed AOT/traced fleet would hide
            # retraces behind the AOT replicas' zero counters).  Build
            # every replica with the SAME EngineConfig.aot object.
            raise ValueError(
                "replicas disagree on the AOT artifact: a fleet shares "
                "ONE loaded AotArtifact (load once, pass the same "
                "EngineConfig.aot object to every replica — not "
                "per-replica aot_path loads)")
        # remembered for the supervisor: _rebuild rebinds this artifact
        # onto replacement engines so a restart reuses the fleet's warm
        # compiled executables (zero post-restart traces)
        self.aot_artifact = self.engines[0].aot_artifact
        gate = gates.pop()
        explicit = [e.engine_config.lifecycle for e in self.engines]
        if explicit[0] is not None and \
                all(t is explicit[0] for t in explicit):
            # every engine was built onto the SAME caller-supplied
            # tracker: adopt it — but its enabled flag must match the
            # engines' gate, or the router would open timelines (enabled
            # tracker) that the gated-off engines never close
            if explicit[0].enabled != gate:
                raise ValueError(
                    f"EngineConfig.lifecycle tracker has enabled="
                    f"{explicit[0].enabled} but the engines set "
                    f"lifecycle_events={gate}; the two must agree")
            self.lifecycle = explicit[0]
        else:
            self.lifecycle = LifecycleTracker(
                registry=self.registry, enabled=gate,
                decode_sample=samples.pop())
        for i, eng in enumerate(self.engines):
            eng.set_lifecycle(self.lifecycle, replica=str(i))
        if self.cfg.flight is not None:
            self.flight = self.cfg.flight
            self.flight.bind_lifecycle(self.lifecycle)
        else:
            self.flight = FlightRecorder(
                registry=self.registry, lifecycle=self.lifecycle,
                config=FlightConfig(dump_dir=self.cfg.flight_dir))
        # per-replica step profilers (ISSUE 9): post-mortem bundles embed
        # the owning replica's last-K step records, keyed by the same
        # replica index the flight rings use
        self.flight.bind_step_profilers(
            {str(i): e.stepprof for i, e in enumerate(self.engines)})
        # cache-stat trackers (ISSUE 13): post-mortem bundles embed the
        # owning replica's last-K pool-timeline samples, same keying
        self.flight.bind_cache_trackers(
            {str(i): e.cachestat for i, e in enumerate(self.engines)})
        # numerics auditors (ISSUE 10): divergence/nonfinite triggers and
        # .npz repros carry the replica INDEX, matching the flight rings
        for i, e in enumerate(self.engines):
            e.audit.bind_flight(self.flight, replica=str(i))
        # deterministic fault injection (ISSUE 12): one injector per
        # replica INDEX, owned here so the exactly-once bookkeeping
        # survives supervisor engine rebuilds
        self.fault_injectors: Dict[int, FaultInjector] = {}
        if self.cfg.fault_plan is not None and self.cfg.fault_plan.faults:
            for i, eng in enumerate(self.engines):
                fi = FaultInjector(self.cfg.fault_plan, replica=str(i),
                                   lifecycle=self.lifecycle,
                                   registry=self.registry)
                self.fault_injectors[i] = fi
                eng.set_fault_injector(fi)
        # self-healing supervisor (ISSUE 12): attached via
        # FleetSupervisor(router, ...); None = legacy semantics (a dead
        # replica stays excluded until an operator acts)
        self.supervisor = None
        self._engine_factory = None  # remembered by build() so the
        # supervisor can rebuild replicas without re-plumbing a factory
        self.replicas: List[EngineReplica] = [
            EngineReplica(i, eng, self.cfg.max_queue,
                          notify=self._notify, on_finish=self._release)
            for i, eng in enumerate(self.engines)
        ]
        for r in self.replicas:
            r.flight = self.flight
        # --- prefill/decode disaggregation (ISSUE 20) ------------------------
        # roles are a ROUTING policy, deliberately NOT one of the
        # homogeneity gates above: a mixed prefill/decode fleet is the
        # point.  FleetConfig.roles (when set) is a deployment
        # assertion — it must match what the engines actually declare.
        self.roles: List[str] = [r.role for r in self.replicas]
        if self.cfg.roles is not None:
            declared = [str(x) for x in self.cfg.roles]
            if declared != self.roles:
                raise ValueError(
                    f"FleetConfig.roles={declared} does not match the "
                    f"engines' declared roles {self.roles}; the role an "
                    "engine was built with (EngineConfig.role) is "
                    "authoritative — fix the factory or the fleet spec")
        if "decode" in self.roles and \
                not any(x in ("prefill", "unified") for x in self.roles):
            raise ValueError(
                "a fleet of only decode specialists can never admit a "
                "request (admission routes to prefill/unified replicas); "
                "add at least one prefill or unified replica")
        self._handoff_metrics = register_handoff_metrics(self.registry)
        self._owner: Dict[object, EngineReplica] = {}  # rid -> replica;
        # bounded by dp * max_queue (entries exist only while the request
        # is in flight on its replica) — evicted on finish/death
        self._submit_lock = threading.Lock()  # serializes submitters:
        # the duplicate-rid check and the owner-map write must be one
        # atomic step when several caller threads submit concurrently
        self._ids = itertools.count(1)
        self._draining = False
        # consistent-hash ring: vnodes per replica, clockwise walk skips
        # dead replicas so only the dead replica's keys remap
        self._ring: List = _build_ring(len(self.replicas), self.cfg.vnodes)
        self._ring_keys = [k for k, _ in self._ring]
        # --- serving_fleet_* observability ---------------------------------
        g, c = self.registry.gauge, self.registry.counter
        self._g_replicas = g("serving_fleet_replicas",
                             "configured data-parallel replica count")
        self._g_alive = g("serving_fleet_replicas_alive",
                          "replicas with a live engine thread")
        self._g_in_flight = g("serving_fleet_in_flight",
                              "in-flight requests fleet-wide")
        self._g_cache_imbalance = g(
            "serving_fleet_cache_imbalance",
            "max - min per-replica cached-token ratio (prefix-affinity "
            "placement imbalance; the cache-aware rebalancing signal)")
        self._affinity_hit = c(
            "serving_fleet_affinity_hit_total",
            "requests routed to their prefix-affinity replica")
        self._fallback = c(
            "serving_fleet_fallback_routed_total",
            "requests routed least-loaded (no key, or affinity target "
            "saturated/unhealthy)")
        self._g_replica_alive = {
            r.index: g("serving_fleet_replica_alive",
                       "1 while the replica's engine thread is live",
                       replica=str(r.index))
            for r in self.replicas}
        self._g_replica_in_flight = {
            r.index: g("serving_fleet_replica_in_flight",
                       "in-flight requests on the replica",
                       replica=str(r.index))
            for r in self.replicas}
        self._g_replica_occupancy = {
            r.index: g("serving_fleet_replica_occupancy",
                       "replica KV-pool occupancy fraction",
                       replica=str(r.index))
            for r in self.replicas}
        self._g_replica_queue = {
            r.index: g("serving_fleet_replica_queue_depth",
                       "replica scheduler waiting-queue depth",
                       replica=str(r.index))
            for r in self.replicas}
        self._g_replicas.set(len(self.replicas))
        self.sample_gauges()
        # --- scrape-time collection + metrics history (ISSUE 14) ------------
        # the fleet gauges above are DERIVED from live replica state, so
        # their refresh rides a registry collect hook: /metrics scrapes,
        # push-gateway exports, JSON snapshots and the history sampler
        # all observe freshly collected values (previously only the HTTP
        # /metrics handler refreshed them — the push gateway exported
        # stale fleet gauges)
        hist_gates = {e.engine_config.history for e in self.engines}
        if len(hist_gates) != 1:
            raise ValueError(
                f"replicas disagree on history={sorted(hist_gates)}; "
                "the fleet samples ONE shared history, so every replica "
                "must use the same EngineConfig knob")
        self.history: Optional[HistoryStore] = None
        self.alerts: Optional[AlertEngine] = None
        if hist_gates.pop():
            # ONE fleet-wide store: every replica's engine thread ticks
            # the same sampler, and the alert engine evaluates the
            # threshold / rate / SLO burn-rate rules after every sample
            self.history = HistoryStore(self.registry,
                                        config=self.cfg.history)
            self.alerts = AlertEngine(
                self.history, rules=self.cfg.alert_rules,
                registry=self.registry, lifecycle=self.lifecycle,
                flight=self.flight)
            for eng in self.engines:
                eng.set_history(self.history)
        # register the hook LAST, after everything above that can raise
        # (gate validation, history/alert series creation on a shared
        # registry near its max_series cap): an aborted __init__ never
        # runs stop(), so a hook registered earlier would keep walking
        # this half-built router's replicas on every later scrape of a
        # caller-owned registry
        self._remove_collect_hook = self.registry.add_collect_hook(
            self.sample_gauges)

    # --- constructors -------------------------------------------------------
    @classmethod
    def build(cls, engine_factory: Callable[[int, MetricsRegistry],
                                            EngineCore],
              dp: int, config: Optional[FleetConfig] = None,
              registry: Optional[MetricsRegistry] = None) -> "FleetRouter":
        """Build a dp-replica fleet on one shared registry.  The factory
        gets ``(replica_index, registry)`` and should construct the
        engine with ``registry=registry,
        metrics_labels={"replica": str(index)}``."""
        if dp < 1:
            raise ValueError(f"dp must be >= 1, got {dp}")
        registry = (registry if registry is not None
                    else MetricsRegistry(max_series=4096))
        engines = [engine_factory(i, registry) for i in range(dp)]
        router = cls(engines, config=config, registry=registry)
        # the supervisor rebuilds crashed replicas through this exact
        # factory (same weights, same config — the factory must be
        # deterministic, e.g. seed before building the model)
        router._engine_factory = engine_factory
        return router

    @classmethod
    def from_engine(cls, engine: EngineCore,
                    max_queue: int = 64) -> "FleetRouter":
        """Wrap ONE pre-built engine as a fleet of one (the dp=1 compat
        path): the engine keeps its own registry and its ``serving_*``
        series stay unlabeled, exactly as before.  The ``serving_fleet_*``
        family IS added to that registry (dp=1 reports itself as a
        one-replica fleet — the selftest asserts it), so budget ~12
        extra series."""
        return cls([engine], config=FleetConfig(max_queue=max_queue))

    # --- lifecycle ----------------------------------------------------------
    @property
    def dp(self) -> int:
        return len(self.replicas)

    @property
    def alive(self) -> bool:
        return any(r.alive for r in self.replicas)

    @property
    def draining(self) -> bool:
        return self._draining

    def attach_supervisor(self, supervisor) -> None:
        """Bind a :class:`~paddle_tpu.serving.resilience.FleetSupervisor`
        (called by its constructor).  One supervisor per fleet."""
        if self.supervisor is not None:
            raise ValueError("a FleetSupervisor is already attached")
        self.supervisor = supervisor

    @property
    def restarting_count(self) -> int:
        """Replicas currently out of service that the attached
        supervisor will bring back (dead/unhealthy, not permanently
        excluded).  0 without a supervisor — the HTTP frontend uses this
        to distinguish 'restarting, Retry-After' from a hard 503."""
        sup = self.supervisor
        if sup is None or self._draining:
            return 0
        return sum(1 for r in self.replicas
                   if not r.healthy and r.index not in sup.excluded)

    @property
    def in_flight(self) -> int:
        return len(self._owner)

    def start(self,
              notify: Optional[Callable[[Optional[EngineReplica]], None]]
              = None) -> "FleetRouter":
        """Spawn every replica's engine thread.  ``notify(replica)`` is
        invoked (from engine threads) after any step/terminal transition
        of that replica — the HTTP frontend wakes the handlers whose
        requests it owns; direct callers poll."""
        if notify is not None:
            self._notify_cb = notify
        for r in self.replicas:
            if r.thread is None:
                r.start()
        self.sample_gauges()
        return self

    def begin_drain(self) -> None:
        """Stop admitting instantly (submit() raises FleetDown); running
        work keeps stepping until :meth:`stop`."""
        self._draining = True

    def stop(self, join_timeout: float = 10.0) -> None:
        """Stop + join every engine thread (each exits once its
        scheduler runs dry — callers abort stragglers first).  An
        attached supervisor is closed FIRST so no restart races the
        teardown."""
        if self.supervisor is not None:
            self.supervisor.close()
        for r in self.replicas:
            r.request_stop()
        for r in self.replicas:
            r.join(join_timeout)
        self.sample_gauges()
        # stop collecting from (and alerting on) a stopped fleet: the
        # registry may outlive the router, and a later scrape must not
        # walk retired replica objects
        self._remove_collect_hook()
        if self.alerts is not None:
            self.alerts.close()

    def shutdown(self, drain_timeout: Optional[float] = None) -> None:
        """Synchronous fleet-wide graceful drain (direct/non-HTTP use;
        the HTTP frontend orchestrates the same phases on its own loop):
        stop admission now, wait for in-flight work up to the deadline,
        abort stragglers through their owning replica, stop every engine
        thread.  Leaves zero pool occupancy on every replica."""
        self.begin_drain()
        deadline = time.monotonic() + (
            drain_timeout if drain_timeout is not None
            else self.cfg.drain_timeout_s)
        while self._owner and time.monotonic() < deadline:
            time.sleep(0.005)
        stragglers = list(self._owner)
        if stragglers:
            # drain-deadline overrun (ISSUE 8): capture the stragglers'
            # timelines BEFORE the aborts end them
            self.flight.trigger(
                "drain_overrun",
                detail=f"{len(stragglers)} request(s) still in flight "
                       f"at the drain deadline")
        for rid in stragglers:
            self.abort(rid, FinishReason.TIMEOUT)
        self.stop()

    # --- routing ------------------------------------------------------------
    def _notify(self, replica: Optional[EngineReplica] = None) -> None:
        # prefill/decode disaggregation (ISSUE 20): each replica calls
        # this from ITS engine thread right after every step, so this is
        # the safe (and rebuild-surviving — the supervisor constructs
        # replacement replicas with notify=self._notify) point to sweep
        # a prefill specialist for requests that just crossed the
        # first-token boundary and hand them to a decode specialist
        if replica is not None:
            self._migrate_first_tokens(replica)
        self._notify_cb(replica)

    def _migrate_first_tokens(self, donor: EngineReplica) -> None:
        """Sweep a prefill specialist for in-flight requests that have
        produced their first token and hand each off to a decode
        specialist.  Runs on the DONOR's engine thread (between steps),
        so reading/detaching its engine state is race-free."""
        if donor.role != "prefill" or not donor.healthy or self._draining:
            return
        for h in list(donor.handles.values()):
            req = h.req
            if (req is None or h.done or req.finished
                    or h.cancel_reason is not None
                    or req.first_token_time is None):
                continue
            self._handoff(donor, h)

    def _handoff(self, donor: EngineReplica, h: SubmitHandle) -> None:
        """Migrate one first-token request off ``donor``: export its
        computed prompt KV, detach it, and re-submit (run + generated
        tokens + original arrival stamp riding the handle) to the
        least-loaded healthy decode specialist.  Unified fallback: with
        no healthy decode specialist the request simply KEEPS decoding
        on the donor — a hand-off is an optimization, never a
        prerequisite.  If every specialist refuses admission the request
        is re-admitted on the donor with its KV still resident (the
        hashed prompt blocks park warm across detach), so no path loses
        the request."""
        targets = [r for r in self.replicas
                   if r is not donor and r.healthy and r.role == "decode"]
        if not targets:
            return
        targets.sort(key=lambda r: r.in_flight)
        rid = h.rid
        req = h.req
        t0 = time.perf_counter()
        try:
            run = donor.engine.export_kv_run(rid)
        except Exception:  # pragma: no cover - defensive
            run = None  # swallow-ok: an export failure degrades the hand-off to re-prefill at the destination; the request itself must still migrate or stay
        # atomic claim: if the donor's own sweep (finish/abort/death)
        # got here first, the handle is no longer ours to move
        if donor.handles.pop(rid, None) is not h:
            return
        h.resume_tokens = list(req.output_tokens)
        h.arrival = req.arrival_time
        h.kv_run = run
        # h.req deliberately KEEPS pointing at the detached (now frozen)
        # request object: pollers reading handle.req.output_tokens
        # mid-transit see the tokens generated so far; the recipient's
        # admission overwrites h.req with the live resumed request
        donor.engine.detach_request(rid)
        placed = None
        with self._submit_lock:
            for target in targets:
                h.replica = target
                self._owner[rid] = target
                if target.try_submit(h):
                    placed = target
                    break
                self._owner.pop(rid, None)
                h.replica = None
        if placed is None:
            # every decode specialist is at its admission cap: re-admit
            # on the donor.  We ARE the donor's engine thread, so this
            # is a direct re-add (its KV is still warm — resume is
            # near-free); known accepted race: an abort() arriving in
            # the claim→rewrite window is dropped and retried by the
            # caller's timeout path.
            with self._submit_lock:
                self._owner[rid] = donor
            h.replica = donor
            donor.handles[rid] = h
            h.req = donor.engine.add_request(
                h.prompt_ids, sampling=h.sampling, request_id=rid,
                priority=h.priority, trace_id=str(rid),
                prefix_hashes=h.prefix_hashes, slo_ms=h.slo_ms,
                resume_tokens=h.resume_tokens)
            if h.arrival is not None:
                h.req.arrival_time = h.arrival
            h.kv_run = None
            h.resume_tokens = None
            h.arrival = None
            return
        dt = time.perf_counter() - t0
        nblocks = len(run["blocks"]) if run else 0
        nbytes = int(run["payload"].nbytes) if run else 0
        self._handoff_metrics["total"].inc()
        self._handoff_metrics["seconds"].observe(dt)
        if nblocks:
            self._handoff_metrics["blocks"].observe(float(nblocks))
        self.lifecycle.event(
            rid, _lc.EV_KV_HANDOFF, src=str(donor.index),
            dst=str(placed.index), blocks=nblocks, bytes=nbytes,
            duration_ms=round(dt * 1000.0, 3))

    def _release(self, rid, replica: Optional[EngineReplica] = None) -> None:
        """Evict an owner-map entry.  A replica-side eviction names its
        replica and only drops the entry while it still points there —
        a stale eviction racing a re-route must not orphan the entry the
        router just wrote for another replica."""
        if replica is None or self._owner.get(rid) is replica:
            self._owner.pop(rid, None)

    def _ring_target(self, key_int: int,
                     eligible: List[EngineReplica]
                     ) -> Optional[EngineReplica]:
        """Consistent-hash affinity target among ``eligible`` replicas
        (shared :func:`_ring_walk`)."""
        idx = _ring_walk(self._ring, self._ring_keys, key_int,
                         {r.index for r in eligible})
        return None if idx is None else self.replicas[idx]

    def affinity_key(self, prompt_ids) -> Optional[List[bytes]]:
        """Leading-block chain hashes of the prompt (≤ affinity_blocks
        full blocks); ``None`` when the prompt has no full block."""
        hashes = prefix_chain_hashes(prompt_ids, self.block_size,
                                     max_blocks=self.cfg.affinity_blocks)
        return hashes or None

    def predict_replica(self, prompt_ids) -> Optional[int]:
        """Routing preview against THIS fleet's live config and ring
        (all replicas eligible): the replica index an unloaded, healthy
        fleet would pick, or ``None`` for a keyless (short) prompt."""
        hashes = self.affinity_key(prompt_ids)
        if hashes is None:
            return None
        return _ring_walk(self._ring, self._ring_keys, _key_int(hashes),
                          set(range(len(self.replicas))))

    @property
    def routing_counts(self) -> Dict[str, int]:
        """Public snapshot of the routing counters:
        ``{"affinity_hit": n, "fallback_routed": m}``."""
        return {"affinity_hit": int(self._affinity_hit.value),
                "fallback_routed": int(self._fallback.value)}

    def submit(self, handle: SubmitHandle) -> EngineReplica:
        """Route ``handle``: affinity target first, least-loaded eligible
        fallback.  Raises :class:`FleetDown` when no replica is live (or
        the fleet drains) and :class:`FleetSaturated` when every eligible
        replica is at its admission cap (per-replica 429 semantics: the
        fleet rejects only when ALL of them reject).  Thread-safe: a
        lock serializes submitters, so the duplicate-rid check, the
        owner-map write, and the replica hand-off are one atomic step
        (replica threads never take this lock — they only pop)."""
        if self._draining:
            raise FleetDown("fleet is draining")
        with self._submit_lock:
            if handle.rid in self._owner:
                # reject duplicates HERE, synchronously — letting the id
                # through would either silently orphan the first
                # request's owner-map entry (different replicas) or
                # raise inside the owning engine thread and kill the
                # whole replica (same replica).  Mirrors
                # EngineCore.add_request's own check.
                raise ValueError(
                    f"request id {handle.rid!r} is already in flight")
            eligible = [r for r in self.replicas if r.healthy]
            if not eligible:
                raise FleetDown("no live engine replica")
            # role-aware admission (ISSUE 20): new requests prefill, so
            # they route to prefill specialists (and unified replicas);
            # decode specialists only receive work via the first-token
            # hand-off.  A handle carrying resume_tokens is PAST its
            # first token (a supervisor re-dispatch recovered it mid-
            # hand-off or off a dead decode specialist): it routes to
            # decode/unified replicas — NEVER a prefill specialist.
            # When none is healthy it saturates instead of falling
            # back, so a supervised re-dispatch stays pending until the
            # restarted decode replica rejoins.  Fresh admissions DO
            # fall back to whatever is healthy (role is routing policy,
            # not capability — every engine runs the full pipeline).
            want = (("decode", "unified") if handle.resume_tokens
                    else ("prefill", "unified"))
            pool = [r for r in eligible if r.role in want]
            if not pool:
                if handle.resume_tokens:
                    raise FleetSaturated(
                        "no healthy decode/unified replica for a mid-"
                        "decode resume (prefill specialists are never "
                        "eligible)")
                pool = eligible
            # the timeline starts HERE, on the router/caller thread: a
            # per-request trace shows routing before any engine thread
            # touches the request.  Terminal rejects below finish the
            # timeline (into the bounded recent ring) so nothing leaks.
            self.lifecycle.event(
                handle.rid, _lc.EV_SUBMITTED, trace_id=str(handle.rid),
                prompt_tokens=len(handle.prompt_ids),
                slo_ms=handle.slo_ms)
            hashes = self.affinity_key(handle.prompt_ids)
            handle.prefix_hashes = hashes
            target = None
            if hashes is not None:
                target = self._ring_target(_key_int(hashes), pool)
            order: List[EngineReplica] = \
                [target] if target is not None else []
            order += [r for r in sorted(pool,
                                        key=lambda r: r.in_flight)
                      if r is not target]
            for r in order:
                # the owner-map entry is written BEFORE the queue
                # hand-off: once the replica can see the handle, its
                # finish/death eviction path must be able to find (and
                # pop) the entry — writing it after try_submit would let
                # that eviction race ahead and leave a permanently
                # leaked entry
                handle.replica = r
                self._owner[handle.rid] = r
                if r.try_submit(handle):
                    affinity = target is not None and r is target
                    if affinity:
                        self._affinity_hit.inc()
                    else:
                        self._fallback.inc()
                    self._g_in_flight.set(len(self._owner))
                    self.lifecycle.event(
                        handle.rid, _lc.EV_ROUTE, replica=str(r.index),
                        affinity=affinity,
                        keyed=hashes is not None,
                        in_flight=r.in_flight)
                    return r
                self._owner.pop(handle.rid, None)
                handle.replica = None
        if not any(r.healthy for r in self.replicas):
            # every refusal was a death race, not a cap: report the
            # fleet as down (HTTP 503), not saturated (429)
            self.lifecycle.event(handle.rid, _lc.EV_ADMISSION_REJECTED,
                                 reason="fleet_down")
            raise FleetDown("no live engine replica")
        self.lifecycle.event(handle.rid, _lc.EV_ADMISSION_REJECTED,
                             reason="saturated")
        raise FleetSaturated(
            f"all {len(pool)} eligible replica(s) at their "
            f"{self.cfg.max_queue}-request admission cap")

    def submit_request(self, prompt_ids,
                       sampling: Optional[SamplingParams] = None,
                       request_id=None, priority: int = 0,
                       slo_ms: Optional[float] = None,
                       retryable: bool = False) -> SubmitHandle:
        """Convenience for direct (non-HTTP) callers: build a handle,
        route it, return it.  Poll ``handle.finished`` /
        ``handle.output_tokens`` (or use :meth:`wait`)."""
        rid = request_id if request_id is not None else \
            f"fleet-{next(self._ids)}"
        handle = SubmitHandle(rid, list(prompt_ids), sampling=sampling,
                              priority=priority, slo_ms=slo_ms,
                              retryable=retryable)
        self.submit(handle)
        return handle

    def abort(self, rid, reason: FinishReason = FinishReason.ABORT) -> bool:
        """Route an abort to the replica that OWNS ``rid`` (the
        request→replica map; evicted on finish).  True if the request was
        still owned — an already-finished rid is a no-op."""
        owner = self._owner.get(rid)
        if owner is None:
            return False
        owner.request_abort(rid, reason)
        return True

    def wait(self, handles: Sequence[SubmitHandle],
             timeout: float = 120.0) -> None:
        """Block until every handle reaches a terminal state."""
        deadline = time.monotonic() + timeout
        for h in handles:
            while not h.finished:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"request {h.rid!r} not finished in {timeout}s")
                time.sleep(0.002)

    # --- observability ------------------------------------------------------
    def cached_token_ratios(self) -> Dict[str, Optional[float]]:
        """Per-replica prefix-cache hit ratio (hit/(hit+computed) over
        each replica's life; ``None`` before any prefill) — the rows the
        cache-imbalance gauge and ``/v1/debug/cache``'s fleet view are
        computed from."""
        return {str(r.index): r.engine.metrics.cached_token_ratio()
                for r in self.replicas}

    def cache_imbalance(self) -> Optional[float]:
        """max − min per-replica cached-token ratio (ISSUE 13): the
        rebalancing trigger signal — one replica's reuse LRU saturating
        while another idles shows up as this gap widening.  ``None``
        until two replicas have prefilled anything (a one-replica fleet
        reports 0.0 once it has data)."""
        vals = [v for v in self.cached_token_ratios().values()
                if v is not None]
        if not vals:
            return None
        return max(vals) - min(vals)

    def reweight_ring(self, weights: Dict[int, float]) -> None:
        """Rebuild the consistent-hash ring with per-replica vnode
        weights (ISSUE 16: the cache-aware rebalancing actuator turns
        the ``serving_fleet_cache_imbalance`` signal into routing
        pressure — a cold replica gets more vnodes so affinity keys
        migrate toward it).  Taken under the submit lock so no router
        thread ever walks a half-swapped ring; in-flight requests keep
        their placement (affinity only guides NEW admissions)."""
        with self._submit_lock:
            self._ring = _build_ring(len(self.replicas), self.cfg.vnodes,
                                     weights)
            self._ring_keys = [k for k, _ in self._ring]

    def sample_gauges(self) -> None:
        """Refresh the serving_fleet_* gauges from replica state (the
        HTTP frontend calls this on every /metrics scrape; direct
        callers, whenever they snapshot)."""
        self._g_alive.set(sum(1 for r in self.replicas if r.alive))
        self._g_in_flight.set(len(self._owner))
        imbalance = self.cache_imbalance()
        if imbalance is not None:
            self._g_cache_imbalance.set(imbalance)
        for r in self.replicas:
            self._g_replica_alive[r.index].set(1 if r.alive else 0)
            self._g_replica_in_flight[r.index].set(r.in_flight)
            self._g_replica_occupancy[r.index].set(
                r.engine.kv.occupancy())
            self._g_replica_queue[r.index].set(
                r.engine.scheduler.queue_depth)
