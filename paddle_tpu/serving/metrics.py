"""Serving metrics: request-level latency + scheduler/pool health.

Registry-backed (ISSUE 2): every counter / gauge / latency distribution
is a series in a :class:`~paddle_tpu.observability.MetricsRegistry`
(``serving_*`` namespace), so a serving process exposes TTFT/ITL
histograms and KV-occupancy gauges on the same Prometheus page as the
jit compile counters — while the legacy inspection surface
(``metrics.counters`` dict view, ``metrics.latency`` OpStat view, the
profiler-style ``summary()`` tables) is preserved exactly.

Tracked:

* **time-to-first-token** (admission-inclusive: arrival → first emitted
  token) and **inter-token latency** per request;
* **prefill / decode step** wall times;
* **queue depth**, **running-set size**, and **KV-pool occupancy** sampled
  once per engine step;
* counters: admitted, finished-by-reason (eos/length/abort), preemptions,
  recompute prefills, decode/prefill jit traces.

Per-op host times ride the dispatch **op-observer bus**
(``core/dispatch.add_op_timer``): ``install_dispatch_timer`` subscribes
alongside any active Profiler instead of the old first-owner-wins
``_set_op_timer`` slot, so Profiler + ServingMetrics coexist.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from ..observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from ..observability.tracer import SpanTracer, get_tracer
from ..profiler.statistic import HostOpRecorder, OpStat, summary_table

# how many raw per-step gauge samples to retain for inspection; the
# summary's avg/max/min come from exact streaming aggregates (registry
# Gauge), so a long-lived server's memory stays constant no matter how
# many steps run
GAUGE_WINDOW = 4096

# sub-second serving latencies: finer low end than the registry default
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_COUNTER_NAMES = (
    "requests_admitted",
    "requests_finished_eos",
    "requests_finished_length",
    "requests_finished_abort",
    "requests_finished_timeout",
    # ISSUE 12: quarantine-drain stragglers aborted through the live
    # engine with the supervisor's honest verdict
    "requests_finished_replica_failed",
    "admission_rejected",
    "preemptions",
    "recompute_prefills",
    "engine_steps",
    # prefix cache + chunked prefill (ISSUE 4)
    "prefix_cache_hit_tokens",    # prompt tokens restored by fork (free)
    "prefix_cache_miss_tokens",   # prompt tokens that needed compute
    "prefix_cache_evictions",     # cached blocks clobbered for allocation
    "prefill_tokens_computed",    # tokens the prefill programs actually ran
    "chunked_prefill_steps",      # chunk-program launches (vs one-shot)
    # SLO goodput pair (ISSUE 8): slo counts every finished request that
    # carried a per-request slo_ms; slo_good the subset that met it
    "slo",
    "slo_good",
    # unified ragged step (ISSUE 11): packed program launches + the
    # in-trace retrace counter of the one collapsed program family
    "unified_steps",
    "ragged_jit_traces",
    # device-resident decode bursts (ISSUE 19): the burst family's own
    # in-trace retrace counter (bounded by the burst bucket lattice)
    "burst_jit_traces",
)

_GAUGE_NAMES = ("queue_depth", "num_running", "kv_pool_occupancy",
                "prefix_cached_token_ratio", "mp_shards")

# pre-registered so every latency surface shows on /metrics from the
# first scrape.  The last four are the per-request SLO breakdown
# (ISSUE 8) derived from the lifecycle timestamps: arrival → first
# prefill chunk (queue_wait) → first token (prefill) → finish (e2e),
# with decode_itl the per-token gap (observed alongside the legacy
# inter_token_latency series).
_HISTOGRAM_NAMES = (
    "time_to_first_token",
    "inter_token_latency",
    "prefill_step",
    "decode_step",
    "unified_step",   # ISSUE 11: wall time of one packed ragged launch
    "burst_step",     # ISSUE 19: wall time of one N-step decode burst
    "queue_wait",
    "prefill",
    "decode_itl",
    "e2e",
)

# the SLO breakdown quartet, in pipeline order (bench.py embeds these)
SLO_PHASES = ("queue_wait", "prefill", "decode_itl", "e2e")

# mesh-spanning step phases (ISSUE 5): pre-registered so the
# serving_collective_seconds series shows on /metrics even before (or
# without) any multi-chip step running.  "ragged" is the unified packed
# step (ISSUE 11) — the one program family that replaces the other two.
_COLLECTIVE_PHASES = ("prefill", "decode", "ragged", "burst")

# every full metric name this module pre-registers, for the README
# metrics-table lint (tools/check_metrics_docs.py)
METRIC_NAMES = tuple(
    [f"serving_{n}_total" for n in _COUNTER_NAMES]
    + [f"serving_{n}" for n in _GAUGE_NAMES]
    + [f"serving_{n}_seconds" for n in _HISTOGRAM_NAMES]
    + ["serving_collective_seconds"]
)


class ServingMetrics:
    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[SpanTracer] = None,
                 labels: Optional[Dict[str, str]] = None):
        # own registry by default so per-engine counts stay per-engine;
        # pass get_registry() to publish on the process-wide /metrics page.
        # ``labels`` rides EVERY series this object creates — the fleet
        # router (ISSUE 6) builds each replica engine with
        # ``labels={"replica": str(i)}`` on one shared registry, so
        # /metrics exposes per-replica-labeled serving series side by
        # side without name collisions.
        self.registry = (registry if registry is not None
                         else MetricsRegistry(max_series=512))
        self.tracer = tracer if tracer is not None else get_tracer()
        self.labels: Dict[str, str] = dict(labels or {})
        self._counters: Dict[str, Counter] = {}
        for name in _COUNTER_NAMES:
            self._counter(name)
        self._hists: Dict[str, Histogram] = {}
        for name in _HISTOGRAM_NAMES:
            self._hist(name)
        # recent per-step gauge samples (bounded window) for inspection;
        # exact full-history aggregates live on the registry Gauges
        self.queue_depth: Deque[int] = deque(maxlen=GAUGE_WINDOW)
        self.num_running: Deque[int] = deque(maxlen=GAUGE_WINDOW)
        self.kv_occupancy: Deque[float] = deque(maxlen=GAUGE_WINDOW)
        self._gauges: Dict[str, Gauge] = {
            name: self.registry.gauge(f"serving_{name}",
                                      f"per-engine-step {name}",
                                      **self.labels)
            for name in _GAUGE_NAMES
        }
        # wall time of one mesh-spanning jitted step, labelled by phase
        # (observed only when mp > 1; present on /metrics regardless)
        self._collective: Dict[str, Histogram] = {
            phase: self.registry.histogram(
                "serving_collective_seconds",
                "wall time of the mesh-spanning jitted step (mp > 1)",
                buckets=LATENCY_BUCKETS, phase=phase, **self.labels)
            for phase in _COLLECTIVE_PHASES
        }
        self._host_ops: Optional[HostOpRecorder] = None
        self._stepprof = None  # StepProfiler, attached by the engine
        self._wire = None      # distrib.WireStats, attached by a
        # cross-process WorkerEngineProxy (ISSUE 17)

    def attach_step_profiler(self, stepprof) -> None:
        """Bind the engine's :class:`~paddle_tpu.observability.stepprof
        .StepProfiler` so :meth:`summary` can render the per-program
        bucket-utilization / padding-waste table (ISSUE 9)."""
        self._stepprof = stepprof

    def attach_wire_stats(self, wire_stats) -> None:
        """Bind a cross-process replica's
        :class:`~paddle_tpu.observability.distrib.WireStats` so
        :meth:`summary` can render the host-vs-wire-vs-engine share of
        every step's wall time (ISSUE 17)."""
        self._wire = wire_stats

    # --- recording ----------------------------------------------------------
    def _counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            c = self._counters[name] = self.registry.counter(
                f"serving_{name}_total", f"serving {name.replace('_', ' ')}",
                **self.labels)
        return c

    def _hist(self, name: str) -> Histogram:
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = self.registry.histogram(
                f"serving_{name}_seconds",
                f"serving {name.replace('_', ' ')} (seconds)",
                buckets=LATENCY_BUCKETS, **self.labels)
        return h

    def count(self, name: str, n: int = 1) -> None:
        self._counter(name).inc(n)

    def observe(self, name: str, seconds: float) -> None:
        self._hist(name).observe(seconds)

    def observe_ttft(self, seconds: float) -> None:
        self.observe("time_to_first_token", seconds)

    def observe_inter_token(self, seconds: float) -> None:
        # decode_itl is the SLO-breakdown name for the same measurement
        # (ISSUE 8); the legacy inter_token_latency series is preserved
        self.observe("inter_token_latency", seconds)
        self.observe("decode_itl", seconds)

    def observe_queue_wait(self, seconds: float) -> None:
        """Arrival → first prefill chunk (observed once per request, at
        the moment its first prefill program launches)."""
        self.observe("queue_wait", seconds)

    def observe_prefill_phase(self, seconds: float) -> None:
        """First prefill chunk → first emitted token (the whole prefill
        phase, chunks and recomputes included — distinct from the
        per-program ``prefill_step`` wall time)."""
        self.observe("prefill", seconds)

    def observe_finish(self, e2e_seconds: float,
                       slo_ms: Optional[float] = None) -> None:
        """End-to-end latency + the SLO goodput pair: every finished
        request that carried an ``slo_ms`` counts toward
        ``serving_slo_total``; the ones that met it toward
        ``serving_slo_good_total`` (goodput = good/total).  The pair is
        incremented under the registry lock so any reader that snapshots
        under the same lock (:meth:`slo_counts`, the history sampler's
        burn-rate windows — ISSUE 14) can never observe good > total."""
        self.observe("e2e", e2e_seconds)
        if slo_ms is not None:
            good = e2e_seconds * 1e3 <= slo_ms
            slo_c, good_c = self._counter("slo"), self._counter("slo_good")
            with self.registry.atomic():
                slo_c.inc()
                if good:
                    good_c.inc()

    def slo_counts(self) -> Tuple[int, int]:
        """(good, total) snapshotted under the registry lock — the
        consistent read side of the goodput pair (a reader interleaving
        the two bare counter reads could transiently see good > total)."""
        good_c, slo_c = self._counter("slo_good"), self._counter("slo")
        with self.registry.atomic():
            return int(good_c.value), int(slo_c.value)

    def slo_breakdown(self) -> Dict[str, Dict]:
        """JSON-able per-phase latency breakdown (the shape ``bench.py``
        embeds per phase): count/avg/p50/p95/p99 for each SLO phase plus
        the goodput pair."""
        out: Dict[str, Dict] = {}
        for name in SLO_PHASES:
            h = self._hist(name)
            out[name] = {
                "count": h.count,
                "avg_s": round(h.avg, 6) if h.count else None,
                "p50_s": _round6(h.quantile(0.50)),
                "p95_s": _round6(h.quantile(0.95)),
                "p99_s": _round6(h.quantile(0.99)),
            }
        good, total = self.slo_counts()  # one consistent pair read
        out["goodput"] = {
            "slo_total": total, "slo_good": good,
            "ratio": round(good / total, 4) if total else None,
        }
        return out

    def observe_collective(self, phase: str, seconds: float) -> None:
        """One mesh-spanning jitted step's wall time (ISSUE 5):
        ``serving_collective_seconds{phase="prefill"|"decode"}``."""
        self._collective[phase].observe(seconds)

    def set_mp_shards(self, mp: int) -> None:
        """Publish the engine's tensor-parallel degree
        (``serving_mp_shards``; 1 = single-chip)."""
        self._gauges["mp_shards"].set(mp)

    def cached_token_ratio(self) -> Optional[float]:
        """hit / (hit + computed) over the whole process life — the
        fraction of prefill-bound tokens the prefix cache served for
        free; ``None`` until any prefill ran.  The fleet's
        ``serving_fleet_cache_imbalance`` gauge (ISSUE 13) is the
        max−min of this value across replicas."""
        hit = self._counter("prefix_cache_hit_tokens").value
        computed = self._counter("prefill_tokens_computed").value
        return hit / (hit + computed) if hit + computed else None

    def set_cached_token_ratio(self) -> None:
        """Publish :meth:`cached_token_ratio` on the gauge.  A no-op
        until any prefill ran."""
        ratio = self.cached_token_ratio()
        if ratio is not None:
            self._gauges["prefix_cached_token_ratio"].set(ratio)

    def sample_gauges(self, queue_depth: int, num_running: int,
                      kv_occupancy: float) -> None:
        for name, window, v in (
                ("queue_depth", self.queue_depth, queue_depth),
                ("num_running", self.num_running, num_running),
                ("kv_pool_occupancy", self.kv_occupancy, kv_occupancy)):
            window.append(v)
            self._gauges[name].set(v)

    # --- legacy inspection views --------------------------------------------
    @property
    def counters(self) -> Dict[str, int]:
        """{legacy_name: count} snapshot over the registry counters."""
        return {name: int(c.value) for name, c in self._counters.items()}

    @property
    def latency(self) -> Dict[str, OpStat]:
        """{name: OpStat} view over the latency histograms (the shape
        ``profiler/statistic.summary_table`` renders)."""
        out: Dict[str, OpStat] = {}
        for name, h in self._hists.items():
            st = OpStat(name)
            st.calls = h.count
            st.total = h.sum
            if h.count:
                st.max = h.max
                st.min = h.min
            out[name] = st
        return out

    # --- dispatch-bus wiring (profiler integration) -------------------------
    def install_dispatch_timer(self):
        """Subscribe per-op dispatch wall times into this metrics object
        via the multi-subscriber op bus — coexists with any active
        Profiler (the old single-owner hook silently no-oped here).
        Returns a zero-arg remover."""
        from ..core import dispatch as _dispatch

        if self._host_ops is None:
            self._host_ops = HostOpRecorder()
        return _dispatch.add_op_timer(self._host_ops)

    # --- exporters ----------------------------------------------------------
    def prometheus_text(self) -> str:
        return self.registry.prometheus_text()

    def snapshot(self) -> Dict:
        return self.registry.snapshot()

    # --- reporting ----------------------------------------------------------
    def _gauge_rows(self):
        rows = []
        for name in _GAUGE_NAMES:
            g = self._gauges[name]
            if g.samples == 0:
                rows.append((name, 0, "-", "-", "-"))
            else:
                rows.append((name, g.samples, f"{g.avg:.2f}",
                             f"{g.max:.2f}", f"{g.min:.2f}"))
        return rows

    def summary(self, time_unit: str = "ms") -> str:
        """Render the serving report in ``profiler/statistic.py`` table
        style (printed AND returned, like ``Profiler.summary``)."""
        parts = []
        latency = self.latency
        if latency:
            parts.append(summary_table(
                latency, "Serving latency summary (request-level)",
                time_unit=time_unit))

        counters = self.counters
        header = f"{'Counter':32s} {'Value':>12s}"
        bar = "-" * len(header)
        lines = [bar, "Serving counters", bar, header, bar]
        for name in sorted(counters):
            lines.append(f"{name:32s} {counters[name]:12d}")
        lines.append(bar)
        parts.append("\n".join(lines))

        header = (f"{'SLO phase':16s} {'Count':>8s} {'Avg(ms)':>10s} "
                  f"{'p50(ms)':>10s} {'p95(ms)':>10s} {'p99(ms)':>10s}")
        bar = "-" * len(header)
        lines = [bar, "SLO breakdown (bucket-quantile estimates)", bar,
                 header, bar]
        for name in SLO_PHASES:
            h = self._hist(name)
            cells = [(f"{q * 1e3:10.3f}" if q is not None else
                      f"{'-':>10s}")
                     for q in (h.avg if h.count else None,
                               h.quantile(0.50), h.quantile(0.95),
                               h.quantile(0.99))]
            lines.append(f"{name:16s} {h.count:8d} " + " ".join(cells))
        good, total = self.slo_counts()
        lines.append(bar)
        lines.append(f"goodput: {int(good)}/{int(total)} requests met "
                     "their slo_ms" if total else
                     "goodput: no request carried an slo_ms")
        lines.append(bar)
        parts.append("\n".join(lines))

        prog_rows = (self._stepprof.program_table()
                     if self._stepprof is not None
                     and self._stepprof.enabled else [])
        if prog_rows:
            header = (f"{'Program/bucket':20s} {'Launches':>8s} "
                      f"{'Sched':>8s} {'Capacity':>8s} {'Util':>7s} "
                      f"{'Waste':>7s} {'Wall(ms)':>10s}")
            bar = "-" * len(header)
            lines = [bar, "Bucket utilization / padding waste "
                          "(per step program)", bar, header, bar]
            for row in prog_rows:
                lines.append(
                    f"{row['program'] + '/' + row['bucket']:20s} "
                    f"{row['launches']:8d} "
                    f"{row['scheduled_tokens']:8d} "
                    f"{row['capacity_tokens']:8d} "
                    f"{row['utilization']:7.3f} "
                    f"{row['padding_ratio']:7.3f} "
                    f"{row['wall_s'] * 1e3:10.3f}")
            comp = self._stepprof.compile_totals()
            lines.append(bar)
            if comp:
                lines.append("compile attribution: " + ", ".join(
                    f"{p}: {t['count']}x {t['seconds'] * 1e3:.1f}ms"
                    for p, t in sorted(comp.items())))
            else:
                lines.append("compile attribution: no traces observed")
            lines.append(bar)
            parts.append("\n".join(lines))

        wire_report = (self._wire.report()
                       if self._wire is not None
                       and self._wire.steps else None)
        if wire_report:
            shares = wire_report["shares"]
            header = (f"{'Program':20s} {'Steps':>8s} {'Wire':>7s} "
                      f"{'Engine':>7s} {'Host':>7s}")
            bar = "-" * len(header)
            lines = [bar, "Cross-process step time shares "
                          "(wire vs engine vs host)", bar, header, bar]
            lines.append(f"{'ALL':20s} {wire_report['steps']:8d} "
                         f"{shares['wire']:7.3f} "
                         f"{shares['engine']:7.3f} "
                         f"{shares['host']:7.3f}")
            for prog, row in wire_report["per_program"].items():
                s = row["shares"]
                lines.append(f"{prog[:20]:20s} {row['steps']:8d} "
                             f"{s['wire']:7.3f} {s['engine']:7.3f} "
                             f"{s['host']:7.3f}")
            lines.append(bar)
            parts.append("\n".join(lines))

        header = (f"{'Gauge':24s} {'Samples':>8s} {'Avg':>10s} "
                  f"{'Max':>10s} {'Min':>10s}")
        bar = "-" * len(header)
        lines = [bar, "Scheduler/pool gauges (per engine step)", bar,
                 header, bar]
        for name, n, avg, mx, mn in self._gauge_rows():
            lines.append(f"{name:24s} {n:8d} {avg:>10s} {mx:>10s} {mn:>10s}")
        lines.append(bar)
        parts.append("\n".join(lines))

        if self._host_ops is not None and self._host_ops.stats:
            parts.append(summary_table(
                self._host_ops.stats,
                "Host operator summary (serving dispatch wall time)",
                time_unit=time_unit))
        report = "\n\n".join(parts)
        print(report)
        return report


def _round6(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 6)


class StepTimer:
    """``with StepTimer(metrics, "decode_step"): ...`` convenience.

    ``collective_phase`` additionally feeds the same wall time into
    ``serving_collective_seconds{phase=...}`` — the engine passes it only
    when the timed step actually spans mesh shards (mp > 1), keeping ONE
    timing path for both series."""

    def __init__(self, metrics: ServingMetrics, name: str,
                 collective_phase: Optional[str] = None):
        self.metrics = metrics
        self.name = name
        self.collective_phase = collective_phase
        self.dt: Optional[float] = None  # wall seconds, set on exit —
        # the engine reads it for the StepProfiler record so step-level
        # introspection shares this ONE timing path

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = self.dt = time.perf_counter() - self._t0
        self.metrics.observe(self.name, dt)
        if self.collective_phase is not None:
            self.metrics.observe_collective(self.collective_phase, dt)
        return False
