"""Serving metrics: request-level latency + scheduler/pool health.

Built on the SAME primitives as the profiler's summary statistics
(``profiler/statistic.py``): latency distributions are
:class:`~paddle_tpu.profiler.statistic.OpStat` entries rendered with
``summary_table``, and the optional per-op host table reuses
``HostOpRecorder`` through the dispatch ``_set_op_timer`` hook — so a
serving summary reads exactly like a profiler summary.

Tracked:

* **time-to-first-token** (admission-inclusive: arrival → first emitted
  token) and **inter-token latency** per request;
* **prefill / decode step** wall times;
* **queue depth**, **running-set size**, and **KV-pool occupancy** sampled
  once per engine step;
* counters: admitted, finished-by-reason (eos/length/abort), preemptions,
  recompute prefills, decode/prefill jit traces.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, Optional

from ..profiler.statistic import HostOpRecorder, OpStat, summary_table

# how many raw per-step gauge samples to retain for inspection; the
# summary's avg/max/min come from exact streaming aggregates, so a
# long-lived server's memory stays constant no matter how many steps run
GAUGE_WINDOW = 4096


class ServingMetrics:
    def __init__(self):
        self.latency: Dict[str, OpStat] = {}
        self.counters: Dict[str, int] = {
            "requests_admitted": 0,
            "requests_finished_eos": 0,
            "requests_finished_length": 0,
            "requests_finished_abort": 0,
            "preemptions": 0,
            "recompute_prefills": 0,
            "engine_steps": 0,
        }
        # recent per-step gauge samples (bounded window) + full-history
        # streaming aggregates [n, sum, max, min] per gauge
        self.queue_depth: Deque[int] = deque(maxlen=GAUGE_WINDOW)
        self.num_running: Deque[int] = deque(maxlen=GAUGE_WINDOW)
        self.kv_occupancy: Deque[float] = deque(maxlen=GAUGE_WINDOW)
        self._gauge_agg: Dict[str, list] = {}
        self._host_ops: Optional[HostOpRecorder] = None

    # --- recording ----------------------------------------------------------
    def _stat(self, name: str) -> OpStat:
        s = self.latency.get(name)
        if s is None:
            s = self.latency[name] = OpStat(name)
        return s

    def count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def observe(self, name: str, seconds: float) -> None:
        self._stat(name).add(seconds)

    def observe_ttft(self, seconds: float) -> None:
        self.observe("time_to_first_token", seconds)

    def observe_inter_token(self, seconds: float) -> None:
        self.observe("inter_token_latency", seconds)

    def sample_gauges(self, queue_depth: int, num_running: int,
                      kv_occupancy: float) -> None:
        for name, window, v in (
                ("queue_depth", self.queue_depth, queue_depth),
                ("num_running", self.num_running, num_running),
                ("kv_pool_occupancy", self.kv_occupancy, kv_occupancy)):
            window.append(v)
            agg = self._gauge_agg.get(name)
            if agg is None:
                self._gauge_agg[name] = [1, v, v, v]
            else:
                agg[0] += 1
                agg[1] += v
                agg[2] = max(agg[2], v)
                agg[3] = min(agg[3], v)

    # --- dispatch-hook wiring (profiler integration) ------------------------
    def install_dispatch_timer(self):
        """Route per-op dispatch wall times into this metrics object via
        the profiler's ``_set_op_timer`` hook (no-op if a Profiler already
        owns the hook).  Returns a zero-arg remover."""
        from ..core import dispatch as _dispatch

        if _dispatch._op_timer is not None:
            return lambda: None
        if self._host_ops is None:
            self._host_ops = HostOpRecorder()
        _dispatch._set_op_timer(self._host_ops)

        def remove():
            if _dispatch._op_timer is self._host_ops:
                _dispatch._set_op_timer(None)

        return remove

    # --- reporting ----------------------------------------------------------
    def _gauge_rows(self):
        rows = []
        for name in ("queue_depth", "num_running", "kv_pool_occupancy"):
            agg = self._gauge_agg.get(name)
            if agg is None:
                rows.append((name, 0, "-", "-", "-"))
            else:
                n, total, mx, mn = agg
                rows.append((name, n, f"{total / n:.2f}",
                             f"{mx:.2f}", f"{mn:.2f}"))
        return rows

    def summary(self, time_unit: str = "ms") -> str:
        """Render the serving report in ``profiler/statistic.py`` table
        style (printed AND returned, like ``Profiler.summary``)."""
        parts = []
        if self.latency:
            parts.append(summary_table(
                self.latency, "Serving latency summary (request-level)",
                time_unit=time_unit))

        header = f"{'Counter':32s} {'Value':>12s}"
        bar = "-" * len(header)
        lines = [bar, "Serving counters", bar, header, bar]
        for name in sorted(self.counters):
            lines.append(f"{name:32s} {self.counters[name]:12d}")
        lines.append(bar)
        parts.append("\n".join(lines))

        header = (f"{'Gauge':24s} {'Samples':>8s} {'Avg':>10s} "
                  f"{'Max':>10s} {'Min':>10s}")
        bar = "-" * len(header)
        lines = [bar, "Scheduler/pool gauges (per engine step)", bar,
                 header, bar]
        for name, n, avg, mx, mn in self._gauge_rows():
            lines.append(f"{name:24s} {n:8d} {avg:>10s} {mx:>10s} {mn:>10s}")
        lines.append(bar)
        parts.append("\n".join(lines))

        if self._host_ops is not None and self._host_ops.stats:
            parts.append(summary_table(
                self._host_ops.stats,
                "Host operator summary (serving dispatch wall time)",
                time_unit=time_unit))
        report = "\n\n".join(parts)
        print(report)
        return report


class StepTimer:
    """``with StepTimer(metrics, "decode_step"): ...`` convenience."""

    def __init__(self, metrics: ServingMetrics, name: str):
        self.metrics = metrics
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.metrics.observe(self.name, time.perf_counter() - self._t0)
        return False
