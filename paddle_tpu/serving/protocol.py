"""Wire protocol for the serving HTTP frontend.

OpenAI-style completions over token ids: the toy models in
``paddle_tpu/models`` have no tokenizer, so ``prompt`` is a list of token
ids (a server configured with a ``tokenize`` callable also accepts
strings) and responses carry ``token_ids`` where the OpenAI schema
carries ``text``.  Everything here is pure data — parsing/validation of
the request body, JSON response bodies, and SSE framing — so
``server.py`` stays transport-only and tests can exercise the protocol
without a socket.

SSE wire format (``stream=true``)::

    data: {"id": ..., "object": "text_completion.chunk", "choices":
           [{"index": 0, "token_ids": [123], "finish_reason": null}]}\n\n
    ...
    data: {"id": ..., ... "token_ids": [], "finish_reason": "length"}\n\n
    data: [DONE]\n\n

Each event carries the tokens NEW since the previous event; the final
data event has empty ``token_ids``, the request's ``finish_reason`` and
a ``usage`` block (prompt/completion totals plus
``prompt_cached_tokens`` — the prefix-cache saving, ISSUE 13); the
literal ``[DONE]`` sentinel terminates the stream (the OpenAI
convention).
"""

from __future__ import annotations

import json
import math
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from .request import SamplingParams

SSE_DONE = b"data: [DONE]\n\n"

# request-body caps: a public frontend must bound what one POST can ask
# for before it ever touches the engine
MAX_BODY_BYTES = 1 << 20
MAX_PROMPT_TOKENS = 32768
MAX_MAX_TOKENS = 65536


class ProtocolError(ValueError):
    """Malformed/invalid request body → HTTP 400."""


@dataclass
class CompletionRequest:
    """Validated ``POST /v1/completions`` body."""

    prompt_ids: List[int]
    max_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    eos_token_id: Optional[int] = None
    stream: bool = False
    timeout: Optional[float] = None   # seconds; server clamps to its max
    priority: int = 0
    slo_ms: Optional[float] = None    # per-request latency objective:
                                      # scored into the serving_slo_*
                                      # goodput pair on finish
    retryable: bool = False           # opt-in transparent retry-from-
                                      # scratch if the owning replica
                                      # dies mid-stream (ISSUE 12):
                                      # greedy recompute re-delivers
                                      # identical tokens; off = such a
                                      # request finishes with
                                      # finish_reason="replica_failed"

    def sampling(self) -> SamplingParams:
        return SamplingParams(
            max_new_tokens=self.max_tokens, temperature=self.temperature,
            top_k=self.top_k, top_p=self.top_p,
            eos_token_id=self.eos_token_id, seed=self.seed)


def _typed(obj: dict, key: str, kinds, default, *, none_ok: bool = False):
    v = obj.get(key, default)
    if v is None and none_ok:
        return None
    if isinstance(v, bool) and bool not in (kinds if isinstance(kinds, tuple)
                                            else (kinds,)):
        raise ProtocolError(f"{key!r} must be {kinds}, got bool")
    if not isinstance(v, kinds):
        raise ProtocolError(
            f"{key!r} must be {getattr(kinds, '__name__', kinds)}, "
            f"got {type(v).__name__}")
    return v


def parse_completion_request(
        body: bytes,
        tokenize: Optional[Callable[[str], List[int]]] = None,
) -> CompletionRequest:
    """Parse + validate a completions body; raises :class:`ProtocolError`
    (→ 400) on anything malformed."""
    if len(body) > MAX_BODY_BYTES:
        raise ProtocolError(f"body exceeds {MAX_BODY_BYTES} bytes")
    try:
        obj = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"body is not valid JSON: {e}") from None
    if not isinstance(obj, dict):
        raise ProtocolError("body must be a JSON object")

    prompt = obj.get("prompt")
    if prompt is None:
        raise ProtocolError("'prompt' is required")
    if isinstance(prompt, str):
        if tokenize is None:
            raise ProtocolError(
                "string prompts need a server-side tokenizer; "
                "send a list of token ids")
        prompt = tokenize(prompt)
    if isinstance(prompt, int) and not isinstance(prompt, bool):
        prompt = [prompt]
    if (not isinstance(prompt, list) or not prompt
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       for t in prompt)):
        raise ProtocolError("'prompt' must be a non-empty list of token ids")
    if len(prompt) > MAX_PROMPT_TOKENS:
        raise ProtocolError(
            f"prompt of {len(prompt)} tokens exceeds {MAX_PROMPT_TOKENS}")

    max_tokens = _typed(obj, "max_tokens", int, 16)
    if not 1 <= max_tokens <= MAX_MAX_TOKENS:
        raise ProtocolError(
            f"'max_tokens' must be in [1, {MAX_MAX_TOKENS}]")
    temperature = float(_typed(obj, "temperature", (int, float), 0.0))
    # json.loads accepts the NaN/Infinity literals: a non-finite value
    # here would detonate inside the ENGINE thread's sampler, not this
    # handler — validate it out at the door
    if not math.isfinite(temperature) or temperature < 0.0:
        raise ProtocolError("'temperature' must be finite and >= 0")
    top_k = _typed(obj, "top_k", int, 0)
    if top_k < 0:
        raise ProtocolError("'top_k' must be >= 0")
    top_p = float(_typed(obj, "top_p", (int, float), 1.0))
    # ISSUE 18: NaN compares False against everything, so an unvalidated
    # NaN would silently disable the nucleus cut inside the traced
    # sampler; 0 would keep no tokens at all — both are 400s here
    if not math.isfinite(top_p) or not 0.0 < top_p <= 1.0:
        raise ProtocolError("'top_p' must be finite and in (0, 1]")
    timeout = _typed(obj, "timeout", (int, float), None, none_ok=True)
    if timeout is not None and (not math.isfinite(float(timeout))
                                or float(timeout) <= 0):
        raise ProtocolError("'timeout' must be finite and > 0 seconds")
    seed = _typed(obj, "seed", int, 0)
    if seed < 0:
        raise ProtocolError("'seed' must be >= 0")  # np rng requirement
    slo_ms = _typed(obj, "slo_ms", (int, float), None, none_ok=True)
    if slo_ms is not None and (not math.isfinite(float(slo_ms))
                               or float(slo_ms) <= 0):
        raise ProtocolError("'slo_ms' must be finite and > 0 milliseconds")

    return CompletionRequest(
        prompt_ids=[int(t) for t in prompt],
        max_tokens=max_tokens,
        temperature=temperature,
        top_k=top_k,
        top_p=top_p,
        seed=seed,
        eos_token_id=_typed(obj, "eos_token_id", int, None, none_ok=True),
        stream=_typed(obj, "stream", bool, False),
        timeout=None if timeout is None else float(timeout),
        priority=_typed(obj, "priority", int, 0),
        slo_ms=None if slo_ms is None else float(slo_ms),
        retryable=_typed(obj, "retryable", bool, False),
    )


# --- response bodies --------------------------------------------------------

def usage_body(prompt_tokens: int, completion_tokens: int,
               prompt_cached_tokens: int = 0) -> dict:
    """The ``usage`` accounting block (ISSUE 13 satellite):
    ``prompt_cached_tokens`` is how many prompt tokens the prefix cache
    served for free at admission — the client-visible cache saving."""
    return {
        "prompt_tokens": int(prompt_tokens),
        "completion_tokens": int(completion_tokens),
        "total_tokens": int(prompt_tokens) + int(completion_tokens),
        "prompt_cached_tokens": int(prompt_cached_tokens),
    }


def completion_body(request_id: str, model: str, token_ids: List[int],
                    finish_reason: Optional[str], prompt_tokens: int,
                    error: Optional[str] = None,
                    prompt_cached_tokens: int = 0) -> dict:
    """Non-streaming ``text_completion`` response object."""
    choice = {"index": 0, "token_ids": list(token_ids),
              "finish_reason": finish_reason}
    if error:
        choice["error"] = error
    return {
        "id": request_id,
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [choice],
        "usage": usage_body(prompt_tokens, len(token_ids),
                            prompt_cached_tokens),
    }


def chunk_body(request_id: str, model: str, token_ids: List[int],
               finish_reason: Optional[str],
               usage: Optional[dict] = None) -> dict:
    """One streaming ``text_completion.chunk`` event payload.  The FINAL
    chunk (the one carrying ``finish_reason``) also carries ``usage``
    with the per-request cache attribution, so SSE clients see the
    prefix-cache savings too (ISSUE 13 satellite)."""
    out = {
        "id": request_id,
        "object": "text_completion.chunk",
        "model": model,
        "choices": [{"index": 0, "token_ids": list(token_ids),
                     "finish_reason": finish_reason}],
    }
    if usage is not None:
        out["usage"] = usage
    return out


def error_body(message: str, type: str = "invalid_request_error") -> dict:
    return {"error": {"message": message, "type": type}}


def sse_event(payload: dict) -> bytes:
    """Frame one JSON payload as a Server-Sent Events data line."""
    return b"data: " + json.dumps(
        payload, separators=(",", ":")).encode("utf-8") + b"\n\n"
