"""User-facing serving entrypoints over :class:`EngineCore`.

Two surfaces:

* :class:`LLM` — offline batch inference (the vLLM ``LLM`` shape): hand it
  every prompt, it drives the continuous-batching loop to completion and
  returns per-request outputs in submission order.
* :func:`stream_generate` — online single-request streaming over a shared
  engine: yields tokens as they decode while other requests keep batching.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Union

from .engine import EngineCore
from .request import Request, SamplingParams
from .scheduler import SchedulerConfig


class CompletionOutput:
    """What one request produced: tokens + why it stopped."""

    def __init__(self, req: Request):
        self.request_id = req.request_id
        self.prompt_ids = list(req.prompt_ids)
        self.token_ids = list(req.output_tokens)
        self.finish_reason = (req.finish_reason.value
                              if req.finish_reason else None)
        self.num_preemptions = req.num_preemptions
        self.error = req.error

    def __repr__(self):
        return (f"CompletionOutput(request_id={self.request_id!r}, "
                f"tokens={self.token_ids}, finish={self.finish_reason})")


class LLM:
    """Offline batch generation with continuous batching underneath."""

    def __init__(self, model, num_blocks: int = 256, block_size: int = 16,
                 dtype=None, max_num_seqs: int = 8, **engine_kw):
        import jax.numpy as jnp

        self.engine = EngineCore(
            model, num_blocks=num_blocks, block_size=block_size,
            dtype=dtype if dtype is not None else jnp.float32,
            scheduler_config=SchedulerConfig(max_num_seqs=max_num_seqs),
            **engine_kw)

    def generate(self, prompts: Sequence,
                 sampling_params: Union[SamplingParams,
                                        Sequence[SamplingParams], None] = None,
                 ) -> List[CompletionOutput]:
        """Submit every prompt, drain the engine, return outputs in
        submission order."""
        if sampling_params is None:
            params = [SamplingParams() for _ in prompts]
        elif isinstance(sampling_params, SamplingParams):
            params = [sampling_params for _ in prompts]
        else:
            params = list(sampling_params)
            if len(params) != len(prompts):
                raise ValueError("one SamplingParams per prompt required")
        reqs = [self.engine.add_request(p, sampling=sp)
                for p, sp in zip(prompts, params)]
        self.engine.run()
        return [CompletionOutput(r) for r in reqs]

    def summary(self) -> str:
        return self.engine.metrics.summary()


def stream_generate(engine: EngineCore, prompt_ids,
                    sampling: Optional[SamplingParams] = None,
                    request_id=None, priority: int = 0) -> Iterator[int]:
    """Submit one request to a (possibly shared) engine and stream its
    tokens; other in-flight requests keep decoding in the same batches."""
    req = engine.add_request(prompt_ids, sampling=sampling,
                             request_id=request_id, priority=priority)
    return engine.stream(req.request_id)
