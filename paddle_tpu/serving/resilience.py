"""Self-healing fleet supervisor (ISSUE 12 tentpole).

The dp fleet (PR 6) survives a replica death only by excluding it
forever: a dead engine stays out of the ring until an operator acts, its
queued-but-unstarted requests are lost, and an audit-``degraded``
replica (PR 9) keeps serving drifting numerics.  This module closes the
loop the observability stack was built for: a :class:`FleetSupervisor`
monitor thread on the router consumes the failure signals the fleet
already emits and **acts** on them —

* **engine death** → tear down the dead :class:`~paddle_tpu.serving
  .fleet.EngineReplica`, re-dispatch its recoverable requests through
  normal routing (the consistent-hash ring already remaps the dead
  replica's keys), then rebuild a fresh engine + thread on the SAME
  replica index under a capped-exponential-backoff restart policy.
  ``max_restarts`` failures inside ``restart_window_s`` is a crash loop:
  the replica is permanently excluded and a ``crash_loop`` flight bundle
  dumps the evidence.
* **audit degraded** (PR 9 shadow-oracle divergence) → **quarantine**:
  stop routing to the replica, let its in-flight work drain (the engine
  still runs — only its numerics are suspect), abort stragglers with
  ``finish_reason="replica_failed"``, replace the engine with a clean
  one.  ``GET /v1/debug/audit`` returns to ``ok`` because the degraded
  auditor is gone with the engine it judged.
* **watchdog stall** → the per-replica :class:`~paddle_tpu.distributed
  .StepWatchdog` (armed around every ``eng.step()``) marks the replica
  **unhealthy on fire** — excluded from routing immediately, not only
  when the thread eventually dies — and the supervisor escalates to a
  full restart after ``watchdog_grace_s`` if the step counter still has
  not advanced (a stall that resolves inside the grace re-includes the
  replica untouched).

**Request triage on a dying replica.**  The replica's in-flight handle
set is claimed by the supervisor (``dict.pop`` is the atomic ownership
claim, the same rule ``try_submit`` uses) and triaged:

* *queued-but-unstarted* (never admitted) and *zero-output* (admitted,
  no token emitted yet) requests are **re-dispatched** through
  ``router.submit`` — nothing was delivered, so the retry is invisible
  and greedy tokens are identical to a fault-free run;
* requests that already streamed tokens re-dispatch too when they opted
  in (``retryable=true``): greedy recompute regenerates the SAME prefix
  tokens, the streaming cursor skips what was already delivered, and
  the client sees a seamless token-identical continuation;
* everything else finishes with the new
  ``finish_reason="replica_failed"`` — an honest verdict instead of a
  hang.

Re-dispatches that cannot place immediately (every survivor saturated,
or the whole fleet mid-restart) park in a pending queue the monitor
retries every tick — **zero queued-but-unstarted requests are ever
lost** while the supervisor lives.  If the router is draining, the
supervisor stops healing (a replica that dies mid-``shutdown()`` is NOT
resurrected) and terminally fails any orphans so the drain completes.

Everything is deterministic-testable: ``serving/faultinject.py``
schedules the faults, and ``tests/test_zz_resilience.py`` proves the
headline contract on CPU — injected engine death mid-stream at dp=2 →
reroute + auto-restart within the backoff bound, zero lost requests,
greedy token identity vs the fault-free run.

Observability: ``serving_replica_restarts_total{cause}``,
``serving_requests_redispatched_total``,
``serving_requests_replica_failed_total``, ``serving_quarantines_total``
and the ``serving_recovery_seconds`` histogram (detection → replacement
serving), plus ``quarantine`` / ``crash_loop`` flight triggers — exactly
one bundle per recovery action (the restart action's bundle is the
``engine_death`` dump the dying thread already fired; the supervisor
re-arms that trigger after each rebuild so the NEXT death of the same
index dumps again).
"""

from __future__ import annotations

import json
import sys
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass
from typing import Dict, Optional

from ..distributed.watchdog import StepWatchdog
from ..observability import lifecycle as _lc
from .fleet import EngineReplica, FleetDown, FleetRouter, FleetSaturated
from .request import FinishReason

RESTART_CAUSES = ("engine_death", "watchdog", "quarantine")

# pre-registered metric names this module owns (tools/check_metrics_docs
# lints that each appears in README's metrics table)
METRIC_NAMES = (
    "serving_replica_restarts_total",
    "serving_requests_redispatched_total",
    "serving_requests_replica_failed_total",
    "serving_quarantines_total",
    "serving_recovery_seconds",
)

_RECOVERY_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                     10.0, 30.0)


@dataclass
class SupervisorConfig:
    """Restart/quarantine policy knobs."""

    poll_interval_s: float = 0.02   # monitor tick
    backoff_initial_s: float = 0.05  # first restart delay ...
    backoff_factor: float = 2.0      # ... doubling per recent failure ...
    backoff_max_s: float = 2.0       # ... capped here
    max_restarts: int = 5           # restarts allowed inside the window;
                                    # one MORE failure within it = crash
                                    # loop -> permanent exclusion
    restart_window_s: float = 60.0
    quarantine: bool = True         # audit degraded -> replace the engine
    quarantine_drain_s: float = 2.0  # grace for in-flight work to finish
                                     # on a quarantined (live) replica
    watchdog_timeout_s: Optional[float] = None  # arm a per-replica step
    # watchdog; None = no watchdog (stalls only surface as deaths)
    watchdog_grace_s: float = 0.25  # stall persisting past this after the
    # watchdog fired escalates to a restart

    def __post_init__(self):
        if self.max_restarts < 1:
            raise ValueError(
                f"max_restarts must be >= 1, got {self.max_restarts}")
        if self.backoff_factor < 1.0:
            raise ValueError(
                f"backoff_factor must be >= 1, got {self.backoff_factor}")


class FleetSupervisor:
    """Monitor loop that keeps a :class:`FleetRouter` serving through
    replica failures.

    ``engine_factory(index, registry)`` must build a replacement engine
    identical to the original (same weights — e.g. seed before build —
    same EngineConfig); fleets built via :meth:`FleetRouter.build`
    remember their factory, so the argument is optional there.  Call
    :meth:`start` after ``router.start()``; :meth:`close` stops the
    monitor (``router.stop()``/``shutdown()`` call it automatically)."""

    def __init__(self, router: FleetRouter, engine_factory=None,
                 config: Optional[SupervisorConfig] = None):
        self.router = router
        self.cfg = config or SupervisorConfig()
        self.factory = (engine_factory if engine_factory is not None
                        else router._engine_factory)
        if self.factory is None:
            raise ValueError(
                "FleetSupervisor needs an engine_factory(index, registry) "
                "to rebuild replicas; pass one, or build the fleet via "
                "FleetRouter.build (which remembers its factory)")
        router.attach_supervisor(self)
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._excluded: set = set()     # permanently excluded indexes
        self._history: Dict[int, deque] = {
            r.index: deque(maxlen=self.cfg.max_restarts)
            for r in router.replicas}
        # scheduled (non-blocking) restarts: index -> (not-before time,
        # cause, detection t0).  The monitor never sleeps through a
        # backoff — a second replica failing during another's backoff is
        # triaged on the very next tick.  Bounded by the replica set.
        self._restart_at: Dict[int, tuple] = {}
        # in-progress quarantine drains: index -> (drain deadline,
        # detection t0).  Tick-based for the same reason — the monitor
        # keeps serving other replicas' failures while one drains.
        # Bounded by the replica set.
        self._quarantining: Dict[int, tuple] = {}
        self._pending: deque = deque()  # unbounded-ok: live re-dispatch work queue, bounded by dp x max_queue in-flight handles
        reg = router.registry
        self._restarts = {
            c: reg.counter("serving_replica_restarts_total",
                           "supervisor replica restarts", cause=c)
            for c in RESTART_CAUSES}
        self._redis_c = reg.counter(
            "serving_requests_redispatched_total",
            "requests re-routed off a dying/quarantined replica")
        self._failed_c = reg.counter(
            "serving_requests_replica_failed_total",
            "in-flight requests finished with replica_failed")
        self._quar_c = reg.counter(
            "serving_quarantines_total",
            "audit-degraded replicas quarantined and replaced")
        self._recovery_h = reg.histogram(
            "serving_recovery_seconds",
            "failure detected -> replacement replica serving",
            buckets=_RECOVERY_BUCKETS)

    # --- lifecycle ----------------------------------------------------------
    def start(self) -> "FleetSupervisor":
        for r in self.router.replicas:
            self._adopt(r)
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="fleet-supervisor", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        """Stop the monitor; terminally fail anything still pending and
        restore the legacy (unsupervised) death semantics on every
        replica so a later death cannot strand handles in limbo."""
        self._stop_ev.set()
        if self._thread is not None:
            self._thread.join(10.0)
            self._thread = None
        for r in self.router.replicas:
            r.supervised = False
            if r.watchdog is not None:
                r.watchdog.shutdown()
                r.watchdog = None
            if not r.alive and r.handles:
                # died while supervised but before the monitor acted:
                # sweep the orphans terminally (legacy semantics)
                self._triage(r, terminal=True)
        self._fail_pending("abort")
        self.router._notify(None)

    @property
    def excluded(self) -> set:
        return set(self._excluded)

    @property
    def pending(self) -> int:
        return len(self._pending)

    # --- replica adoption ---------------------------------------------------
    def _adopt(self, replica: EngineReplica) -> None:
        replica.supervised = True
        if self.cfg.watchdog_timeout_s is not None \
                and replica.watchdog is None:
            replica.watchdog = self._make_watchdog(replica)

    def _make_watchdog(self, replica: EngineReplica) -> StepWatchdog:
        wd = StepWatchdog(timeout=self.cfg.watchdog_timeout_s)

        def fired(label, timeout_s, replica=replica):
            # mark unhealthy ON FIRE (satellite): the replica leaves the
            # routing set the moment the stall is detected — a truly
            # hung thread must not keep receiving traffic just because
            # it has not died
            replica.stall = (replica.steps_done, time.monotonic())
            replica.unhealthy = True
            self.router.lifecycle.event(
                None, "watchdog_stall", replica=str(replica.index),
                section=label, timeout_s=timeout_s)
            self.router.flight.trigger(
                "watchdog", replica=str(replica.index),
                detail=f"section {label!r} exceeded {timeout_s}s; "
                       "replica excluded from routing")

        wd.on_timeout = fired
        return wd

    # --- monitor loop -------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop_ev.is_set():
            try:
                self._tick()
            except Exception:
                # the healer must never die silently: a broken tick is
                # reported and the next tick tries again
                sys.stderr.write("[supervisor] tick failed:\n"
                                 + traceback.format_exc())
            self._stop_ev.wait(self.cfg.poll_interval_s)

    def _tick(self) -> None:
        router = self.router
        if router.draining:
            # drain mode: NO healing (a replica dying mid-shutdown is
            # not resurrected) — but orphans of a supervised death must
            # still terminate so the drain can complete
            acted = False
            for r in list(router.replicas):
                if not r.alive and r.thread is not None and r.handles:
                    r.join(1.0)
                    self._triage(r, terminal=True)
                    acted = True
            if self._pending:
                self._fail_pending("abort")
                acted = True
            if acted:
                router._notify(None)
            return
        self._flush_pending()
        for r in list(router.replicas):
            i = r.index
            if i in self._excluded or r.thread is None:
                continue
            if i in self._restart_at:
                # rebuild already scheduled — checked BEFORE the _stop
                # guard below: an escalated replica was request_stop()ed
                # by the supervisor itself
                self._maybe_rebuild(i)
                continue
            if i in self._quarantining:
                self._continue_quarantine(r)
                continue
            if r._stop:
                continue  # stopped for drain/shutdown: not a failure
            if not r.alive:
                self._recover(r, cause="engine_death")
            elif r.stall is not None:
                self._check_stall(r)
            elif self.cfg.quarantine and r.engine.audit.degraded:
                self._begin_quarantine(r)

    # --- handle triage ------------------------------------------------------
    def _triage(self, replica: EngineReplica, terminal: bool) -> None:
        """Claim and disposition every handle still owned by
        ``replica``.  ``terminal=False`` re-dispatches recoverable
        requests (unstarted / zero-output / retryable) and fails the
        rest with ``replica_failed``; ``terminal=True`` (drain / close)
        fails everything un-finished with abort."""
        lc = self.router.lifecycle
        rep = str(replica.index)
        # with the engine thread confirmed dead its request objects are
        # frozen: a failed handle may keep its req so direct callers
        # still see the partial output.  A thread that may still run
        # (watchdog escalation) could mutate/finish the old req out
        # from under the verdict, so there the handle detaches.
        thread_dead = (replica.thread is not None
                       and not replica.thread.is_alive())
        for rid, h in list(replica.handles.items()):
            if replica.handles.pop(rid, None) is None:
                continue  # a racing claimer won the pop — not ours
            self.router._release(rid, replica)
            req = h.req
            if h.done or (req is not None and req.finished):
                continue  # already terminal; the handler reads it fine
            if h.cancel_reason is not None:
                # a deadline/disconnect abort raced the failure: honor it
                if not thread_dead:
                    h.req = None
                h.done = True
                lc.event(rid, _lc.EV_FINISH, replica=rep,
                         reason=h.cancel_reason.value)
                continue
            if self._recoverable(h) and not terminal:
                if (req is not None and req.output_tokens
                        and h.resume_tokens is None):
                    # mid-decode death (ISSUE 20): carry the emitted
                    # tokens so re-dispatch RESUMES instead of replaying
                    # — and so FleetRouter.submit routes this handle to
                    # a same-role/unified replica, never a prefill
                    # specialist.  The KV itself is unexportable (the
                    # engine thread is dead); the recipient recomputes
                    # the prompt+resume tail, which preserves greedy
                    # token identity.
                    h.resume_tokens = [int(t) for t in req.output_tokens]
                    h.arrival = req.arrival_time
                h.req = None
                lc.event(rid, "redispatch", replica=rep,
                         had_output=bool(req and req.output_tokens))
                self._pending.append(h)
            else:
                if not thread_dead:
                    h.req = None
                h.cancel_reason = (FinishReason.ABORT if terminal
                                   else FinishReason.REPLICA_FAILED)
                h.done = True
                if not terminal:
                    self._failed_c.inc()
                lc.event(rid, _lc.EV_FINISH, replica=rep,
                         reason=h.cancel_reason.value)

    def _flush_pending(self) -> None:
        """Re-dispatch parked handles through normal routing; a handle
        that still cannot place (fleet saturated / mid-restart) stays
        parked for the next tick — zero lost."""
        if not self._pending:
            return
        routed = False
        for _ in range(len(self._pending)):
            h = self._pending.popleft()
            if self.router.draining:
                self._pending.append(h)
                break
            if all(r.index in self._excluded
                   for r in self.router.replicas):
                # nothing will ever come back: fail honestly.
                # cancel_reason BEFORE done: a concurrent poller that
                # sees done must never read a missing reason as "abort"
                h.cancel_reason = FinishReason.REPLICA_FAILED
                h.done = True
                self._failed_c.inc()
                self.router.lifecycle.event(
                    h.rid, _lc.EV_FINISH,
                    reason=FinishReason.REPLICA_FAILED.value)
                routed = True
                continue
            try:
                self.router.submit(h)
            except (FleetSaturated, FleetDown):
                self._pending.append(h)  # retry next tick
            else:
                self._redis_c.inc()
                routed = True
        if routed:
            self.router._notify(None)

    def _fail_pending(self, reason: str) -> None:
        while self._pending:
            h = self._pending.popleft()
            # cancel_reason BEFORE done (concurrent pollers read done
            # first and must see the final reason with it)
            h.cancel_reason = (FinishReason.REPLICA_FAILED
                               if reason == "replica_failed"
                               else FinishReason.ABORT)
            h.done = True
            self.router.lifecycle.event(h.rid, _lc.EV_FINISH,
                                        reason=h.cancel_reason.value)

    # --- recovery actions ---------------------------------------------------
    def _recover(self, replica: EngineReplica, cause: str) -> None:
        """First observation of a dead replica: triage its handles NOW,
        then SCHEDULE the rebuild after the backoff (non-blocking — the
        monitor keeps ticking, so a second replica failing during this
        one's backoff is triaged immediately, not after it)."""
        i = replica.index
        t0 = time.monotonic()
        if replica.watchdog is not None:
            replica.watchdog.shutdown()
        replica.join(2.0)
        self._triage(replica, terminal=False)
        self._flush_pending()
        self.router._notify(None)
        hist = self._history[i]
        now = time.monotonic()
        recent = [t for t in hist if now - t <= self.cfg.restart_window_s]
        if len(recent) >= self.cfg.max_restarts:
            self._exclude(i, cause)
            return
        delay = min(self.cfg.backoff_max_s,
                    self.cfg.backoff_initial_s
                    * self.cfg.backoff_factor ** len(recent))
        hist.append(now)
        self._restart_at[i] = (now + delay, cause, t0)

    def _maybe_rebuild(self, index: int) -> None:
        """Scheduled-restart tick: rebuild once the backoff deadline has
        passed."""
        not_before, cause, t0 = self._restart_at[index]
        if time.monotonic() < not_before or self.router.draining:
            return
        del self._restart_at[index]
        if self._rebuild(index, cause):
            self._recovery_h.observe(time.monotonic() - t0)

    @staticmethod
    def _recoverable(h) -> bool:
        """THE re-dispatch eligibility rule, shared by death triage and
        quarantine stragglers: nothing delivered yet (never admitted or
        zero output), or the request opted in with ``retryable``."""
        req = h.req
        return req is None or not req.output_tokens or h.retryable

    def _check_stall(self, replica: EngineReplica) -> None:
        steps0, t_fire = replica.stall
        if replica.steps_done > steps0 \
                or not replica.engine.scheduler.has_work():
            # the stall resolved inside the grace: re-include untouched.
            # The idle check covers the stamp race — a step can complete
            # between the watchdog popping the expired section and the
            # handler recording steps_done, and an excluded idle replica
            # would otherwise never "advance" again.
            replica.stall = None
            replica.unhealthy = False
            self.router.lifecycle.event(
                None, "watchdog_stall_recovered",
                replica=str(replica.index))
            return
        if time.monotonic() - t_fire < self.cfg.watchdog_grace_s:
            return
        # still wedged past the grace: escalate to a restart.  The hung
        # thread cannot be killed — it is marked dead (error set), its
        # handles are claimed, and it is left to finish into the void
        # (its notify/evict paths are replica-scoped no-ops once the
        # owner map points at the replacement).
        replica.error = (f"watchdog escalation: step stalled past "
                         f"{self.cfg.watchdog_grace_s}s grace")
        replica.request_stop()
        self.router.lifecycle.event(
            None, "watchdog_escalation", replica=str(replica.index))
        self._recover(replica, cause="watchdog")

    def _begin_quarantine(self, replica: EngineReplica) -> None:
        """First observation of an audit-degraded replica: stop routing
        to it NOW and start the drain clock.  The drain itself is
        tick-based (:meth:`_continue_quarantine`) so the monitor keeps
        serving every other replica's failures while this one drains."""
        i = replica.index
        now = time.monotonic()
        replica.unhealthy = True
        self._quar_c.inc()
        snap = replica.engine.audit.snapshot()
        self.router.lifecycle.event(
            None, "quarantine", replica=str(i),
            divergences=sum(snap["divergences"].values()))
        self.router.flight.trigger(
            "quarantine", replica=str(i),
            detail=json.dumps(snap.get("last_divergence"), default=str))
        self._quarantining[i] = (now + self.cfg.quarantine_drain_s, now)

    def _continue_quarantine(self, replica: EngineReplica) -> None:
        i = replica.index
        deadline, t0 = self._quarantining[i]
        if not replica.alive:
            # died mid-drain: this is a death now — triage + scheduled
            # rebuild through the normal recovery path
            del self._quarantining[i]
            self._recover(replica, cause="quarantine")
            return
        if replica.handles and time.monotonic() < deadline:
            return  # still draining; other replicas keep being served
        self._finish_quarantine(replica, t0)

    def _finish_quarantine(self, replica: EngineReplica,
                           t0: float) -> None:
        """Drain over (or empty): disposition stragglers, stop the old
        engine, replace it with a clean one."""
        i = replica.index
        # stragglers: recoverable ones re-dispatch (their engine-side
        # twins are aborted so the old engine frees their blocks and
        # runs dry); the rest finish replica_failed THROUGH the live
        # engine so its pool empties before the teardown
        for rid, h in list(replica.handles.items()):
            req = h.req
            if h.done or (req is not None and req.finished):
                continue  # completed during the drain; engine evicts it
            if self._recoverable(h):
                if not self._park(replica, rid, h, quarantine=True):
                    continue
                if req is not None:
                    # free the abandoned twin's blocks on the old engine
                    try:
                        replica.abort_q.put_nowait(
                            (rid, FinishReason.ABORT))
                    except Exception:
                        pass  # swallow-ok: queue full only delays the old engine's cleanup; the engine is being torn down
                    replica.wake.set()
            else:
                replica.request_abort(rid, FinishReason.REPLICA_FAILED)
                self._failed_c.inc()
        self._flush_pending()
        replica.request_stop()
        replica.join(5.0)
        if replica.watchdog is not None:
            replica.watchdog.shutdown()
        del self._quarantining[i]
        if self._stop_ev.is_set() or self.router.draining:
            return
        if self._rebuild(i, cause="quarantine"):
            self._recovery_h.observe(time.monotonic() - t0)

    def _park(self, replica: EngineReplica, rid, h, **event_attrs) -> bool:
        """Claim one recoverable handle off ``replica`` (dict.pop is the
        ownership rule) and park it for re-dispatch; False when a racing
        claimer won the pop."""
        if replica.handles.pop(rid, None) is None:
            return False
        self.router._release(rid, replica)
        had = bool(h.req is not None and h.req.output_tokens)
        h.req = None
        self.router.lifecycle.event(
            rid, "redispatch", replica=str(replica.index),
            had_output=had, **event_attrs)
        self._pending.append(h)
        return True

    def _exclude(self, index: int, cause: str) -> None:
        self._excluded.add(index)
        self.router.lifecycle.event(
            None, "crash_loop_excluded", replica=str(index), cause=cause,
            restarts=len(self._history[index]))
        self.router.flight.trigger(
            "crash_loop", replica=str(index),
            detail=f"{self.cfg.max_restarts} restart(s) within "
                   f"{self.cfg.restart_window_s}s after {cause}; replica "
                   "permanently excluded")
        # handles parked for this replica route elsewhere; if this was
        # the last replica, the next flush fails them honestly
        self._flush_pending()

    def _rebuild(self, index: int, cause: str) -> bool:
        """Fresh engine + replica + thread on the same index, rewired
        onto the fleet's shared tracker/flight/injector exactly like
        :meth:`FleetRouter.__init__` wired the original.  Returns False
        when the replica was permanently excluded instead (rebuild
        cannot match the fleet's AOT artifact)."""
        from .aot import AotError

        router = self.router
        try:
            eng = self.factory(index, router.registry)
            if router.aot_artifact is None:
                if eng.aot_artifact is not None:
                    # mirror the build-time fleet gate: a traced fleet
                    # must not gain an AOT replica on rebuild (retraces
                    # would hide behind its zero counters)
                    raise AotError(
                        "rebuild factory bound an AOT artifact but the "
                        "fleet serves traced — a mixed fleet is refused "
                        "at build and on rebuild alike")
            elif eng.aot_artifact is not router.aot_artifact:
                # the robustness payoff of ISSUE 15: the rebuilt replica
                # REUSES the fleet's loaded artifact — warm compiled
                # executables, zero post-restart traces, millisecond
                # boot — even when the factory forgot to thread it
                # through (or loaded its own copy).  validate() inside
                # still fails loudly on a genuine deployment mismatch;
                # record_load=False: no disk load happened here, so the
                # load histogram must not gain a phantom sample per
                # restart.
                eng.bind_aot(router.aot_artifact, record_load=False)
        except AotError as e:
            # deterministic drift between the rebuild factory and the
            # fleet's artifact (whether raised binding here or inside
            # the factory's own EngineConfig.aot/aot_path): retrying
            # would fail the same way forever — exclude permanently and
            # loudly instead of letting the monitor tick swallow the
            # raise with the replica dead and unaccounted
            sys.stderr.write(
                f"[supervisor] replica {index} rebuild cannot match "
                f"the fleet's AOT configuration: {e}\n")
            self._exclude(index, cause=f"aot_mismatch({cause})")
            return False
        eng.set_lifecycle(router.lifecycle, replica=str(index))
        eng.audit.bind_flight(router.flight, replica=str(index))
        if router.history is not None:
            # the rebuilt engine keeps ticking the fleet's ONE history
            # store (ISSUE 14) — its registry counters continue from the
            # shared totals, so rate windows see no reset here; engine-
            # local resets are clamped by HistoryStore.increase anyway
            eng.set_history(router.history)
        fi = router.fault_injectors.get(index)
        if fi is not None:
            eng.set_fault_injector(fi)
        new = EngineReplica(index, eng, router.cfg.max_queue,
                            notify=router._notify,
                            on_finish=router._release)
        new.flight = router.flight
        self._adopt(new)
        router.engines[index] = eng
        router.replicas[index] = new
        router.flight.bind_step_profilers(
            {str(r.index): r.engine.stepprof for r in router.replicas})
        router.flight.bind_cache_trackers(
            {str(r.index): r.engine.cachestat for r in router.replicas})
        # re-arm the fired-once engine_death trigger (and its cooldown)
        # for this index: the NEXT death is a new incident and must dump
        # its own bundle — exactly one bundle per recovery action
        router.flight.reset_once("engine_death", str(index))
        new.start()
        self._restarts[cause].inc()
        self.router.lifecycle.event(
            None, "replica_restarted", replica=str(index), cause=cause)
        sys.stderr.write(f"[supervisor] replica {index} restarted "
                         f"(cause: {cause})\n")
        router.sample_gauges()
        return True
