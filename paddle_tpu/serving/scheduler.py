"""Continuous-batching scheduler.

Request-level scheduling over the ragged paged KV pool (the Ragged Paged
Attention shape, PAPERS.md): every engine step the scheduler

1. **admits** waiting requests into the running set while (a) the running
   set is under ``max_num_seqs`` and (b) the pool can cover the request's
   whole prompt *plus one decode block of headroom* without preempting
   anyone — admission never steals blocks from running work;
2. **reserves** this step's decode slot for every running request, and on
   exhaustion **preempts** — the least-important running request (highest
   ``(priority, arrival_seq)``) is evicted, its blocks freed, and it is
   re-enqueued at the FRONT of the waiting queue for prefill-recompute.
   Exhaustion is a scheduling event, not an error.

Invariants (tested by ``tests/test_serving_engine.py``):

* slot reservation is all-or-nothing per request — a preemption pass never
  leaves a half-allocated sequence behind;
* a preempted request keeps its generated tokens, so recompute costs one
  prefill over ``prompt + output_tokens`` and produces token-identical
  continuations (greedy);
* a request whose total footprint can never fit the pool (prompt blocks >
  usable pool) is finished as ABORT instead of live-locking the queue;
* batch composition changes NEVER change tensor shapes the compiler sees —
  the engine pads each batch to a size bucket (``bucket_size``), so the
  jitted decode step compiles once per bucket (MPK's fixed-shape
  mega-program argument, PAPERS.md).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional, Tuple

from .kv_manager import KVCacheManager
from .request import FinishReason, Request, RequestState


def bucket_size(n: int, cap: Optional[int] = None) -> int:
    """Next power of two ≥ n (≥1); optionally clamped to ``cap``.  The
    shape-bucketing that bounds jit trace count: any batch/width in the
    same bucket replays the same compiled program."""
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap) if cap is not None else b


@dataclass
class SchedulerConfig:
    max_num_seqs: int = 8            # running-set cap (decode batch ≤ this)
    max_prefills_per_step: int = 1   # admission throttle: prefill is the
                                     # expensive fixed-shape program; decode
                                     # latency of running requests is
                                     # protected by not batching many
                                     # prefills into one engine step


@dataclass
class SchedulerOutput:
    """One step's plan: prefills to run, the decode set, and who was
    preempted to make room."""

    prefills: List[Request] = field(default_factory=list)
    decodes: List[Request] = field(default_factory=list)
    preempted: List[Request] = field(default_factory=list)
    aborted: List[Request] = field(default_factory=list)


class ContinuousBatchingScheduler:
    """Owns the waiting queue and the running set; pure bookkeeping — the
    engine executes the plan this object returns."""

    def __init__(self, config: SchedulerConfig, kv: KVCacheManager):
        self.config = config
        self.kv = kv
        self.waiting: Deque[Request] = deque()  # unbounded-ok: live work queue (admission drains it); not telemetry
        self.running: List[Request] = []

    # --- queue ops ----------------------------------------------------------
    def add(self, req: Request) -> None:
        req.state = RequestState.WAITING
        self.waiting.append(req)

    def remove(self, req: Request) -> None:
        if req in self.running:
            self.running.remove(req)
        try:
            self.waiting.remove(req)
        except ValueError:
            pass

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # --- planning -----------------------------------------------------------
    def _usable_blocks(self) -> int:
        return self.kv.num_blocks - 1  # block 0 = null page

    def _admit(self, out: SchedulerOutput) -> None:
        admitted = 0
        promised = 0  # blocks pledged to prefills admitted THIS pass: the
                      # engine allocates them only when it runs the prefill,
                      # so kv.num_free alone would double-count the pool
        while (self.waiting
               and len(self.running) < self.config.max_num_seqs
               and admitted < self.config.max_prefills_per_step):
            req = self.waiting[0]
            prompt_blocks = self.kv.blocks_for(req.num_computed_tokens)
            if prompt_blocks > self._usable_blocks():
                # can never fit, even with the whole pool: fail THIS request
                # honestly rather than live-locking everyone behind it
                self.waiting.popleft()
                req.state = RequestState.FINISHED
                req.finish_reason = FinishReason.ABORT
                req.error = (f"request needs {prompt_blocks} KV blocks; "
                             f"pool has {self._usable_blocks()} usable")
                out.aborted.append(req)
                continue
            # +1 decode-slot headroom, but never demand more than the pool
            # HAS: a prompt filling the pool exactly is still servable when
            # its decode tokens fit the last block's free slots
            need = min(prompt_blocks + 1, self._usable_blocks())
            if need > self.kv.num_free - promised:
                break  # admission never preempts running work
            promised += need
            self.waiting.popleft()
            req.state = RequestState.RUNNING
            self.running.append(req)
            out.prefills.append(req)
            admitted += 1

    def _preempt(self, victim: Request) -> None:
        """Evict ``victim``: free its blocks, re-enqueue at the FRONT of
        the waiting queue (a preempted request outranks new arrivals, so
        it is re-admitted and recomputed as soon as blocks free up)."""
        self.running.remove(victim)
        self.kv.free(victim.request_id)
        victim.state = RequestState.PREEMPTED
        victim.num_preemptions += 1
        self.waiting.appendleft(victim)

    def _pick_victim(self, exclude) -> Optional[Request]:
        # only block-holding requests relieve pressure, and a request
        # that already reserved its slot this step (= more important in
        # the iteration order) is never stolen from
        candidates = [r for r in self.running if r not in exclude
                      and self.kv.num_owned_blocks(r.request_id) > 0]
        if not candidates:
            return None
        return max(candidates, key=lambda r: r.preempt_key)

    def _reserve_decode_slots(self, out: SchedulerOutput) -> None:
        """Reserve one decode slot per running request, preempting the
        least-important block-holding requests on exhaustion.  Iterates
        most-important first so preemption pressure lands on the tail."""
        granted: List[Request] = []
        for req in sorted(list(self.running), key=lambda r: r.preempt_key):
            if req.state is not RequestState.RUNNING:
                continue  # preempted by an earlier iteration
            while True:
                slot = self.kv.append_slot(req.request_id)
                if slot is not None:
                    req._slot = slot
                    granted.append(req)
                    out.decodes.append(req)
                    break
                victim = self._pick_victim(exclude=granted + [req])
                if victim is None:
                    # nothing evictable below it: this request itself
                    # yields (it is the least important slot-seeker left)
                    self._preempt(req)
                    out.preempted.append(req)
                    break
                self._preempt(victim)
                out.preempted.append(victim)

    def schedule(self) -> SchedulerOutput:
        """Plan one engine step.  Decode slots are reserved BEFORE
        admission, so blocks promised to a freshly admitted prefill can
        never be consumed by this step's decode appends.  Prefilled
        requests decode their first token within the same step (the
        prefill's last-position logits ARE that token), so they are not
        in ``decodes``."""
        out = SchedulerOutput()
        self._reserve_decode_slots(out)
        self._admit(out)
        return out
