"""Continuous-batching scheduler.

Request-level scheduling over the ragged paged KV pool (the Ragged Paged
Attention shape, PAPERS.md): every engine step the scheduler

1. **admits** waiting requests into the running set while (a) the running
   set is under ``max_num_seqs`` and (b) the pool can cover the request's
   *uncached* prompt tail *plus one decode block of headroom* without
   preempting anyone — admission never steals blocks from running work.
   Admission first **forks the longest cached block-prefix** of the
   prompt from the prefix cache (``KVCacheManager.fork_prefix``:
   refcount++, zero recompute), so a cache hit both skips prefill work
   AND shrinks the admission charge;
2. **plans prefill chunks** under the per-step token budget
   (``max_prefill_tokens_per_step``): a long prompt advances in chunks
   across engine steps — continuing partial prefills outrank new
   admissions — so prefill work shares steps with the running decode
   batch instead of stalling it.  ``None`` (the default) keeps the
   one-shot behaviour;
3. **reserves** this step's decode slot for every fully-prefilled running
   request, and on exhaustion **preempts** — the least-important running
   request (highest ``(priority, arrival_seq)``) is evicted, its blocks
   freed (shared prefix blocks stay with their other owners), and it is
   re-enqueued at the FRONT of the waiting queue for prefill-recompute.
   Exhaustion is a scheduling event, not an error.

Invariants (tested by ``tests/test_serving_engine.py``):

* slot reservation is all-or-nothing per request — a preemption pass never
  leaves a half-allocated sequence behind;
* a preempted request keeps its generated tokens, so recompute costs one
  prefill over ``prompt + output_tokens`` and produces token-identical
  continuations (greedy);
* a request whose total footprint can never fit the pool (prompt blocks >
  usable pool) is finished as ABORT instead of live-locking the queue;
* batch composition changes NEVER change tensor shapes the compiler sees —
  the engine pads each batch to a size bucket (``bucket_size``), so the
  jitted decode step compiles once per bucket (MPK's fixed-shape
  mega-program argument, PAPERS.md);
* the scheduler is **mesh-oblivious** (ISSUE 5): under tensor-parallel
  serving the KV pools shard over the ``mp`` axis but the block pool
  bookkeeping this scheduler plans against is host-side and replicated —
  one plan drives every shard, admission math is unchanged (the pool is
  logically ONE pool; only the per-shard byte footprint divides by mp),
  and the bucket sets (hence the jit trace bound) are mp-invariant.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional, Tuple

from .kv_manager import KVCacheManager
from .request import FinishReason, Request, RequestState


def bucket_size(n: int, cap: Optional[int] = None) -> int:
    """Next power of two ≥ n (≥1); optionally clamped to ``cap``.  The
    shape-bucketing that bounds jit trace count: any batch/width in the
    same bucket replays the same compiled program."""
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap) if cap is not None else b


@dataclass
class SchedulerConfig:
    """Per-step planning knobs.  Rides ``EngineConfig.scheduler`` in the
    one-object engine construction form, or the legacy
    ``EngineCore(scheduler_config=...)`` keyword."""

    max_num_seqs: int = 8            # running-set cap (decode batch ≤ this)
    max_prefills_per_step: int = 1   # admission throttle: prefill is the
                                     # expensive fixed-shape program; decode
                                     # latency of running requests is
                                     # protected by not batching many
                                     # prefills into one engine step
    max_prefill_tokens_per_step: Optional[int] = None
                                     # chunked prefill: per-step token
                                     # budget shared by ALL prefill work
                                     # (continuations + admissions) so a
                                     # long prompt advances in bucketed
                                     # chunks alongside the decode batch
                                     # instead of stalling it.  None =
                                     # unlimited (one-shot prefill).
    max_tokens_per_step: Optional[int] = None
                                     # unified ragged packing (ISSUE 11):
                                     # ONE token budget for the whole
                                     # step — decode rows (1 token each)
                                     # claim it first (they are NEVER
                                     # split across steps), prefill work
                                     # (continuations + admissions)
                                     # competes for the remainder.  The
                                     # packed token bucket is therefore
                                     # bounded by bucket_size(max(this,
                                     # max_num_seqs)) — a decode batch
                                     # larger than the budget still runs
                                     # whole.  None = no combined cap
                                     # (prefill still honours its own
                                     # budget).

    def __post_init__(self):
        if (self.max_prefill_tokens_per_step is not None
                and self.max_prefill_tokens_per_step < 1):
            # a zero/negative budget plans NO prefill ever: requests would
            # queue forever while has_work() stays True — fail fast instead
            raise ValueError(
                "max_prefill_tokens_per_step must be None or >= 1, got "
                f"{self.max_prefill_tokens_per_step}")
        if (self.max_tokens_per_step is not None
                and self.max_tokens_per_step < 1):
            raise ValueError(
                "max_tokens_per_step must be None or >= 1, got "
                f"{self.max_tokens_per_step}")


@dataclass
class SchedulerOutput:
    """One step's plan: prefill chunks to run, the decode set, and who
    was preempted to make room."""

    prefills: List[Request] = field(default_factory=list)
    admitted: List[Request] = field(default_factory=list)  # ⊆ prefills:
                                     # newly admitted this step (the
                                     # engine counts their cache hits)
    decodes: List[Request] = field(default_factory=list)
    preempted: List[Request] = field(default_factory=list)
    aborted: List[Request] = field(default_factory=list)
    # speculative-decode headroom (ISSUE 18): tokens left of
    # ``max_tokens_per_step`` after this plan's decode rows + prefill
    # chunks — the engine may pack at most this many DRAFT tokens into
    # the unified launch, so the packed token count never outgrows the
    # same ``max(total, decode rows)`` bucket bound the plain plan has.
    # 0 when no combined budget is configured (spec requires one).
    draft_budget: int = 0
    # decode-burst headroom (ISSUE 19): the largest per-row burst length
    # the pool can back for THIS plan's decode rows, from the ONE
    # `KVCacheManager.burst_capacity` accessor — the engine's launch
    # clamp reads this field instead of re-deriving headroom, so the
    # planning math and the clamp can never disagree.
    burst_capacity: int = 0


class ContinuousBatchingScheduler:
    """Owns the waiting queue and the running set; pure bookkeeping — the
    engine executes the plan this object returns."""

    def __init__(self, config: SchedulerConfig, kv: KVCacheManager):
        self.config = config
        self.kv = kv
        self.waiting: Deque[Request] = deque()  # unbounded-ok: live work queue (admission drains it); not telemetry
        self.running: List[Request] = []
        # exact planned-work ledger (ISSUE 9): every prefill token and
        # decode row this scheduler ever put in a plan.  The engine
        # executes plans verbatim, so the StepProfiler's scheduled-token
        # sum must equal these — the bucket-utilization invariant tests
        # and bench assert.
        self.tokens_planned_prefill = 0
        self.tokens_planned_decode = 0
        # blocks pledged to the MOST RECENT planning pass's prefill
        # chunks — a planning-pressure indicator the pool-timeline
        # sampler (ISSUE 13) records per step.  NOTE: the engine
        # executes the plan within the same step, so by the time the
        # end-of-step sample reads this the pledged blocks are
        # typically already materialized into the pool's allocated
        # count — promised is NOT extra unaccounted capacity and must
        # not be summed with `allocated`.
        self.promised_blocks = 0
        # hard sequence-length cap beyond the pool's own capacity
        # (ISSUE 15): an AOT-bound engine can only dispatch buckets
        # inside the artifact's saved universe, so admission must
        # reject a request whose prompt + max_new_tokens outgrows the
        # manifest's max_seq_len HONESTLY (finish_reason=abort + error)
        # instead of letting AotBucketMissing kill the engine thread
        # mid-stream — in a supervised fleet a re-dispatched oversize
        # request would otherwise cascade replica deaths.  None = no cap
        # (traced engines bucket anything the pool holds).
        self.seq_len_cap: Optional[int] = None

    # --- queue ops ----------------------------------------------------------
    def add(self, req: Request) -> None:
        req.state = RequestState.WAITING
        self.waiting.append(req)

    def remove(self, req: Request) -> None:
        if req in self.running:
            self.running.remove(req)
        try:
            self.waiting.remove(req)
        except ValueError:
            pass  # swallow-ok: remove() contract is idempotent — "not queued" is a normal state (running, or already removed), not a fault

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # --- planning -----------------------------------------------------------
    def _usable_blocks(self) -> int:
        return self.kv.num_blocks - 1  # block 0 = null page

    def _needs_prefill(self, req: Request) -> bool:
        """True while ``req``'s prompt (+ kept output, on recompute) is
        not yet in the pool.  The newest generated token's KV is written
        by the decode step that consumes it, so a recompute that reaches
        ``prompt + output - 1`` committed tokens resumes straight into
        decode — the decode step IS its final prefill position."""
        target = len(req.prompt_ids) + len(req.output_tokens)
        if req.output_tokens:
            target -= 1
        return self.kv.seq_len(req.request_id) < target

    def _chunk_capacity(self, req: Request, want: int, promised: int) -> int:
        """Clamp a continuation chunk to what the pool can actually back
        right now (``promised`` = blocks already pledged this pass): the
        pool may have drained since this request was admitted, and a
        chunk the engine cannot allocate must never be planned."""
        rid = req.request_id
        free_slots = (self.kv.num_owned_blocks(rid) * self.kv.block_size
                      - self.kv.seq_len(rid))
        avail = max(0, self.kv.num_available - promised)
        return min(want, free_slots + avail * self.kv.block_size)

    def _plan_prefills(self, out: SchedulerOutput) -> None:
        """Plan this step's prefill work under the chunk token budget:
        first continue partial prefills (most-important first — finishing
        an in-flight prompt beats admitting a new one), then admit from
        the waiting queue."""
        budget = self.config.max_prefill_tokens_per_step
        remaining = float("inf") if budget is None else int(budget)
        total = self.config.max_tokens_per_step
        if total is not None:
            # unified packing (ISSUE 11): this step's decode rows (slots
            # reserved before prefill planning) already claimed one
            # packed token each — prefill work competes for the rest of
            # the SINGLE budget, so decode latency is protected.  Decode
            # rows themselves are never split across steps, so the
            # packed token count is bounded by max(total, num decode
            # rows), not by total alone.
            remaining = min(remaining,
                            max(0, int(total) - len(out.decodes)))
        promised = 0  # blocks pledged to prefills planned THIS pass: the
                      # engine allocates them only when it runs the chunk,
                      # so kv.num_available alone would double-count
        for req in sorted(self.running, key=lambda r: r.preempt_key):
            if req.state is not RequestState.RUNNING:
                continue
            if not self._needs_prefill(req):
                continue
            if remaining <= 0:
                break
            want = (len(req.prompt_ids) + len(req.output_tokens)
                    - self.kv.seq_len(req.request_id))
            n = self._chunk_capacity(req, min(want, remaining), promised)
            if n <= 0:
                continue  # pool pressure: wait for decode-side churn
            req._chunk_tokens = int(n)
            promised += self.kv.blocks_needed(req.request_id, n)
            remaining -= n
            out.prefills.append(req)

        admitted = 0
        while (self.waiting
               and len(self.running) < self.config.max_num_seqs
               and admitted < self.config.max_prefills_per_step
               and remaining > 0):
            req = self.waiting[0]
            ids = req.prompt_ids + req.output_tokens
            prompt_blocks = self.kv.blocks_for(len(ids))
            target_len = len(req.prompt_ids) + req.sampling.max_new_tokens
            if self.seq_len_cap is not None \
                    and target_len > self.seq_len_cap:
                # outside the AOT artifact's saved bucket universe: the
                # zero-trace contract can never serve this sequence, so
                # fail it honestly AT ADMISSION instead of raising
                # AotBucketMissing from the engine thread mid-stream
                self.waiting.popleft()
                req.state = RequestState.FINISHED
                req.finish_reason = FinishReason.ABORT
                req.error = (
                    f"request targets {target_len} tokens (prompt "
                    f"{len(req.prompt_ids)} + max_new_tokens "
                    f"{req.sampling.max_new_tokens}) but the AOT "
                    f"artifact was saved for max_seq_len="
                    f"{self.seq_len_cap}; re-save with a larger bound")
                out.aborted.append(req)
                continue
            if prompt_blocks > self._usable_blocks():
                # can never fit, even with the whole pool: fail THIS request
                # honestly rather than live-locking everyone behind it
                self.waiting.popleft()
                req.state = RequestState.FINISHED
                req.finish_reason = FinishReason.ABORT
                req.error = (f"request needs {prompt_blocks} KV blocks; "
                             f"pool has {self._usable_blocks()} usable")
                out.aborted.append(req)
                continue
            # admit on the UNCACHED tail, not the whole prompt: blocks
            # already in the prefix cache cost nothing new (live shares)
            # or only their reuse-LRU slot (``from_reuse`` — those leave
            # the available set when forked, so they are charged).  This
            # is what makes a warm cache raise admission capacity.
            if req._probe_epoch != self.kv.cache_epoch:
                # leading-block hashes the fleet router already computed
                # (req.prefix_hashes) are reused, not re-hashed
                req._probe_blocks = self.kv.match_prefix(
                    ids, precomputed=req.prefix_hashes)
                req._probe_epoch = self.kv.cache_epoch
            hit = req._probe_blocks
            from_reuse = self.kv.reuse_count(hit)
            uncached = prompt_blocks - len(hit)
            # +1 decode-slot headroom, but never demand more than the pool
            # HAS: a prompt filling the pool exactly is still servable when
            # its decode tokens fit the last block's free slots
            need = min(uncached + 1, self._usable_blocks())
            if need + from_reuse > self.kv.num_available - promised:
                break  # admission never preempts running work
            self.waiting.popleft()
            cached = self.kv.fork_prefix(req.request_id, ids, blocks=hit)
            req.num_cached_tokens = cached
            promised += need  # the fork itself already moved from_reuse
                              # blocks out of num_available
            req.state = RequestState.RUNNING
            self.running.append(req)
            n = min(len(ids) - cached, remaining)
            req._chunk_tokens = int(n)
            remaining -= n
            out.prefills.append(req)
            out.admitted.append(req)
            admitted += 1
        self.promised_blocks = promised

    def _preempt(self, victim: Request) -> None:
        """Evict ``victim``: free its blocks (shared prefix blocks stay
        with their other owners — refcounts guarantee a preemption never
        clobbers a block someone else forked), re-enqueue at the FRONT of
        the waiting queue (a preempted request outranks new arrivals, so
        it is re-admitted and recomputed as soon as blocks free up)."""
        self.running.remove(victim)
        self.kv.free(victim.request_id)
        victim.state = RequestState.PREEMPTED
        victim.num_preemptions += 1
        victim.num_cached_tokens = 0
        victim._chunk_tokens = None
        victim._probe_blocks = None  # re-admission hashes prompt + output,
        victim._probe_epoch = -1     # not the ids this match was for
        self.waiting.appendleft(victim)

    def _pick_victim(self, exclude) -> Optional[Request]:
        # only block-holding requests relieve pressure, and a request
        # that already reserved its slot this step (= more important in
        # the iteration order) is never stolen from
        candidates = [r for r in self.running if r not in exclude
                      and self.kv.num_owned_blocks(r.request_id) > 0]
        if not candidates:
            return None
        return max(candidates, key=lambda r: r.preempt_key)

    def _reserve_decode_slots(self, out: SchedulerOutput) -> None:
        """Reserve one decode slot per running request, preempting the
        least-important block-holding requests on exhaustion.  Iterates
        most-important first so preemption pressure lands on the tail."""
        granted: List[Request] = []
        for req in sorted(list(self.running), key=lambda r: r.preempt_key):
            if req.state is not RequestState.RUNNING:
                continue  # preempted by an earlier iteration
            if self._needs_prefill(req):
                continue  # mid-(chunked)-prefill: no decode slot yet —
                          # the chunk planner advances it instead
            while True:
                slot = self.kv.append_slot(req.request_id)
                if slot is not None:
                    req._slot = slot
                    granted.append(req)
                    out.decodes.append(req)
                    break
                victim = self._pick_victim(exclude=granted + [req])
                if victim is None:
                    # nothing evictable below it: this request itself
                    # yields (it is the least important slot-seeker left)
                    self._preempt(req)
                    out.preempted.append(req)
                    break
                self._preempt(victim)
                out.preempted.append(victim)

    def schedule(self) -> SchedulerOutput:
        """Plan one engine step.  Decode slots are reserved BEFORE
        prefill planning, so blocks promised to a freshly planned chunk
        can never be consumed by this step's decode appends.  A request
        whose prefill completes samples its first token from the final
        chunk's last-position logits within the same step, so it is not
        in ``decodes``."""
        out = SchedulerOutput()
        self._reserve_decode_slots(out)
        self._plan_prefills(out)
        # burst headroom (ISSUE 19): computed AFTER slot reservation and
        # chunk planning, so it reflects the pool this plan leaves behind
        out.burst_capacity = self.kv.burst_capacity(len(out.decodes))
        self.tokens_planned_prefill += sum(
            r._chunk_tokens or 0 for r in out.prefills)
        self.tokens_planned_decode += len(out.decodes)
        total = self.config.max_tokens_per_step
        if total is not None:
            # leftover of the SINGLE step budget after decode rows and
            # planned prefill chunks: the spec-decode draft allowance
            # (the engine ledgers any drafts it actually packs)
            used = len(out.decodes) + sum(
                r._chunk_tokens or 0 for r in out.prefills)
            out.draft_budget = max(0, int(total) - used)
        return out

    @property
    def tokens_planned(self) -> int:
        """Total tokens ever planned (prefill chunk tokens + one per
        decode row) — the scheduler side of the scheduled-token
        invariant."""
        return self.tokens_planned_prefill + self.tokens_planned_decode
