"""Cross-process serving worker (ISSUE 16 tentpole (b)).

``python -m paddle_tpu.serving.worker`` wraps ONE
:class:`~paddle_tpu.serving.EngineCore` behind the fleet wire protocol
(``serving/wire.py``): the router process drives it through a
:class:`~paddle_tpu.serving.procfleet.WorkerEngineProxy` exactly the way
an in-process fleet drives a live engine, so FleetRouter and
FleetSupervisor transfer unchanged.

Boot protocol: the worker binds an ephemeral localhost port, builds its
engine (optionally onto a shared ``--aot-path`` artifact — PR 14's
zero-trace boot), then prints ONE machine-readable ready line to stdout::

    PADDLE_TPU_WORKER_READY port=<p> pid=<pid> aot_hash=<h> boot_s=<s>

The parent reads that line to learn the port; everything after it is
free-form logging.  With ``--compile-cache DIR`` the worker points JAX's
persistent compilation cache at ``DIR`` **before** anything compiles, so
N sibling workers compile each AOT program once machine-wide; the boot
log reports the cache-entry delta::

    PADDLE_TPU_COMPILE_CACHE dir=<d> entries_before=<a> entries_after=<b>

(``--warm`` executes every loaded program once at boot so the delta —
and a sibling's hit — is observable at boot time rather than smeared
over the first request wave.)

Connection model: one ``engine`` connection (submit/abort/step — driven
by the parent replica's engine thread, strictly serial) plus any number
of ``control`` connections (health/debug/drain — heartbeats and HTTP
debug handlers).  Engine state is guarded by one lock; a handshake or
frame error poisons only its connection (the process survives — that is
the wire-robustness satellite), while an engine-step failure is fatal by
design: the worker reports ``step_error`` with its traceback plus any
newly-fired fault-plan indexes, then exits so the supervisor's rebuild
respawns a clean process onto the shared artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time
import traceback
from typing import Dict, Optional

from . import wire

# metric names this module owns (tools/check_metrics_docs lints that
# each appears in README's metrics table)
METRIC_NAMES = (
    "serving_worker_connections_total",
    "serving_worker_boot_seconds",
)

from .wire import CACHE_PREFIX, READY_PREFIX  # noqa: F401  (canonical
# home is wire.py; re-exported here since they are worker protocol)

# engine-spec keys forwarded into EngineConfig (everything else in the
# spec is scheduler/model shape); a bounded vocabulary so a drifted
# parent fails loudly instead of silently half-configuring the worker
_ENGINE_KEYS = ("lifecycle_events", "decode_event_sample", "step_profile",
                "cache_stats", "history", "unified_step", "prefix_cache",
                "burst_steps", "role")
_SPEC_KEYS = _ENGINE_KEYS + (
    "layers", "num_blocks", "block_size", "max_num_seqs",
    "max_prefill_tokens_per_step", "max_tokens_per_step", "seed",
    "audit_enabled", "audit_sample_every", "telemetry", "mp", "spec")


def _count_cache_entries(path: Optional[str]) -> int:
    if not path or not os.path.isdir(path):
        return 0
    total = 0
    for _root, _dirs, files in os.walk(path):
        total += len(files)
    return total


def build_engine(spec: Dict, replica: int, registry, aot=None):
    """Deterministic toy-engine factory, mirroring the fleet's
    ``_toy_fleet`` shape: seed first, one model instance, per-replica
    metric labels.  The spec is the SAME dict the router's proxies
    template their gate attributes from, so the heterogeneity gates in
    ``FleetRouter.__init__`` hold across the process boundary."""
    unknown = sorted(set(spec) - set(_SPEC_KEYS))
    if unknown:
        raise ValueError(f"unknown engine-spec key(s) {unknown} — "
                         "router/worker version drift")
    import paddle_tpu as paddle

    from ..models import LlamaConfig, LlamaForCausalLM
    from ..observability.audit import AuditConfig
    from .engine import EngineConfig, EngineCore
    from .scheduler import SchedulerConfig

    mp = int(spec.get("mp", 1) or 1)
    if mp > 1:
        # multi-chip worker (ISSUE 18 fleet satellite): build the mesh
        # BEFORE the model so parameters and KV pools land sharded — the
        # same ordering serving/server.py enforces for --mp.  On CPU the
        # parent injects XLA_FLAGS=--xla_force_host_platform_device_count
        # into this process's environment before spawn.
        from ..distributed import topology

        topology.init_mesh(mp=mp)
    spec_decode = None
    if spec.get("spec"):
        from .spec import SpecConfig

        spec_decode = SpecConfig(**spec["spec"])
    paddle.seed(int(spec.get("seed", 0)))
    model = LlamaForCausalLM(
        LlamaConfig.tiny(num_hidden_layers=int(spec.get("layers", 2))))
    audit = None
    if spec.get("audit_enabled"):
        audit = AuditConfig(
            enabled=True,
            sample_every=max(1, int(spec.get("audit_sample_every", 1))))
    kwargs = {k: spec[k] for k in _ENGINE_KEYS if k in spec}
    cfg = EngineConfig(
        num_blocks=int(spec.get("num_blocks", 64)),
        block_size=int(spec.get("block_size", 4)),
        mp=mp if mp > 1 else None,
        scheduler=SchedulerConfig(
            max_num_seqs=int(spec.get("max_num_seqs", 4)),
            max_prefill_tokens_per_step=spec.get(
                "max_prefill_tokens_per_step"),
            max_tokens_per_step=spec.get("max_tokens_per_step")),
        audit=audit, aot=aot, spec=spec_decode, **kwargs)
    return EngineCore(model, config=cfg, registry=registry,
                      metrics_labels={"replica": str(replica)})


class WorkerHost:
    """The serving side of the wire: owns the engine, the lock that
    serializes engine mutation, and the fired-fault bookkeeping the
    router needs to keep its exactly-once chaos accounting across
    respawns."""

    def __init__(self, engine, registry, replica: int,
                 aot_hash: Optional[str], max_frame: int,
                 telemetry: bool = False,
                 deploy: Optional[Dict] = None):
        self.engine = engine
        self.registry = registry
        self.replica = int(replica)
        self.aot_hash = aot_hash
        self.max_frame = max_frame
        # deployment identity (ISSUE 18 fleet satellite): mesh-slice
        # shape + spec-decoding config, validated against every hello —
        # a router driving a different deployment is refused with a
        # typed deploy_mismatch, connection-scoped like aot_mismatch
        self.deploy = deploy
        # ISSUE 17 telemetry streaming: buffer this engine's lifecycle
        # events (sequence-numbered, bounded) and piggyback deltas onto
        # step/health replies — the router merges them into ITS tracker
        self.telemetry = bool(telemetry)
        self.outbox = None
        if self.telemetry and getattr(engine, "lifecycle", None) is not None:
            from ..observability.distrib import TelemetryOutbox

            self.outbox = TelemetryOutbox()
            engine.lifecycle.add_listener(self.outbox.on_event)
        self.lock = threading.RLock()
        self.started = time.time()
        self.draining = False
        self.dead = threading.Event()  # set => main exits the process
        self.exit_code = 0
        self._live: Dict = {}  # rid -> engine Request, evicted on finish
        self._fired_reported: set = set()  # unbounded-ok: subset of the frozen fault plan's finite index set
        self._conns = registry.counter(
            "serving_worker_connections_total",
            "accepted wire connections by role", role="engine",
            replica=str(replica))
        self._conns_ctl = registry.counter(
            "serving_worker_connections_total",
            "accepted wire connections by role", role="control",
            replica=str(replica))

    # --- fault bookkeeping --------------------------------------------------
    def _fired_delta(self):
        fi = self.engine._fault
        if fi is None:
            return []
        fired = set(fi.snapshot().get("fired_plan_indexes", []))
        delta = sorted(fired - self._fired_reported)
        self._fired_reported |= fired
        return delta

    def _drain(self, limit: int = 256) -> Optional[Dict]:
        """Pop a bounded telemetry delta for piggybacking (``None``
        when streaming is off or there is nothing to report)."""
        if self.outbox is None:
            return None
        delta = self.outbox.drain(limit)
        if not delta["events"] and not delta["dropped"]:
            return None
        return delta

    # --- frame handlers -----------------------------------------------------
    def _state(self) -> Dict:
        eng = self.engine
        return {
            "step_seq": int(eng.step_seq),
            "has_work": bool(eng.scheduler.has_work()),
            "queue_depth": int(eng.scheduler.queue_depth),
            "occupancy": float(eng.kv.occupancy()),
            "degraded": bool(eng.audit.degraded),
        }

    def handle_submit(self, frame: Dict) -> Dict:
        from .request import SamplingParams

        if self.draining:
            return wire.error_frame("protocol",
                                    "worker is draining; not admitting")
        sp = frame.get("sampling") or {}
        sampling = SamplingParams(
            max_new_tokens=int(sp.get("max_new_tokens", 16)),
            temperature=float(sp.get("temperature", 0.0)),
            top_k=int(sp.get("top_k", 0)),
            top_p=float(sp.get("top_p", 1.0)),
            eos_token_id=sp.get("eos_token_id"),
            seed=int(sp.get("seed", 0)))
        hashes = frame.get("prefix_hashes")
        if hashes is not None:
            hashes = [bytes.fromhex(h) for h in hashes]
        resume = frame.get("resume_tokens")
        with self.lock:
            req = self.engine.add_request(
                [int(t) for t in frame["prompt_ids"]], sampling=sampling,
                request_id=frame["rid"],
                priority=int(frame.get("priority", 0)),
                trace_id=str(frame.get("trace_id", frame["rid"])),
                prefix_hashes=hashes, slo_ms=frame.get("slo_ms"),
                resume_tokens=([int(t) for t in resume]
                               if resume else None))
            if frame.get("arrival") is not None:
                # migrated request (ISSUE 20): its e2e span starts at
                # the ORIGINAL arrival stamp (perf_counter is
                # CLOCK_MONOTONIC machine-wide, so the donor worker's
                # stamp is valid in this process too)
                req.arrival_time = float(frame["arrival"])
            self._live[frame["rid"]] = req
        return {"type": "submit_ok", "rid": frame["rid"],
                "telemetry": self._drain(limit=64)}

    def handle_abort(self, frame: Dict) -> Dict:
        from .request import FinishReason

        reason = FinishReason(frame.get("reason", "abort"))
        with self.lock:
            ok = self.engine.abort_request(frame["rid"], reason)
            if ok:
                self._live.pop(frame["rid"], None)
        return {"type": "abort_ok", "rid": frame["rid"], "ok": bool(ok),
                "telemetry": self._drain(limit=64)}

    def handle_step(self, conn: wire.Connection,
                    t_recv: Optional[float] = None) -> None:
        """One engine step, ONE reply: ``step_done`` carries the step's
        full emission batch (``emitted``: rid -> [tokens], possibly many
        per row when the engine ran a decode burst — the wire cost of a
        burst is one round-trip regardless of N), the post-step
        state + fired-fault delta + a full metrics dump (the router
        merges it before ticking the shared history, so alert rules see
        fresh cross-process values deterministically), plus — with
        telemetry streaming on — the worker-clock timestamps
        (recv/eng0/eng1/reply) feeding the router's wire-latency
        attribution, the pending lifecycle-event delta, and the step's
        stepprof record.  A step failure sends ``step_error`` and kills
        the process — the supervisor's respawn path owns recovery."""
        if t_recv is None:
            t_recv = time.perf_counter()
        with self.lock:
            eng = self.engine
            if not eng.scheduler.has_work():
                now = time.perf_counter()
                conn.send({"type": "step_done", "stepped": False,
                           "finished": {}, "fired": self._fired_delta(),
                           "metrics": wire.dump_registry(self.registry),
                           "telemetry": self._drain(),
                           "t": {"recv": t_recv, "eng0": now, "eng1": now,
                                 "reply": time.perf_counter()},
                           **self._state()})
                return
            before = {rid: len(req.output_tokens)
                      for rid, req in self._live.items()}
            t_eng0 = time.perf_counter()
            try:
                eng.step()
            except BaseException:
                err = traceback.format_exc()
                try:
                    # final drain: ship everything buffered so the
                    # router's mirror holds the events leading into the
                    # death before this process exits
                    conn.send({"type": "step_error", "error": err,
                               "fired": self._fired_delta(),
                               "telemetry": self._drain(limit=1024),
                               "metrics": wire.dump_registry(
                                   self.registry)})
                except wire.WireError:
                    pass  # swallow-ok: the parent's socket died first; its heartbeat/EOF path already reports this death
                sys.stderr.write(f"[worker {self.replica}] engine step "
                                 f"failed; exiting for respawn:\n{err}")
                self.exit_code = 3
                self.dead.set()
                return
            t_eng1 = time.perf_counter()
            finished: Dict = {}
            emitted: Dict = {}
            for rid, req in list(self._live.items()):
                toks = req.output_tokens
                fresh = toks[before.get(rid, 0):]
                if fresh:
                    emitted[rid] = [int(tok) for tok in fresh]
                if req.finished:
                    finished[rid] = (req.finish_reason.value
                                     if req.finish_reason else None)
                    del self._live[rid]
            conn.send({"type": "step_done", "stepped": True,
                       "emitted": emitted,
                       "finished": finished,
                       "fired": self._fired_delta(),
                       "metrics": wire.dump_registry(self.registry),
                       "telemetry": self._drain(),
                       "step_record": eng.stepprof.last_record(),
                       "t": {"recv": t_recv, "eng0": t_eng0,
                             "eng1": t_eng1,
                             "reply": time.perf_counter()},
                       **self._state()})

    # --- KV hand-off (ISSUE 20) ---------------------------------------------
    def handle_kv_export(self, conn: wire.Connection, frame: Dict) -> None:
        """Serialize a request's computed prompt KV (or a hot prefix
        chain, when ``chain`` is given) and stream it back as
        ``kv_run_begin`` + chunked ``kv_run_chunk`` frames.  An empty /
        untransferable run answers one ``kv_export_ok empty`` frame —
        the router falls back to re-prefill."""
        from . import handoff

        with self.lock:
            try:
                if frame.get("chain") is not None:
                    mb = frame.get("max_blocks")
                    run = handoff.export_prefix_run(
                        self.engine, bytes.fromhex(str(frame["chain"])),
                        max_blocks=(int(mb) if mb is not None else None))
                else:
                    run = handoff.export_request_run(self.engine,
                                                     frame["rid"])
            except Exception as e:
                conn.send(wire.error_frame("protocol",
                                           f"kv export failed: {e}"))
                return
        if run is None:
            conn.send({"type": "kv_export_ok", "empty": True})
            return
        for out in handoff.run_to_frames(run):
            conn.send(out)

    def handle_kv_import(self, conn: wire.Connection, begin: Dict) -> None:
        """Assemble a streamed KV run (the chunk frames follow ``begin``
        on this same strictly-serial connection) and admit it into the
        pool.  Corrupt/truncated streams answer the usual TYPED wire
        errors and the process keeps serving — frame boundaries stay
        intact because the declared chunk count is always consumed."""
        from . import handoff

        chunks = []
        declared = max(0, min(int(begin.get("chunks", 0) or 0), 4096))
        try:
            for _ in range(declared):
                chunks.append(conn.recv())
        except wire.FrameError as e:
            try:
                conn.send(wire.error_frame(e.kind, str(e)))
            except wire.WireError:
                pass  # swallow-ok: peer already gone; recv counted the error
            raise  # connection is desynced mid-stream: let the caller close it
        try:
            run = handoff.run_from_frames(begin, chunks)
            with self.lock:
                placed = handoff.import_run(self.engine, run)
        except wire.FrameError as e:
            conn.send(wire.error_frame(e.kind, str(e)))
            return
        except handoff.HandoffError as e:
            conn.send(wire.error_frame("malformed", str(e)))
            return
        conn.send({"type": "kv_import_ok",
                   "placed": (None if placed is None else int(placed))})

    def handle_kv_detach(self, frame: Dict) -> Dict:
        with self.lock:
            ok = self.engine.detach_request(frame["rid"])
            if ok:
                self._live.pop(frame["rid"], None)
        return {"type": "kv_detach_ok", "rid": frame["rid"],
                "ok": bool(ok)}

    def handle_debug(self, frame: Dict) -> Dict:
        what = frame.get("what")
        eng = self.engine
        with self.lock:
            if what == "audit":
                data = eng.audit.snapshot()
            elif what == "cache":
                data = eng.cachestat.snapshot()
            elif what == "cache_timeline":
                data = eng.cachestat.timeline()
            elif what == "compile_table":
                data = eng.stepprof.compile_table()
            elif what == "compile_totals":
                data = eng.stepprof.compile_totals()
            elif what == "aot":
                data = eng.stepprof.aot_snapshot()
            elif what == "records":
                data = eng.stepprof.records()
            elif what == "metrics":
                data = wire.dump_registry(self.registry)
            elif what == "describe":
                data = {"pid": os.getpid(), "replica": self.replica,
                        "aot_hash": self.aot_hash,
                        "deploy": wire.canonical_deploy(self.deploy),
                        "traces": {
                            "prefill": eng.prefill_trace_count,
                            "decode": eng.decode_trace_count,
                            "ragged": eng.ragged_trace_count,
                            "burst": eng.burst_trace_count},
                        **self._state()}
            else:
                return wire.error_frame(
                    "protocol", f"unknown debug target {what!r}")
        return {"type": "debug_ok", "what": what, "data": data}

    def handle_set_fault(self, frame: Dict) -> Dict:
        from .faultinject import FaultInjector, FaultPlan

        plan_obj = frame.get("plan")
        with self.lock:
            if not plan_obj:
                self.engine.set_fault_injector(None)
                return {"type": "ok"}
            plan = FaultPlan.from_obj(plan_obj)
            fi = FaultInjector(plan, replica=str(self.replica),
                               lifecycle=self.engine.lifecycle,
                               registry=self.registry)
            fi.mark_fired(frame.get("fired") or [])
            self._fired_reported = set(
                fi.snapshot().get("fired_plan_indexes", []))
            self.engine.set_fault_injector(fi)
        return {"type": "ok"}

    # --- connection loops ---------------------------------------------------
    def serve_connection(self, sock: socket.socket) -> None:
        labels = {"replica": str(self.replica)}
        conn = wire.Connection(sock, registry=self.registry,
                               labels=labels, side="worker",
                               max_frame=self.max_frame)
        try:
            conn.settimeout(60.0)
            try:
                hello = conn.recv()
                role = wire.check_hello(hello, self.aot_hash,
                                        deploy=self.deploy)
            except wire.HandshakeMismatch as e:
                conn.count_error(e.code)
                conn.send(wire.error_frame(e.code, str(e)))
                return
            except wire.FrameError as e:
                try:
                    conn.send(wire.error_frame(e.kind, str(e)))
                except wire.WireError:
                    pass  # swallow-ok: peer already gone; the frame error itself was counted by recv
                return
            except wire.ConnectionClosed:
                return  # swallow-ok: counted by recv; a port probe, not a peer
            conn.send({"type": "hello_ok", "version": wire.WIRE_VERSION,
                       "replica": self.replica, "pid": os.getpid(),
                       "aot_hash": self.aot_hash,
                       "deploy": wire.canonical_deploy(self.deploy)})
            (self._conns if role == "engine" else self._conns_ctl).inc()
            conn.settimeout(None)
            while not self.dead.is_set():
                try:
                    frame = conn.recv()
                except wire.ConnectionClosed:
                    return  # swallow-ok: clean peer disconnect at a frame boundary, counted by recv
                except wire.FrameError as e:
                    # per-connection error isolation: answer, close this
                    # connection, keep the process serving others
                    try:
                        conn.send(wire.error_frame(e.kind, str(e)))
                    except wire.WireError:
                        pass  # swallow-ok: peer already gone; the frame error itself was counted by recv
                    return
                self._dispatch(conn, frame)
        except wire.WireError:
            return  # swallow-ok: counted at the Connection layer; connection-scoped by design
        except Exception:
            sys.stderr.write(f"[worker {self.replica}] connection "
                             f"handler failed:\n{traceback.format_exc()}")
        finally:
            conn.close()

    def _dispatch(self, conn: wire.Connection, frame: Dict) -> None:
        # dispatch-entry timestamp: the NTP-style clock probe's t1 and
        # the wire-attribution "recv" stamp (worker monotonic clock)
        t_recv = time.perf_counter()
        t = frame.get("type")
        if t == "step":
            self.handle_step(conn, t_recv)
        elif t == "submit":
            conn.send(self.handle_submit(frame))
        elif t == "abort":
            conn.send(self.handle_abort(frame))
        elif t == "health":
            reply = {"type": "health_ok", "pid": os.getpid(),
                     "step_seq": int(self.engine.step_seq),
                     "draining": self.draining,
                     "uptime_s": round(time.time() - self.started, 3),
                     "telemetry": self._drain(limit=128)}
            if frame.get("t0") is not None:
                # clock-sync probe: echo the router's t0, stamp our
                # receipt (t1) and just-before-send (t2) so the router
                # completes the (t0,t1,t2,t3) NTP sample on receipt
                reply["t0"] = frame["t0"]
                reply["t1"] = t_recv
                reply["t2"] = time.perf_counter()
            conn.send(reply)
        elif t == "kv_export":
            self.handle_kv_export(conn, frame)
        elif t == "hot_prefixes":
            k = frame.get("k")
            with self.lock:
                rows = self.engine.hot_prefixes(
                    int(k) if k is not None else None)
            conn.send({"type": "hot_prefixes_ok", "rows": rows})
        elif t == "kv_run_begin":
            self.handle_kv_import(conn, frame)
        elif t == "kv_detach":
            conn.send(self.handle_kv_detach(frame))
        elif t == "debug":
            conn.send(self.handle_debug(frame))
        elif t == "set_fault":
            conn.send(self.handle_set_fault(frame))
        elif t == "drain":
            self.draining = True
            with self.lock:
                pending = len(self._live)
            conn.send({"type": "drain_ok", "pending": pending})
        elif t == "shutdown":
            conn.send({"type": "ok"})
            self.dead.set()
        else:
            conn.send(wire.error_frame("protocol",
                                       f"unknown frame type {t!r}"))


def main(argv=None) -> int:
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # mirror serving/server.py: the TPU plugin's sitecustomize may
        # pin the platform; override after import
        import jax

        jax.config.update("jax_platforms", "cpu")

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.worker",
        description="one EngineCore replica behind the fleet wire "
                    "protocol (spawned by serving/procfleet.py)")
    p.add_argument("--replica", type=int, default=0)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--spec", default="{}",
                   help="JSON engine spec (layers/num_blocks/block_size/"
                        "scheduler caps/audit/unified...) — must match "
                        "the router's proxy template exactly")
    p.add_argument("--aot-path", default=None,
                   help="boot zero-trace from this shared AOT artifact; "
                        "its manifest model_hash becomes the handshake "
                        "hash the router must present")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="JAX persistent compilation cache dir: sibling "
                        "workers compile each program once machine-wide")
    p.add_argument("--warm", action="store_true",
                   help="execute every loaded AOT program once at boot "
                        "(first request wave pays zero lazy compiles; "
                        "with --compile-cache the compiles land in the "
                        "shared cache at boot)")
    p.add_argument("--max-frame", type=int, default=wire.MAX_FRAME_BYTES)
    args = p.parse_args(argv)

    t0 = time.perf_counter()
    import jax

    if args.compile_cache:
        # BEFORE anything compiles: every compile this process performs
        # lands in (or is served from) the shared machine-wide cache.
        # The min-compile-time / min-entry-size floors default to values
        # tuned for real models — the toy programs compile in
        # milliseconds, so both floors must drop to 0 or nothing would
        # ever be cached.
        os.makedirs(args.compile_cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", args.compile_cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    entries_before = _count_cache_entries(args.compile_cache)

    from ..observability.metrics import MetricsRegistry

    registry = MetricsRegistry()
    spec = json.loads(args.spec)
    aot = None
    aot_hash = None
    if args.aot_path:
        from .aot import AotArtifact

        aot = AotArtifact.load(args.aot_path)
        aot_hash = aot.manifest["model_hash"]
    engine = build_engine(spec, args.replica, registry, aot=aot)
    if args.warm and aot is not None:
        wall = aot.warm(registry=registry,
                        labels={"replica": str(args.replica)})
        print(f"[worker {args.replica}] warmed {aot.program_count} "
              f"program(s) in {wall:.3f}s", flush=True)
    entries_after = _count_cache_entries(args.compile_cache)
    if args.compile_cache:
        print(f"{CACHE_PREFIX} dir={args.compile_cache} "
              f"entries_before={entries_before} "
              f"entries_after={entries_after}", flush=True)
    boot_s = time.perf_counter() - t0
    registry.gauge("serving_worker_boot_seconds",
                   "worker process boot wall (imports + engine build + "
                   "artifact load + optional warm)",
                   replica=str(args.replica)).set(boot_s)

    spec_cfg = getattr(engine, "spec", None)
    host = WorkerHost(engine, registry, args.replica, aot_hash,
                      args.max_frame,
                      telemetry=bool(spec.get("telemetry", False)),
                      deploy={"mp": int(engine.mp),
                              "spec": (spec_cfg.config.manifest_dict()
                                       if spec_cfg is not None else None),
                              "role": engine.engine_config.role})
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind((args.host, args.port))
    server.listen(16)
    port = server.getsockname()[1]
    print(f"{READY_PREFIX} port={port} pid={os.getpid()} "
          f"aot_hash={aot_hash} boot_s={boot_s:.3f}", flush=True)

    def _accept_loop() -> None:
        while not host.dead.is_set():
            try:
                sock, _addr = server.accept()
            except OSError:
                return  # swallow-ok: listener closed during shutdown
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=host.serve_connection, args=(sock,),
                             daemon=True).start()

    acceptor = threading.Thread(target=_accept_loop, daemon=True)
    acceptor.start()
    try:
        host.dead.wait()
    except KeyboardInterrupt:
        pass  # swallow-ok: Ctrl-C is a normal operator stop; the finally below closes the listener
    finally:
        try:
            server.close()
        except OSError:
            pass  # swallow-ok: closing an already-dead listener during shutdown
    return host.exit_code


if __name__ == "__main__":
    sys.exit(main())
