"""Prefill/decode KV-cache hand-off (ISSUE 20 tentpole).

The block transfer core between two replicas' pools: a **KV run** is the
serialized form of a leading block chain — the PR 4 chain-hash records
(:meth:`~paddle_tpu.ops.paged_attention.BlockPool.export_blocks` /
``export_chain``) plus the gathered device payload of those pages and a
SHA-256 digest over it.  A donor replica builds a run with
:func:`export_request_run` (a migrating request's computed prompt KV) or
:func:`export_prefix_run` (a heat-table-hot prefix, ISSUE 20 satellite);
the recipient admits it with :func:`import_run`, which

* re-checks the pool compatibility header (block size, layer count, KV
  heads, head dim, dtype) — a mismatch raises :class:`HandoffError`;
* re-verifies the payload digest — transport corruption raises
  :class:`HandoffError` before anything mutates;
* hands the block records to ``BlockPool.import_blocks`` (which
  re-verifies the token chain from the hash root and either places every
  fresh block atomically or refuses with ``None``), then scatters the
  payload into exactly the freshly-placed pages.

Everything here is EAGER host/device work — no traced program runs, so
hand-off provably adds zero jit traces, zero new buckets, and leaves AOT
artifacts untouched (the unit tests assert the engine's trace counters
and bucket sets across export+import).

Cross-process, the same run ships as ``wire.py`` block-stream frames
(``kv_run_begin`` + chunked base64 ``kv_run_chunk``), converted by
:func:`run_to_frames` / :func:`run_from_frames`.

A refused or failed import is never a lost request: callers fall back to
re-prefill on the recipient (the prompt tokens always travel with the
request), so hand-off is strictly an optimization layer.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.paged_attention import shard_kv_pool
from . import wire

HANDOFF_VERSION = 1

# metric names this module owns (tools/check_metrics_docs lints that
# each appears in README's metrics table); registered by the fleet
# router / process fleet via register_handoff_metrics
METRIC_NAMES = (
    "serving_handoff_total",
    "serving_handoff_seconds",
    "serving_handoff_blocks",
)

_SECONDS_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5)
_BLOCKS_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


class HandoffError(RuntimeError):
    """A KV run that cannot be admitted: deployment-shape mismatch,
    digest/content verification failure, or a malformed run.  Typed so
    the fleet/worker layers answer with a typed error and fall back to
    recompute instead of dying — hand-off failures degrade, never lose
    requests."""


def register_handoff_metrics(registry, labels: Optional[Dict] = None):
    """Pre-register the ``serving_handoff_*`` family on ``registry`` and
    return ``{"total", "seconds", "blocks"}`` handles (the router bumps
    them per completed hand-off)."""
    labels = dict(labels or {})
    return {
        "total": registry.counter(
            "serving_handoff_total",
            "completed prefill→decode KV hand-offs (role-aware fleet "
            "migrations at the first-token boundary)", **labels),
        "seconds": registry.histogram(
            "serving_handoff_seconds",
            "end-to-end hand-off duration: export + transfer + verified "
            "import", buckets=_SECONDS_BUCKETS, **labels),
        "blocks": registry.histogram(
            "serving_handoff_blocks",
            "KV blocks shipped per hand-off", buckets=_BLOCKS_BUCKETS,
            **labels),
    }


# --- run construction (donor side) ------------------------------------------
def pool_meta(engine) -> Dict:
    """The pool-compatibility header both ends must agree on before any
    page content moves."""
    cfg = engine.model.config
    return {
        "version": HANDOFF_VERSION,
        "block_size": int(engine.block_size),
        "layers": int(cfg.num_hidden_layers),
        "kv_heads": int(cfg.num_key_value_heads),
        "head_dim": int(cfg.head_dim),
        "dtype": str(np.dtype(engine._pool_dtype)),
    }


def build_run(engine, records: List[dict]) -> Dict:
    """Gather the device payload for ``records`` (the
    ``BlockPool.export_blocks`` record shape) into one serialized run.
    Pure read on the donor: no pool mutation, no refcount change.  The
    per-layer gathers are eager ``take`` ops — at mp>1 the head-sharded
    pools are device_get-assembled into the GLOBAL (unsharded) payload,
    so donor and recipient need not share a mesh layout."""
    idx = np.asarray([r["block"] for r in records], dtype=np.int32)
    k = np.stack([np.asarray(jax.device_get(p[idx]))
                  for p in engine._k_pools])
    v = np.stack([np.asarray(jax.device_get(p[idx]))
                  for p in engine._v_pools])
    payload = np.ascontiguousarray(np.stack([k, v]))
    run = pool_meta(engine)
    run["blocks"] = [{"hash": r["hash"], "depth": int(r["depth"]),
                      "tokens": tuple(int(t) for t in r["tokens"])}
                     for r in records]
    run["payload"] = payload
    run["digest"] = hashlib.sha256(payload.tobytes()).digest()
    run["tokens_total"] = len(records) * engine.block_size
    return run


def export_request_run(engine, request_id) -> Optional[Dict]:
    """Serialize the hashed leading blocks of ``request_id``'s KV (the
    computed prompt prefix a decode specialist can resume from).
    ``None`` when nothing is transferable (no table, nothing hashed yet)
    — the caller just re-prefills at the destination."""
    kv = engine.kv
    if not kv.has(request_id):
        return None
    hashes = []
    for b in kv.table(request_id):
        h = kv.block_chain_hash(b)
        if h is None:
            break
        hashes.append(h)
    if not hashes:
        return None
    records = kv.export_blocks(hashes)
    if not records:
        return None
    return build_run(engine, records)


def export_prefix_run(engine, chain_hash: bytes,
                      max_blocks: Optional[int] = None) -> Optional[Dict]:
    """Serialize the full leading chain addressed by its DEEPEST digest
    (the prefix-heat table's key) — the hot-prefix migration entry
    point.  ``max_blocks`` bounds the shipped run (leading blocks win:
    the shortest prefixes are the most shareable).  ``None`` when the
    chain is broken (an ancestor was evicted since the heat sample)."""
    records = engine.kv.export_chain(chain_hash)
    if not records:
        return None
    if max_blocks is not None and len(records) > max_blocks:
        records = records[:max_blocks]
    return build_run(engine, records)


# --- run admission (recipient side) -----------------------------------------
def import_run(engine, run: Dict) -> Optional[int]:
    """Admit a KV run into ``engine``'s pool: verify the compatibility
    header and payload digest (:class:`HandoffError` on any mismatch —
    the pool is untouched), place the fresh blocks atomically through
    ``BlockPool.import_blocks``, then scatter the payload into exactly
    those pages and re-apply the pool sharding.  Returns the number of
    freshly-placed blocks (0 = everything was already cached here), or
    ``None`` on a capacity refusal — the caller re-prefills.  Eager ops
    only: trace counters and bucket sets provably do not move."""
    meta = pool_meta(engine)
    if int(run.get("version", -1)) != HANDOFF_VERSION:
        raise HandoffError(
            f"kv run version {run.get('version')!r}, this engine speaks "
            f"{HANDOFF_VERSION}")
    for key in ("block_size", "layers", "kv_heads", "head_dim", "dtype"):
        if run.get(key) != meta[key]:
            raise HandoffError(
                f"kv run {key}={run.get(key)!r} does not match this "
                f"pool's {key}={meta[key]!r} — donor and recipient must "
                "share one deployment shape")
    records = run.get("blocks") or []
    if not records:
        return 0
    payload = np.asarray(run["payload"])
    if hashlib.sha256(payload.tobytes()).digest() != run.get("digest"):
        raise HandoffError(
            "kv run payload fails SHA-256 digest verification — "
            "refusing corrupted content")
    expect = (2, meta["layers"], len(records), meta["block_size"],
              meta["kv_heads"], meta["head_dim"])
    if tuple(payload.shape) != expect:
        raise HandoffError(
            f"kv run payload shape {tuple(payload.shape)} does not "
            f"match its block records (expected {expect})")
    try:
        placed = engine.kv.import_blocks(records)
    except ValueError as e:
        raise HandoffError(f"kv run rejected by the pool: {e}") from e
    if placed is None:
        return None
    if not placed:
        return 0
    src = [i for i, r in enumerate(records) if r["hash"] in placed]
    dst = [placed[records[i]["hash"]] for i in src]
    src_ix = np.asarray(src, dtype=np.int32)
    dst_ix = jnp.asarray(np.asarray(dst, dtype=np.int32))
    dtype = engine._pool_dtype
    engine._k_pools = tuple(
        shard_kv_pool(p.at[dst_ix].set(
            jnp.asarray(payload[0, l][src_ix], dtype=dtype)))
        for l, p in enumerate(engine._k_pools))
    engine._v_pools = tuple(
        shard_kv_pool(p.at[dst_ix].set(
            jnp.asarray(payload[1, l][src_ix], dtype=dtype)))
        for l, p in enumerate(engine._v_pools))
    return len(placed)


# --- wire form ---------------------------------------------------------------
def run_to_frames(run: Dict) -> List[Dict]:
    """A run's ``wire.py`` block-stream frames: ``kv_run_begin`` plus
    chunked ``kv_run_chunk`` frames, each under ``MAX_FRAME_BYTES``."""
    payload = np.ascontiguousarray(np.asarray(run["payload"]))
    meta = {k: run[k] for k in ("version", "block_size", "layers",
                                "kv_heads", "head_dim", "dtype",
                                "tokens_total")}
    meta["shape"] = [int(s) for s in payload.shape]
    blocks = [[r["hash"].hex(), int(r["depth"]),
               [int(t) for t in r["tokens"]]] for r in run["blocks"]]
    return wire.kv_run_frames(meta, blocks, payload.tobytes(),
                              run["digest"].hex())


def run_from_frames(begin: Dict, chunks: List[Dict]) -> Dict:
    """Rebuild a run from its wire frames.  Frame-protocol violations
    (missing/misordered chunks, bad base64, byte shortfall) raise
    :class:`wire.FrameError` with the usual typed kinds; a structurally
    valid run that lies about its own shape raises
    :class:`HandoffError` (and the digest check in :func:`import_run`
    still guards the content)."""
    payload_bytes = wire.kv_run_assemble(begin, chunks)
    meta = begin.get("meta") or {}
    try:
        arr = np.frombuffer(
            payload_bytes, dtype=np.dtype(str(meta["dtype"]))
        ).reshape([int(s) for s in meta["shape"]])
        blocks = [{"hash": bytes.fromhex(h), "depth": int(d),
                   "tokens": tuple(int(t) for t in toks)}
                  for h, d, toks in begin.get("blocks") or []]
        digest = bytes.fromhex(str(begin.get("digest", "")))
    except (KeyError, TypeError, ValueError) as e:
        raise HandoffError(f"undecodable kv run frames: {e}") from e
    run = {k: meta.get(k) for k in ("version", "block_size", "layers",
                                    "kv_heads", "head_dim", "dtype",
                                    "tokens_total")}
    run["blocks"] = blocks
    run["payload"] = arr
    run["digest"] = digest
    return run
