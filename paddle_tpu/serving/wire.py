"""Cross-process fleet wire protocol (ISSUE 16 tentpole (a)).

Length-prefixed JSON frames over localhost sockets: every frame is a
4-byte big-endian payload length followed by one UTF-8 JSON object
carrying a ``"type"`` key.  The protocol is deliberately boring — the
interesting contracts are the FAILURE shapes, because the router's
self-healing machinery (PR 11) keys off them:

* **versioned handshake** — the first frame on every connection is a
  ``hello`` carrying :data:`WIRE_VERSION`, the connection role
  (``engine`` drives submit/abort/step; ``control`` drives
  health/debug/drain), and the AOT manifest hash the client expects the
  worker to serve from.  A version or manifest-hash mismatch is answered
  with an ``error`` frame and a closed CONNECTION — the worker process
  stays alive (a stale router must not take down a healthy replica);
* **per-connection error isolation** — malformed JSON, a truncated
  frame, or an oversized length prefix poisons only the connection it
  arrived on (best-effort ``error`` frame, then close).  Every such
  failure is counted under ``serving_wire_errors_total{kind=...}``;
* **clean vs dirty EOF** — EOF on a frame boundary raises
  :class:`ConnectionClosed` (a graceful hangup); EOF mid-header or
  mid-payload raises :class:`FrameError` kind ``truncated`` (the peer
  died mid-frame — exactly what a ``kill -9`` looks like from the
  router's side, and what flips a :class:`~paddle_tpu.serving.procfleet.
  WorkerEngineProxy` into its death path).

Frame vocabulary (see ``serving/worker.py`` for server-side semantics):
``hello``/``hello_ok``, ``submit``/``submit_ok``, ``abort``/``abort_ok``,
``step`` → zero or more streamed ``token`` frames then ``step_done`` (or
``step_error``), ``health``/``health_ok``, ``drain``/``drain_ok``,
``debug``/``debug_ok``, ``set_fault``/``ok``, ``shutdown``/``ok``,
``error``.

Telemetry piggybacking (ISSUE 17, all fields OPTIONAL — a reply
without them is valid, so mixed router/worker versions interoperate):

* ``step_done``/``step_error``/``submit_ok``/``abort_ok``/``health_ok``
  may carry ``telemetry`` — a bounded, sequence-numbered delta of the
  worker engine's lifecycle events (``{"events": [...], "dropped": n}``)
  the router merges idempotently
  (:class:`~paddle_tpu.observability.distrib.DeltaMerger`);
* ``step_done`` may carry ``t`` — worker-clock timestamps
  ``{"recv","eng0","eng1","reply"}`` feeding the router's
  host-vs-wire-vs-engine attribution
  (:class:`~paddle_tpu.observability.distrib.WireStats`) — and
  ``step_record``, the worker's stepprof record for the step;
* a ``health`` frame may carry ``t0`` (router clock); the worker echoes
  it on ``health_ok`` with ``t1`` (receipt) and ``t2`` (just before
  send), completing an NTP-style ``(t0,t1,t2,t3)`` clock-sync sample
  (:class:`~paddle_tpu.observability.distrib.ClockSync`).
"""

from __future__ import annotations

import base64
import json
import math
import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

WIRE_VERSION = 1

# worker boot-protocol stdout markers (canonical home here so the
# router side never imports the worker module — `python -m
# paddle_tpu.serving.worker` must own it as __main__)
READY_PREFIX = "PADDLE_TPU_WORKER_READY"
CACHE_PREFIX = "PADDLE_TPU_COMPILE_CACHE"

# one frame carries at most this many payload bytes (a step_done frame
# embeds a full worker metrics dump — generous, but bounded: a length
# prefix past this is hostile/corrupt, not big)
MAX_FRAME_BYTES = 8 << 20

_HEADER = struct.Struct(">I")

# metric names this module owns (tools/check_metrics_docs lints that
# each appears in README's metrics table)
METRIC_NAMES = (
    "serving_wire_frames_total",
    "serving_wire_errors_total",
)

# bounded error-kind label vocabulary for serving_wire_errors_total
ERROR_KINDS = ("closed", "truncated", "oversized", "malformed",
               "version_mismatch", "aot_mismatch", "deploy_mismatch",
               "protocol", "io")


class WireError(RuntimeError):
    """Base class for wire-protocol failures."""

    kind = "io"


class ConnectionClosed(WireError):
    """EOF on a frame boundary: the peer hung up cleanly."""

    kind = "closed"


class FrameError(WireError):
    """A frame that cannot be decoded: truncated (EOF mid-frame — the
    ``kill -9`` signature), oversized (length prefix past the cap), or
    malformed (not a JSON object with a ``type``)."""

    def __init__(self, kind: str, detail: str):
        super().__init__(f"{kind}: {detail}")
        self.kind = kind


class HandshakeMismatch(WireError):
    """The two ends disagree on protocol version or AOT manifest hash —
    answered with an ``error`` frame; the connection dies, the worker
    does not."""

    def __init__(self, code: str, detail: str):
        super().__init__(f"{code}: {detail}")
        self.kind = code
        self.code = code


def error_frame(code: str, detail: str) -> Dict:
    return {"type": "error", "code": str(code), "detail": str(detail)[:2000]}


def hello_frame(role: str, aot_hash: Optional[str],
                deploy: Optional[Dict] = None) -> Dict:
    """``deploy`` is the caller's deployment identity (ISSUE 18 fleet
    satellite): ``{"mp": int, "spec": manifest_dict|None}``.  ``None``
    means "default single-chip, spec off" — an old peer that never sends
    the field is indistinguishable from one that runs the defaults,
    which is exactly the interop we want."""
    return {"type": "hello", "version": WIRE_VERSION, "role": role,
            "aot_hash": aot_hash, "deploy": deploy}


def canonical_deploy(deploy: Optional[Dict]) -> Optional[Dict]:
    """Normalize a deployment-identity dict for comparison: the default
    shape (mp=1, spec decoding off, unified role) collapses to ``None``
    so a peer that predates the field and one that runs the defaults
    agree.  ``role`` (ISSUE 20) rides the same rule: ``"unified"`` (or
    absent) drops out of the dict, so a role-less old peer and a
    unified-role new peer still shake hands."""
    if not deploy:
        return None
    out = {"mp": int(deploy.get("mp", 1) or 1),
           "spec": deploy.get("spec") or None}
    role = str(deploy.get("role") or "unified")
    if role != "unified":
        out["role"] = role
    if out["mp"] == 1 and out["spec"] is None and "role" not in out:
        return None
    if out["spec"] is not None:
        # JSON round-trips must compare equal: coerce the manifest's
        # values through int (they are all counts/flags by contract)
        out["spec"] = {str(k): int(v) for k, v in out["spec"].items()}
    return out


def check_hello(frame: Dict, aot_hash: Optional[str],
                deploy: Optional[Dict] = None) -> str:
    """Worker-side handshake validation: returns the connection role or
    raises :class:`HandshakeMismatch` (the caller answers with
    :func:`error_frame` and closes the connection — never the process)."""
    if not isinstance(frame, dict) or frame.get("type") != "hello":
        raise HandshakeMismatch(
            "protocol", f"expected a hello frame, got "
                        f"{frame.get('type') if isinstance(frame, dict) else frame!r}")
    if frame.get("version") != WIRE_VERSION:
        raise HandshakeMismatch(
            "version_mismatch",
            f"peer speaks wire version {frame.get('version')!r}, this "
            f"worker speaks {WIRE_VERSION}")
    theirs = frame.get("aot_hash") or None
    ours = aot_hash or None
    if theirs != ours:
        raise HandshakeMismatch(
            "aot_mismatch",
            f"peer expects AOT manifest hash {str(theirs)[:16]!r}, this "
            f"worker serves {str(ours)[:16]!r} — the router and worker "
            "must share ONE artifact")
    their_dep = canonical_deploy(frame.get("deploy"))
    our_dep = canonical_deploy(deploy)
    if their_dep != our_dep:
        # mesh-slice shape (mp) or spec-decoding config drift between
        # the router and a worker: refuse the CONNECTION, exactly like
        # an aot_mismatch — a typed, connection-scoped rejection the
        # supervisor can see, never a poisoned half-configured fleet
        raise HandshakeMismatch(
            "deploy_mismatch",
            f"peer deploys {their_dep!r}, this worker deploys "
            f"{our_dep!r} — mp degree and spec-decoding config must "
            "match fleet-wide")
    role = frame.get("role")
    if role not in ("engine", "control"):
        raise HandshakeMismatch(
            "protocol", f"unknown connection role {role!r} "
                        "(expected 'engine' or 'control')")
    return role


class Connection:
    """One framed socket endpoint.  Sends are serialized under a lock
    (the control connection is shared by the heartbeat thread and HTTP
    debug handlers); receives are single-reader by convention.  When a
    registry is supplied, traffic lands on
    ``serving_wire_frames_total{direction,side,...}`` and failures on
    ``serving_wire_errors_total{kind,side,...}``."""

    def __init__(self, sock: socket.socket, registry=None,
                 labels: Optional[Dict[str, str]] = None,
                 side: str = "router", max_frame: int = MAX_FRAME_BYTES):
        self._sock = sock
        self._wlock = threading.Lock()
        self.max_frame = int(max_frame)
        self._registry = registry
        self._labels = dict(labels or {})
        self._labels["side"] = side
        self._tx = self._rx = None
        if registry is not None:
            self._tx = registry.counter(
                "serving_wire_frames_total",
                "frames sent/received on fleet wire connections",
                direction="tx", **self._labels)
            self._rx = registry.counter(
                "serving_wire_frames_total",
                "frames sent/received on fleet wire connections",
                direction="rx", **self._labels)

    def count_error(self, kind: str) -> None:
        if self._registry is not None:
            if kind not in ERROR_KINDS:
                kind = "io"
            self._registry.counter(
                "serving_wire_errors_total",
                "wire-protocol failures by kind (truncated/oversized/"
                "malformed frames, handshake mismatches, socket errors)",
                kind=kind, **self._labels).inc()

    # --- framed I/O ---------------------------------------------------------
    def send(self, obj: Dict) -> None:
        try:
            payload = json.dumps(obj).encode("utf-8")
        except (TypeError, ValueError) as e:
            raise FrameError("malformed", f"unserializable frame: {e}")
        if len(payload) > self.max_frame:
            self.count_error("oversized")
            raise FrameError(
                "oversized", f"frame of {len(payload)} bytes exceeds the "
                             f"{self.max_frame}-byte cap")
        try:
            with self._wlock:
                self._sock.sendall(_HEADER.pack(len(payload)) + payload)
        except OSError as e:
            self.count_error("io")
            raise WireError(f"send failed: {e}") from e
        if self._tx is not None:
            self._tx.inc()

    def _recv_exact(self, n: int, boundary: bool) -> bytes:
        buf = b""
        while len(buf) < n:
            try:
                chunk = self._sock.recv(n - len(buf))
            except socket.timeout:
                raise
            except OSError as e:
                self.count_error("io")
                raise WireError(f"recv failed: {e}") from e
            if not chunk:
                if boundary and not buf:
                    self.count_error("closed")
                    raise ConnectionClosed("peer closed the connection")
                self.count_error("truncated")
                raise FrameError(
                    "truncated",
                    f"EOF after {len(buf)}/{n} bytes — the peer died "
                    "mid-frame")
            buf += chunk
        return buf

    def recv(self) -> Dict:
        header = self._recv_exact(_HEADER.size, boundary=True)
        (length,) = _HEADER.unpack(header)
        if length > self.max_frame:
            self.count_error("oversized")
            raise FrameError(
                "oversized", f"length prefix {length} exceeds the "
                             f"{self.max_frame}-byte cap")
        payload = self._recv_exact(length, boundary=False)
        try:
            obj = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            self.count_error("malformed")
            raise FrameError("malformed", f"undecodable frame: {e}")
        if not isinstance(obj, dict) or "type" not in obj:
            self.count_error("malformed")
            raise FrameError(
                "malformed", "frame is not a JSON object with a 'type'")
        if self._rx is not None:
            self._rx.inc()
        return obj

    def request(self, obj: Dict) -> Dict:
        """One call-response round trip (caller guarantees exclusive use
        of the connection for the duration — the proxy's locks do)."""
        self.send(obj)
        return self.recv()

    def settimeout(self, s: Optional[float]) -> None:
        self._sock.settimeout(s)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass  # swallow-ok: closing a dead socket; the connection is being discarded either way


def connect(host: str, port: int, role: str, aot_hash: Optional[str],
            registry=None, labels: Optional[Dict[str, str]] = None,
            side: str = "router", timeout: Optional[float] = 30.0,
            max_frame: int = MAX_FRAME_BYTES,
            deploy: Optional[Dict] = None) -> Connection:
    """Dial a worker and complete the client half of the handshake.
    Raises :class:`HandshakeMismatch` when the worker answers with an
    ``error`` frame (version/AOT-hash disagreement)."""
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    conn = Connection(sock, registry=registry, labels=labels, side=side,
                      max_frame=max_frame)
    conn.settimeout(timeout)
    try:
        reply = conn.request(hello_frame(role, aot_hash, deploy=deploy))
    except WireError:
        conn.close()
        raise
    if reply.get("type") == "error":
        code = str(reply.get("code", "protocol"))
        conn.count_error(code if code in ERROR_KINDS else "protocol")
        conn.close()
        raise HandshakeMismatch(code, str(reply.get("detail", "")))
    if reply.get("type") != "hello_ok":
        conn.count_error("protocol")
        conn.close()
        raise FrameError("protocol",
                         f"expected hello_ok, got {reply.get('type')!r}")
    conn.settimeout(None)
    return conn


# --- KV block-stream frames (ISSUE 20) --------------------------------------
# A KV run (serving/handoff.py) ships as one ``kv_run_begin`` frame —
# block metadata (chain-hash hex, depth, tokens), payload digest, byte
# count, chunk count — followed by exactly ``chunks`` base64
# ``kv_run_chunk`` frames.  Raw chunks are capped well under
# MAX_FRAME_BYTES so the base64 expansion (4/3) plus JSON overhead never
# trips the oversized guard.
KV_CHUNK_BYTES = 4 << 20


def kv_run_frames(meta: Dict, blocks: List, payload: bytes,
                  digest_hex: str) -> List[Dict]:
    """Frame a serialized KV run for the wire: ``meta`` is the pool
    compatibility header, ``blocks`` the JSON-able block records
    (``[hash_hex, depth, [tokens...]]`` rows), ``payload`` the raw
    gathered KV bytes."""
    chunks = [payload[i:i + KV_CHUNK_BYTES]
              for i in range(0, len(payload), KV_CHUNK_BYTES)] or [b""]
    frames: List[Dict] = [{
        "type": "kv_run_begin", "meta": dict(meta), "blocks": blocks,
        "digest": str(digest_hex), "bytes": len(payload),
        "chunks": len(chunks)}]
    for i, c in enumerate(chunks):
        frames.append({"type": "kv_run_chunk", "seq": i,
                       "data": base64.b64encode(c).decode("ascii")})
    return frames


def kv_run_assemble(begin: Dict, chunks: List[Dict]) -> bytes:
    """Reassemble a KV run's payload bytes from its frames, validating
    the chunk protocol: mistyped/misordered chunks raise
    :class:`FrameError` kind ``protocol``, undecodable base64 kind
    ``malformed``, and a byte-count shortfall kind ``truncated`` — the
    same typed vocabulary every other frame failure uses, so the worker
    answers with a typed error and SURVIVES."""
    if begin.get("type") != "kv_run_begin":
        raise FrameError(
            "protocol",
            f"expected kv_run_begin, got {begin.get('type')!r}")
    want = int(begin.get("chunks", 0))
    if len(chunks) != want:
        raise FrameError(
            "truncated",
            f"kv run carries {len(chunks)} of {want} chunk frame(s)")
    parts: List[bytes] = []
    for i, fr in enumerate(chunks):
        if fr.get("type") != "kv_run_chunk" or int(fr.get("seq", -1)) != i:
            raise FrameError(
                "protocol",
                f"kv run chunk {i} is mistyped or out of order")
        try:
            parts.append(base64.b64decode(fr.get("data", ""),
                                          validate=True))
        except (ValueError, TypeError) as e:
            raise FrameError(
                "malformed", f"kv run chunk {i} is not valid base64: {e}")
    payload = b"".join(parts)
    if len(payload) != int(begin.get("bytes", -1)):
        raise FrameError(
            "truncated",
            f"kv run payload is {len(payload)} bytes, the header "
            f"promised {begin.get('bytes')}")
    return payload


# --- registry dump/merge shapes ---------------------------------------------
def dump_registry(registry) -> List[Dict]:
    """JSON-able dump of every series in ``registry``, exact enough for
    the router to merge losslessly: counters ship their value (the
    router applies monotonic deltas), gauges ship their full streaming
    aggregate, histograms ship their NON-cumulative bucket counts so the
    router can merge them bucket-by-bucket (no quantile re-derivation).
    Collect hooks run first, matching every other rendering path."""
    registry.run_collect_hooks()
    rows: List[Dict] = []
    for m in registry.series():
        row = {"name": m.name, "kind": m.kind, "help": m.help,
               "labels": [list(kv) for kv in m.labels]}
        if m.kind == "counter":
            row["value"] = m.value
        elif m.kind == "gauge":
            with m._lock:
                row.update(value=m._value, samples=m.samples,
                           total=m.total,
                           max=None if m.samples == 0 else m.max,
                           min=None if m.samples == 0 else m.min)
        elif m.kind == "histogram":
            with m._lock:
                row.update(bounds=list(m.bounds), counts=list(m._counts),
                           count=m.count, sum=m.sum,
                           max=None if m.count == 0 else m.max,
                           min=None if m.count == 0 else m.min)
        else:
            continue
        rows.append(row)
    return rows


class RegistryMerger:
    """Applies one worker's :func:`dump_registry` rows into the router's
    registry.  Per-(series) delta state makes counter/histogram merges
    idempotent-monotonic: re-sent values add nothing, and a RESPAWNED
    worker (fresh process, counters back at zero) simply contributes
    fresh deltas — accumulated fleet history is never regressed.  One
    merger per worker incarnation (the proxy builds a new one per
    spawn), so the delta baselines reset exactly when the worker's
    counters do.

    Only rows carrying this replica's ``replica`` label are merged: the
    worker exclusively owns those series fleet-wide, which is what makes
    verbatim gauge copies and bucket-exact histogram merges correct.
    Unlabeled worker-local series (its private lifecycle tracker, ...)
    stay worker-local."""

    def __init__(self, registry, replica_label: str):
        self._registry = registry
        self._replica = str(replica_label)
        self._last_counter: Dict = {}    # unbounded-ok: keyed by the worker's bounded (max_series-capped) series set
        self._last_hist: Dict = {}       # unbounded-ok: keyed by the worker's bounded (max_series-capped) series set
        self.errors = 0

    def merge(self, rows: List[Dict]) -> None:
        for row in rows:
            try:
                self._merge_row(row)
            except Exception:
                # a malformed row must not poison the rest of the dump;
                # surfaced as a counted error the tests assert on
                self.errors += 1
                self._registry.counter(
                    "serving_wire_errors_total",
                    "wire-protocol failures by kind",
                    kind="malformed", side="router",
                    replica=self._replica).inc()

    def _merge_row(self, row: Dict) -> None:
        labels = {str(k): str(v) for k, v in (row.get("labels") or [])}
        if labels.get("replica") != self._replica:
            return
        name, kind = row["name"], row["kind"]
        key = (name, tuple(sorted(labels.items())))
        help = row.get("help", "")
        if kind == "counter":
            c = self._registry.counter(name, help, **labels)
            v = float(row["value"])
            delta = v - self._last_counter.get(key, 0.0)
            if delta > 0:
                c.inc(delta)
            self._last_counter[key] = v
        elif kind == "gauge":
            g = self._registry.gauge(name, help, **labels)
            with g._lock:
                g._value = float(row["value"])
                g.samples = int(row["samples"])
                g.total = float(row["total"])
                g.max = (-math.inf if row["max"] is None
                         else float(row["max"]))
                g.min = (math.inf if row["min"] is None
                         else float(row["min"]))
        elif kind == "histogram":
            bounds = tuple(float(b) for b in row["bounds"])
            h = self._registry.histogram(name, help, buckets=bounds,
                                         **labels)
            if tuple(h.bounds) != bounds:
                raise ValueError(f"bucket bounds drifted for {name}")
            counts = [int(c) for c in row["counts"]]
            lastc, lastn, lasts = self._last_hist.get(
                key, ([0] * len(counts), 0, 0.0))
            with h._lock:
                for i in range(min(len(counts), len(h._counts))):
                    h._counts[i] += max(0, counts[i] - lastc[i])
                h.count += max(0, int(row["count"]) - lastn)
                h.sum += max(0.0, float(row["sum"]) - lasts)
                if row["max"] is not None:
                    h.max = max(h.max, float(row["max"]))
                if row["min"] is not None:
                    h.min = min(h.min, float(row["min"]))
            self._last_hist[key] = (counts, int(row["count"]),
                                    float(row["sum"]))
