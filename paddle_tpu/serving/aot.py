"""AOT serving artifacts: zero-trace engine boot (ISSUE 15 tentpole).

Compile time is the measured majority of cold-phase wall time
(``serving_compile_seconds_total``, ``GET /v1/debug/compiles``), and the
self-healing fleet (PR 11) pays it again on every replica rebuild.  The
bucketed fixed-shape discipline that bounds the compile COUNT also makes
the whole program set **enumerable up front**: every shape the engine
can ever dispatch is a point in a small power-of-two lattice derived
from the deployment config (pool capacity, scheduler caps, chunk
budgets).  This module closes the loop the ROADMAP names — MPK's
compile-once artifact (PAPERS.md #5), the deployment shape the
Julia-to-TPU work (#4) and the repo's own 8B proof (AOT_8B.md) already
validated:

* :func:`enumerate_buckets` walks that closed universe — the legacy
  three program families (one-shot ``prefill`` / ``chunk``\\ ed prefill /
  batched ``decode``), or the single ``ragged`` family when the engine
  serves ``EngineConfig.unified_step=True``;
* :meth:`AotArtifact.save` lowers each (program, bucket) through
  ``jax.export`` — the engine's OWN jitted entry points, mesh-spanning
  in/out shardings included, traced abstractly (no weights move) — and
  serializes StableHLO programs plus a versioned **manifest** (framework
  + jax versions, platform, model-config hash, mp degree, pool/dtype
  geometry, scheduler caps, bucket sets, kernel-routing/autotune
  decisions) into an artifact directory;
* :meth:`AotArtifact.load` deserializes every program eagerly (a corrupt
  artifact fails at load, not mid-request) and
  :meth:`AotArtifact.validate` applies the **mismatch matrix**: wrong mp
  degree, bucket set, model hash, pool geometry, dtype, kernel routing,
  unified flag, platform or jax version all raise
  :class:`AotManifestMismatch` — a stale artifact fails LOUDLY at boot
  instead of silently retracing;
* :meth:`AotArtifact.call` replaces the engine's jit dispatch: the
  in-trace retrace counters provably never move (tests assert ``== 0``
  end to end), and a bucket outside the saved universe raises
  :class:`AotBucketMissing` naming the shape — never a silent retrace.

The loaded ``Exported`` objects cache their compiled executables
in-process, so ONE artifact shared across a dp fleet
(``EngineConfig.aot``; the router refuses per-replica loads) compiles
each program once fleet-wide — and a supervisor-rebuilt replica
(:meth:`~paddle_tpu.serving.resilience.FleetSupervisor._rebuild` rebinds
the router's artifact) restarts onto warm executables in milliseconds
with zero post-restart traces, instead of re-paying the whole compile
bill mid-incident.

Everything round-trips on CPU meshes (``jax.export`` lowers and replays
mesh-spanning programs with forced host devices), so the contract —
token-identical greedy serving with trace counters pinned at zero — is
tier-1-provable; ``tests/test_zzzzz_aot.py``.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import weakref
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ..parallel._compat import get_jax_export
from .scheduler import bucket_size

# v2 (ISSUE 18): every program takes the per-row sampling quartet
# (temps f32, top_ks i32, top_ps f32, keys u32[...,2]) and returns token
# ids as output 0 — v1 artifacts predate in-trace sampling and refuse to
# load rather than serve the wrong signature.
# v3 (ISSUE 19): the "burst" family (device-resident multi-step decode,
# bucketed on (rows, burst-length)) joins the saved universe when
# ``EngineConfig.burst_steps >= 2``, and the manifest records
# ``burst_steps`` — v2 artifacts predate the family and refuse to load.
ARTIFACT_VERSION = 3
MANIFEST_NAME = "manifest.json"
_PROGRAM_DIR = "programs"

# metric names this module owns (registered by the StepProfiler when an
# artifact is bound — tools/check_metrics_docs lints that each appears
# in README's metrics table)
METRIC_NAMES = (
    "serving_aot_hits_total",
    "serving_aot_load_seconds",
    # ISSUE 16: wall seconds spent executing every saved program once
    # (--aot-warm at save time / --warm at worker boot)
    "serving_aot_warm_seconds",
)


class AotError(RuntimeError):
    """Base class for artifact save/load/dispatch failures."""


class AotManifestMismatch(AotError):
    """The artifact was built for a DIFFERENT deployment (mp degree,
    bucket set, model hash, pool geometry, jax version, ...) — loading
    it would silently retrace or serve wrong shapes, so boot fails
    loudly instead."""


class AotBucketMissing(AotError):
    """A serving step needed a (program, bucket) shape outside the
    artifact's saved universe — the zero-trace contract refuses to fall
    back to a silent retrace; re-save with a larger ``max_seq_len`` /
    matching scheduler caps."""


def _pow2_upto(cap: int) -> List[int]:
    """[1, 2, 4, ..., bucket_size(cap)] — the bucket lattice axis."""
    out, b = [], 1
    top = bucket_size(max(1, int(cap)))
    while b <= top:
        out.append(b)
        b <<= 1
    return out


def _max_seq_cap(engine, max_seq_len: Optional[int]) -> int:
    """THE max-seq clamp, shared by :meth:`AotArtifact.save` (manifest
    record) and :func:`enumerate_buckets` (lattice bound) so the two can
    never disagree: the pool capacity ``(num_blocks - 1) * block_size``
    caps whatever the caller asked for — no sequence can outgrow the
    pool."""
    pool_cap = max(1, (engine.num_blocks - 1) * engine.block_size)
    return min(int(max_seq_len), pool_cap) if max_seq_len else pool_cap


def enumerate_buckets(engine, max_seq_len: Optional[int] = None,
                      ) -> List[Tuple[str, Tuple[int, ...]]]:
    """The CLOSED set of (program, bucket) shapes ``engine`` can ever
    dispatch for sequences up to ``max_seq_len`` tokens (default: the
    pool capacity ``(num_blocks - 1) * block_size`` — no sequence can
    outgrow the pool).  Derived from the same bucketing rules the
    dispatch sites use (``scheduler.bucket_size`` over batch / token /
    table-width axes), so a workload within the caps can never step
    outside this universe — which is exactly what makes the zero-trace
    AOT contract provable rather than probabilistic."""
    sched = engine.scheduler.config
    bs = engine.block_size
    max_seq = _max_seq_cap(engine, max_seq_len)
    # table width covers the whole sequence: ceil(max_seq / block_size)
    widths = _pow2_upto((max_seq + bs - 1) // bs)
    out: List[Tuple[str, Tuple[int, ...]]] = []
    # decode-burst family (ISSUE 19): a bounded two-axis lattice —
    # (decode-rows bucket, burst-length bucket) — independent of the
    # unified flag (the burst path runs in both dispatch modes).  The
    # table width is NOT an axis: burst programs pin it to the one
    # max_seq-derived width bucket (engine._burst_width), so bursts
    # never change shape as rows cross block boundaries mid-loop.
    # Length buckets start at 2: the engine never launches a 1-step
    # burst (that is just decode with padding).
    burst_steps = int(getattr(engine, "_burst_steps", 0) or 0)
    if burst_steps >= 2:
        for b in _pow2_upto(sched.max_num_seqs):
            for n in _pow2_upto(burst_steps):
                if n >= 2:
                    out.append(("burst", (b, n)))
    pf_budget = sched.max_prefill_tokens_per_step
    if getattr(engine, "_unified", False):
        # unified ragged family (PR 10): ONE packed launch per step.
        # Decode rows are never split, so the token bucket is bounded by
        # bucket_size(max(budget, max_num_seqs)).  Without a packed
        # budget the launch aggregates EVERY row's prefill work: the
        # per-step prefill total is capped by the chunk budget when one
        # is set (it is a single budget decremented across all planned
        # chunks — and it can exceed one sequence's max_seq by spreading
        # over rows), else only by every running row prefilling its
        # whole remaining prompt at once (max_num_seqs * max_seq — e.g.
        # a preemption-recompute wave packing with fresh admissions).
        total = sched.max_tokens_per_step
        if total is not None:
            tmax = max(int(total), sched.max_num_seqs)
        else:
            pf_cap = sched.max_num_seqs * max_seq
            if pf_budget is not None:
                pf_cap = min(int(pf_budget), pf_cap)
            tmax = sched.max_num_seqs + pf_cap
        for t in _pow2_upto(tmax):
            for w in widths:
                out.append(("ragged", (t, w)))
        return out
    # legacy three families.  One-shot prefill runs only when the whole
    # prompt fits one planning pass (n == target <= the chunk budget).
    oneshot = min(pf_budget or max_seq, max_seq)
    for t in _pow2_upto(oneshot):
        out.append(("prefill", (t,)))
    for c in _pow2_upto(oneshot):
        for w in widths:
            out.append(("chunk", (c, w)))
    for b in _pow2_upto(sched.max_num_seqs):
        for w in widths:
            out.append(("decode", (b, w)))
    return out


def _key_str(program: str, bucket: Tuple[int, ...]) -> str:
    return program + "_" + "x".join(str(int(b)) for b in bucket)


def model_config_hash(engine) -> str:
    """Deterministic digest of the deployment's MODEL IDENTITY: the
    model config's scalar fields plus every parameter's (shape, dtype)
    — the shapes the exported programs were traced over.  Weight VALUES
    are deliberately not hashed (an artifact serves any checkpoint of
    the same architecture; weights enter the programs as arguments)."""
    cfg = engine.model.config
    fields = {k: v for k, v in sorted(vars(cfg).items())
              if isinstance(v, (int, float, str, bool, type(None)))}
    params = [[list(np.shape(p._value)), str(np.dtype(p._value.dtype))]
              for p in engine._params]
    blob = json.dumps({"config": fields, "params": params},
                      sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


def _autotune_decisions(engine) -> Dict:
    """Kernel-routing + autotune decisions baked into the exported
    programs — recorded so a load under DIFFERENT routing fails loudly
    (the StableHLO already committed to a path; the engine config would
    be silently dead otherwise)."""
    dec = {
        "use_pallas_paged": engine.engine_config.use_pallas_paged,
        "unified_step": bool(getattr(engine, "_unified", False)),
    }
    try:  # best-effort snapshot of the committed op-autotune table
        from ..ops import autotune as _at

        table = getattr(_at, "_RESULTS", None)
        if isinstance(table, dict):
            dec["op_autotune_keys"] = sorted(str(k) for k in table)[:64]
    except Exception:
        pass  # swallow-ok: the op-autotune table is informational in the manifest; its absence must not block a save
    return dec


def _arg_specs(engine, program: str, bucket: Tuple[int, ...]):
    """Abstract ``ShapeDtypeStruct`` argument pytree for one (program,
    bucket) — mirrors exactly what the engine's dispatch sites build
    (``_prefill`` / ``_decode`` / ``_unified_exec``), with integer
    routing arrays in their CANONICALIZED int32 form (x64 is off; the
    traced program only ever sees int32)."""
    s = jax.ShapeDtypeStruct
    i32 = np.int32

    def sampling(n):
        # ISSUE 18: the per-row sampling quartet every program family now
        # consumes as its trailing arguments (SamplingPack.arrays()) —
        # (temps f32, top_ks i32, top_ps f32, keys u32[n, 2])
        return (s((n,), np.float32), s((n,), i32), s((n,), np.float32),
                s((n, 2), np.uint32))

    params = tuple(s(np.shape(p._value), np.dtype(p._value.dtype))
                   for p in engine._params)
    pools = tuple(s(tuple(k.shape), np.dtype(k.dtype))
                  for k in engine._k_pools)
    head = (params, pools, pools)
    if program == "decode":
        Bb, Wb = bucket
        return head + (s((Bb, 1), i32), s((Bb,), i32), s((Bb, Wb), i32),
                       s((Bb,), i32), s((Bb,), i32), s((Bb,), i32)) \
            + sampling(Bb)
    if program == "prefill":
        (Tb,) = bucket
        return head + (s((1, Tb), i32), s((), i32), s((Tb,), i32),
                       s((Tb,), i32)) + sampling(1)
    if program == "chunk":
        Wb, TWb = bucket
        return head + (s((1, Wb), i32), s((), i32), s((), i32),
                       s((1, TWb), i32), s((1,), i32), s((1, Wb), i32),
                       s((1, Wb), i32)) + sampling(1)
    if program == "ragged":
        Tb, TWb = bucket
        return head + (s((1, Tb), i32), s((1, Tb), i32), s((Tb,), i32),
                       s((Tb,), i32), s((Tb, TWb), i32), s((Tb,), i32),
                       s((Tb,), i32), s((Tb,), i32)) + sampling(Tb)
    if program == "burst":
        # (ids, pos, tables, lens, slot_blocks, slot_offsets, n_steps,
        #  active, eos_ids) + sampling quartet — ISSUE 19.  The table
        # width is the engine's pinned burst width (max_seq-derived;
        # save() aligns it with the manifest's max_seq_len before
        # lowering, bind_aot() re-derives the same value at load).
        Bb, Nb = bucket
        W = engine._burst_width
        return head + (s((Bb, 1), i32), s((Bb,), i32), s((Bb, W), i32),
                       s((Bb,), i32), s((Bb, Nb), i32), s((Bb, Nb), i32),
                       s((), i32), s((Bb,), np.bool_), s((Bb,), i32)) \
            + sampling(Bb)
    raise AotError(f"unknown program family {program!r}")


def _jit_for(engine, program: str):
    return {"decode": engine._jit_decode,
            "prefill": engine._jit_prefill,
            "chunk": engine._jit_chunk_prefill,
            "ragged": engine._jit_unified,
            "burst": engine._jit_burst}[program]


class AotArtifact:
    """One saved-or-loaded serving program set + its manifest.

    Save side: :meth:`save` traces + lowers every bucket of a BUILDER
    engine (its retrace counters advance — that engine is a compile
    host, not a serving replica) and writes ``programs/*.stablehlo``
    first, the manifest last via tmp→rename, so a torn save can never
    load.  Load side: :meth:`load` → :meth:`validate` (engine build
    calls it) → :meth:`call` at every step dispatch.  The deserialized
    ``Exported`` objects cache compiled executables per process, so the
    artifact object is SHARED — across dp replicas and across
    supervisor rebuilds — and each program compiles once fleet-wide."""

    def __init__(self, manifest: Dict, programs: Dict, path: str,
                 load_seconds: float = 0.0):
        self.manifest = manifest
        # (program, bucket...) -> deserialized Exported
        self._programs = programs
        self.path = path
        self.load_seconds = float(load_seconds)
        # registries that already observed this artifact's load wall
        # (WeakSet: a registry's death must not pin it here).  ONE disk
        # load must land as ONE serving_aot_load_seconds sample per
        # registry, however many replicas/rebuilds bind the artifact.
        self._observed_registries = weakref.WeakSet()

    def mark_load_observed(self, registry) -> bool:
        """True exactly once per (this artifact, ``registry``): the
        caller that gets True records ``serving_aot_load_seconds``;
        later binds of the same loaded artifact into the same registry
        (dp replicas, supervisor rebuilds) must not re-observe a disk
        load that happened once."""
        if registry in self._observed_registries:
            return False
        self._observed_registries.add(registry)
        return True

    # --- inspection ---------------------------------------------------------
    @property
    def program_count(self) -> int:
        return len(self._programs)

    @property
    def bucket_sets(self) -> Dict[str, List[Tuple[int, ...]]]:
        out: Dict[str, List] = {}
        for key in self._programs:
            out.setdefault(key[0], []).append(tuple(key[1:]))
        return {p: sorted(v) for p, v in sorted(out.items())}

    def describe(self) -> Dict:
        m = self.manifest
        return {
            "path": self.path,
            "programs": self.program_count,
            "families": {p: len(v) for p, v in self.bucket_sets.items()},
            "mp": m["mp"], "dtype": m["dtype"],
            "num_blocks": m["num_blocks"], "block_size": m["block_size"],
            "max_seq_len": m["max_seq_len"],
            "unified_step": m["autotune"]["unified_step"],
            "burst_steps": m.get("burst_steps", 0),
            "model_hash": m["model_hash"][:16],
            "jax_version": m["jax_version"],
            "load_seconds": round(self.load_seconds, 4),
        }

    # --- save ---------------------------------------------------------------
    @classmethod
    def save(cls, engine, path: str,
             max_seq_len: Optional[int] = None) -> "AotArtifact":
        """Lower + serialize ``engine``'s full bucketed program set into
        the ``path`` directory.  ``max_seq_len`` bounds the universe
        (default: pool capacity).  The saved set is always the full
        :func:`enumerate_buckets` lattice — :meth:`validate` requires
        exactly that coverage at load, so a pruned save could never
        bind."""
        ex = get_jax_export()
        t0 = time.perf_counter()
        sched = engine.scheduler.config
        max_seq = _max_seq_cap(engine, max_seq_len)
        # burst programs (ISSUE 19) pin their table width to ONE
        # max_seq-derived bucket; align the builder engine's width with
        # the universe being saved so the lowered shapes match what
        # bind_aot() re-derives from the manifest at load.  (The builder
        # is a compile host — narrowing its launch width is fine.)
        engine._burst_width = bucket_size(
            max(1, (max_seq + engine.block_size - 1) // engine.block_size))
        buckets = enumerate_buckets(engine, max_seq)
        # the whole artifact is STAGED next to its destination and
        # swapped in only after the manifest commit: a re-save that dies
        # midway (a bucket fails to lower, the process is killed) leaves
        # the previous good artifact untouched and loadable — and a
        # smaller universe can never strand orphaned blobs from the old
        # one, because the staged dir starts empty
        stage = path.rstrip("/") + ".staging"
        if os.path.exists(stage):
            shutil.rmtree(stage)
        prog_dir = os.path.join(stage, _PROGRAM_DIR)
        os.makedirs(prog_dir)
        programs: Dict = {}
        prog_meta: Dict[str, Dict] = {}
        try:
            for program, bucket in buckets:
                bucket = tuple(int(b) for b in bucket)
                exported = ex.export(_jit_for(engine, program))(
                    *_arg_specs(engine, program, bucket))
                blob = exported.serialize()
                key = _key_str(program, bucket)
                fname = key + ".stablehlo"
                with open(os.path.join(prog_dir, fname), "wb") as f:
                    f.write(blob)
                programs[(program,) + bucket] = exported
                prog_meta[key] = {"program": program,
                                  "bucket": list(bucket),
                                  "file": _PROGRAM_DIR + "/" + fname,
                                  "bytes": len(blob)}
        except BaseException:
            shutil.rmtree(stage, ignore_errors=True)
            raise
        import paddle_tpu as _p

        manifest = {
            "artifact_version": ARTIFACT_VERSION,
            "framework": "paddle_tpu",
            "framework_version": str(_p.__version__),
            "jax_version": jax.__version__,
            "platform": jax.default_backend(),
            "created_unix": round(time.time(), 3),
            "model_hash": model_config_hash(engine),
            "mp": int(engine.mp),
            "dtype": str(np.dtype(engine._pool_dtype)),
            "num_blocks": int(engine.num_blocks),
            "block_size": int(engine.block_size),
            "num_layers": len(engine._k_pools),
            "max_seq_len": int(max_seq),
            "scheduler": {
                "max_num_seqs": sched.max_num_seqs,
                "max_prefill_tokens_per_step":
                    sched.max_prefill_tokens_per_step,
                "max_tokens_per_step": sched.max_tokens_per_step,
            },
            # ISSUE 19: the burst-length cap the lattice was enumerated
            # under.  Not a validate() mismatch row — a burst-off engine
            # may load a burst-on artifact (superset), and an engine
            # with a LARGER burst_steps fails the bucket-coverage check.
            "burst_steps": int(getattr(engine, "_burst_steps", 0) or 0),
            "autotune": _autotune_decisions(engine),
            # ISSUE 18: recorded for inspection only — deliberately NOT a
            # validate() mismatch row.  Spec decode packs verify chunks
            # into the SAME ragged bucket lattice (no new family, no new
            # axis), so one artifact serves spec-on and spec-off engines
            # alike; refusing on a spec flip would break that contract.
            "spec": (engine.spec.config.manifest_dict()
                     if getattr(engine, "spec", None) is not None
                     else None),
            "programs": prog_meta,
            "save_seconds": round(time.perf_counter() - t0, 4),
        }
        # manifest LAST, atomically: its presence is the commit record —
        # a save killed mid-way leaves programs but no manifest, and
        # load() refuses cleanly instead of serving half a universe
        tmp = os.path.join(stage, MANIFEST_NAME + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        os.replace(tmp, os.path.join(stage, MANIFEST_NAME))
        # swap the committed stage into place; the prior artifact (if
        # any) stays loadable right up to this point
        if os.path.exists(path):
            old = path.rstrip("/") + ".old"
            if os.path.exists(old):
                shutil.rmtree(old)
            os.rename(path, old)
            os.rename(stage, path)
            shutil.rmtree(old)
        else:
            os.rename(stage, path)
        return cls(manifest, programs, path)

    # --- load ---------------------------------------------------------------
    @classmethod
    def load(cls, path: str) -> "AotArtifact":
        """Read the manifest + deserialize EVERY program eagerly.
        Environment mismatches (artifact version, jax version, platform)
        fail here; deployment-shape mismatches fail in
        :meth:`validate` once an engine exists to compare against."""
        ex = get_jax_export()
        t0 = time.perf_counter()
        mpath = os.path.join(path, MANIFEST_NAME)
        if not os.path.exists(mpath):
            raise AotError(
                f"no AOT artifact at {path!r}: {MANIFEST_NAME} missing "
                "(unsaved, or a save was torn before commit)")
        with open(mpath) as f:
            manifest = json.load(f)
        mismatches: List[str] = []
        if manifest.get("artifact_version") != ARTIFACT_VERSION:
            mismatches.append(
                f"artifact_version {manifest.get('artifact_version')!r} "
                f"!= supported {ARTIFACT_VERSION}")
        if manifest.get("jax_version") != jax.__version__:
            mismatches.append(
                f"artifact was lowered under jax "
                f"{manifest.get('jax_version')!r} but "
                f"{jax.__version__} is installed (stale artifact — "
                "re-save after upgrading)")
        if manifest.get("platform") != jax.default_backend():
            mismatches.append(
                f"artifact platform {manifest.get('platform')!r} != "
                f"running backend {jax.default_backend()!r}")
        if mismatches:
            raise AotManifestMismatch(
                f"refusing to load AOT artifact {path!r}:\n  - "
                + "\n  - ".join(mismatches))
        programs: Dict = {}
        for key, meta in manifest["programs"].items():
            fpath = os.path.join(path, meta["file"])
            try:
                with open(fpath, "rb") as f:
                    programs[(meta["program"],)
                             + tuple(meta["bucket"])] = ex.deserialize(
                                 f.read())
            except Exception as e:
                raise AotError(
                    f"AOT artifact {path!r}: program {key!r} failed to "
                    f"deserialize from {meta['file']!r}: {e}") from e
        return cls(manifest, programs, path,
                   load_seconds=time.perf_counter() - t0)

    # --- validation (the mismatch matrix) -----------------------------------
    def validate(self, engine) -> None:
        """Raise :class:`AotManifestMismatch` naming EVERY way this
        artifact disagrees with ``engine``'s deployment — mp degree,
        model hash, pool geometry, dtype, kernel routing, unified flag,
        and the derived bucket universe.  A mismatch here would
        otherwise surface as a silent retrace (or a shape error deep in
        a step) — failing at boot is the whole point."""
        m = self.manifest
        mm: List[str] = []
        if m["mp"] != engine.mp:
            mm.append(f"mp degree: artifact {m['mp']}, engine {engine.mp}")
        if m["model_hash"] != model_config_hash(engine):
            mm.append("model-config hash: the artifact was lowered for a "
                      "different architecture/parameter layout")
        if m["num_blocks"] != engine.num_blocks \
                or m["block_size"] != engine.block_size:
            mm.append(
                f"pool geometry: artifact {m['num_blocks']}x"
                f"{m['block_size']}, engine {engine.num_blocks}x"
                f"{engine.block_size} (pool tensors are program inputs "
                "— shapes must match exactly)")
        if m["num_layers"] != len(engine._k_pools):
            mm.append(f"layer count: artifact {m['num_layers']}, engine "
                      f"{len(engine._k_pools)}")
        if m["dtype"] != str(np.dtype(engine._pool_dtype)):
            mm.append(f"pool dtype: artifact {m['dtype']}, engine "
                      f"{np.dtype(engine._pool_dtype)}")
        if bool(m["autotune"]["unified_step"]) != bool(engine._unified):
            mm.append(
                f"program family: artifact saved "
                f"unified_step={m['autotune']['unified_step']}, engine "
                f"runs unified_step={engine._unified}")
        if m["autotune"]["use_pallas_paged"] \
                != engine.engine_config.use_pallas_paged:
            mm.append(
                f"kernel routing: artifact baked use_pallas_paged="
                f"{m['autotune']['use_pallas_paged']}, engine configured "
                f"{engine.engine_config.use_pallas_paged} (the StableHLO "
                "already committed to a path — the config flip would be "
                "silently dead)")
        if not mm:
            # bucket-set coverage LAST (it needs an engine whose family
            # flag already matched): everything the engine's caps can
            # dispatch within the artifact's max_seq_len must be saved
            required = set(
                (p,) + tuple(b) for p, b in enumerate_buckets(
                    engine, max_seq_len=m["max_seq_len"]))
            missing = sorted(required - set(self._programs))
            if missing:
                mm.append(
                    f"bucket set: engine scheduler caps need "
                    f"{len(missing)} program shape(s) the artifact never "
                    f"saved (first: {missing[:4]}) — scheduler config "
                    "drifted since the save")
        if mm:
            raise AotManifestMismatch(
                f"AOT artifact {self.path!r} does not match this engine:"
                + "".join(f"\n  - {x}" for x in mm)
                + "\n(re-save the artifact for THIS deployment; a "
                "mismatched artifact would retrace silently)")

    # --- serving dispatch ---------------------------------------------------
    def call(self, program: str, bucket: Tuple[int, ...], *args):
        """Run one saved program.  Host-side integer arrays are
        canonicalized to the exported int32 avals (the engine builds
        int64 token ids; x64-off tracing saw int32) — ``Exported.call``
        is strict where ``jit`` canonicalizes.  Returns the engine's
        step-output tuple ``(tokens, logits, logit_stats, k_pools,
        v_pools)`` with the pool pytrees coerced back to tuples."""
        key = (program,) + tuple(int(b) for b in bucket)
        exported = self._programs.get(key)
        if exported is None:
            saved = self.bucket_sets
            raise AotBucketMissing(
                f"step program {program!r} bucket "
                f"{tuple(int(b) for b in bucket)} is outside the "
                f"artifact's saved universe (max_seq_len="
                f"{self.manifest['max_seq_len']}, saved "
                f"{ {p: len(v) for p, v in saved.items()} }); the "
                "zero-trace contract refuses to retrace — re-save with "
                "a larger max_seq_len / matching scheduler caps")
        flat, tree = jax.tree_util.tree_flatten(args)
        avals = exported.in_avals
        if len(flat) != len(avals):
            raise AotError(
                f"{program} {bucket}: argument count {len(flat)} != "
                f"exported {len(avals)} (framework drift — re-save)")
        coerced = [
            np.asarray(x, aval.dtype)
            if (not isinstance(x, jax.Array)
                and np.dtype(getattr(x, "dtype", aval.dtype))
                != aval.dtype) else x
            for x, aval in zip(flat, avals)]
        out = exported.call(*jax.tree_util.tree_unflatten(tree, coerced))
        return out[0], out[1], out[2], tuple(out[3]), tuple(out[4])

    def warm(self, registry=None, labels: Optional[Dict] = None) -> float:
        """Execute every saved program once with zero-filled arguments of
        the exported shapes (ISSUE 16 warm-boot satellite).  Exported
        programs compile lazily on first ``call`` — warming moves that
        cost from the first request wave to boot/save time, and (because
        this IS the serving-time ``Exported.call`` path, not a jit
        re-wrap) the XLA executables land in the persistent compilation
        cache under the exact keys serving will look up.  Returns the
        wall seconds spent; recorded as ``serving_aot_warm_seconds``
        when a ``registry`` is given."""
        t0 = time.perf_counter()
        for key, exported in sorted(self._programs.items()):
            flat = [np.zeros(a.shape, a.dtype) for a in exported.in_avals]
            args, kwargs = jax.tree_util.tree_unflatten(
                exported.in_tree, flat)
            out = exported.call(*args, **kwargs)
            for leaf in jax.tree_util.tree_leaves(out):
                if hasattr(leaf, "block_until_ready"):
                    leaf.block_until_ready()
        wall = time.perf_counter() - t0
        if registry is not None:
            registry.gauge(
                "serving_aot_warm_seconds",
                "wall seconds executing every saved AOT program once "
                "(warm boot/save)", **(labels or {})).set(wall)
        return wall
