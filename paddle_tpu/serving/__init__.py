"""``paddle_tpu.serving`` — request-level continuous-batching engine.

The serving subsystem VERDICT N31 asked for, layered over the existing
paged-attention ops and predictor API:

* :class:`EngineCore` (``engine.py``) — request queue, bucketed
  fixed-shape jitted prefill/decode programs, streaming, abort.
* :class:`ContinuousBatchingScheduler` (``scheduler.py``) — admission
  control + decode-slot reservation with preemption-and-recompute.
* :class:`KVCacheManager` (``kv_manager.py``) — refcounted paged block
  pool bookkeeping shared by all layers.
* :class:`ServingMetrics` (``metrics.py``) — TTFT / inter-token latency,
  queue/pool gauges, preemption counters, profiler-style ``summary()``.
* :class:`LLM` / :func:`stream_generate` (``entrypoints.py``) — batch and
  streaming user surfaces.

Architecture sketch and scheduler invariants: see ``scheduler.py``'s
module docstring and the README's serving section.
"""

from .engine import EngineCore  # noqa: F401
from .entrypoints import LLM, CompletionOutput, stream_generate  # noqa: F401
from .kv_manager import KVCacheManager, PoolExhausted  # noqa: F401
from .metrics import ServingMetrics  # noqa: F401
from .request import (  # noqa: F401
    FinishReason,
    Request,
    RequestState,
    SamplingParams,
)
from .scheduler import (  # noqa: F401
    ContinuousBatchingScheduler,
    SchedulerConfig,
    SchedulerOutput,
    bucket_size,
)
