"""``paddle_tpu.serving`` — request-level continuous-batching engine.

The serving subsystem VERDICT N31 asked for, layered over the existing
paged-attention ops and predictor API:

* :class:`EngineCore` (``engine.py``) — request queue, bucketed
  fixed-shape jitted prefill/decode programs, streaming, abort.
  :class:`EngineConfig` bundles deployment knobs (pool sizing, prefix
  cache, ``use_pallas_paged`` kernel routing, expected ``mp`` degree);
  under a live mesh with ``mp > 1`` the engine serves tensor-parallel
  (KV pools head-sharded, routing replicated — README "Multi-chip
  serving").
* :class:`ContinuousBatchingScheduler` (``scheduler.py``) — admission
  control + decode-slot reservation with preemption-and-recompute.
* :class:`KVCacheManager` (``kv_manager.py``) — refcounted paged block
  pool bookkeeping shared by all layers.
* :class:`ServingMetrics` (``metrics.py``) — TTFT / inter-token latency,
  queue/pool gauges, preemption counters, profiler-style ``summary()``.
* :class:`LLM` / :func:`stream_generate` (``entrypoints.py``) — batch and
  streaming user surfaces.
* :class:`CompletionServer` (``server.py`` + ``protocol.py``) — asyncio
  HTTP/SSE frontend: OpenAI-style ``POST /v1/completions`` (SSE when
  ``stream=true``), ``/healthz`` / ``/readyz`` / ``/metrics``, admission
  control (429 + Retry-After), per-request deadlines, graceful drain.
* :class:`FleetRouter` (``fleet.py``) — data-parallel serving fleet
  (ISSUE 6): N engine replicas on their own engine threads behind
  consistent-hash **prefix-affinity** routing (same chain hashes as the
  prefix cache), least-loaded fallback, per-replica admission/health,
  fleet-wide drain, and ``serving_fleet_*`` metrics.  The frontend wraps
  any bare engine as a fleet of one, so dp=1 deployments are unchanged.

Architecture sketch and scheduler invariants: see ``scheduler.py``'s
module docstring and the README's serving sections.
"""

from ..observability.alerts import (  # noqa: F401
    AlertRule,
    AlertRuleSet,
    default_rule_set,
)
from ..observability.history import HistoryConfig, HistoryStore  # noqa: F401
from .aot import (  # noqa: F401
    AotArtifact,
    AotBucketMissing,
    AotError,
    AotManifestMismatch,
)
from .engine import EngineConfig, EngineCore  # noqa: F401
from .entrypoints import LLM, CompletionOutput, stream_generate  # noqa: F401
from .faultinject import (  # noqa: F401
    FaultInjector,
    FaultPlan,
    FaultSpec,
    InjectedFault,
)
from .fleet import (  # noqa: F401
    EngineReplica,
    FleetConfig,
    FleetDown,
    FleetRouter,
    FleetSaturated,
    SubmitHandle,
    parse_roles,
)
from .handoff import HandoffError  # noqa: F401
from .procfleet import (  # noqa: F401
    AutoscalerConfig,
    CacheRebalancer,
    FleetAutoscaler,
    ProcessFleet,
    ProcessFleetConfig,
    RebalancerConfig,
    ScaleDecider,
    WorkerDied,
)
from .resilience import FleetSupervisor, SupervisorConfig  # noqa: F401
from .kv_manager import KVCacheManager, PoolExhausted  # noqa: F401
from .spec import NgramProposer, SpecConfig, SpecDecoder  # noqa: F401
from .wire import (  # noqa: F401
    ConnectionClosed,
    FrameError,
    HandshakeMismatch,
    RegistryMerger,
    WireError,
)
from .metrics import ServingMetrics  # noqa: F401
from .protocol import (  # noqa: F401
    CompletionRequest,
    ProtocolError,
    parse_completion_request,
)


def __getattr__(name):
    # lazy: eager `from .server import ...` would put the module in
    # sys.modules before `python -m paddle_tpu.serving.server` executes
    # it as __main__, tripping runpy's double-import warning
    if name in ("CompletionServer", "ServerConfig", "server"):
        from . import server as _server

        return _server if name == "server" else getattr(_server, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
from .request import (  # noqa: F401
    FinishReason,
    Request,
    RequestState,
    SamplingParams,
)
from .scheduler import (  # noqa: F401
    ContinuousBatchingScheduler,
    SchedulerConfig,
    SchedulerOutput,
    bucket_size,
)
