"""Deterministic fault injection for the serving fleet (ISSUE 12).

Self-healing is only trustworthy if it is *testable*, and the faults a
fleet must survive — a dying engine thread, a wedged step, a drained
pool, a silently drifting kernel — cannot be waited for in CI.  This
module makes them **schedulable**: a :class:`FaultPlan` is a frozen,
fleet-config-style value (the :class:`~paddle_tpu.observability.audit
.AuditConfig` discipline — comparable across replicas, no wall-clock,
no randomness) listing *exactly when* each fault fires, keyed by the
target replica's deterministic engine-step counter.  The same plan on
the same request stream produces the same chaos run every time, which
is what lets ``bench.py --serving`` and ``tests/test_zz_resilience.py``
assert greedy token identity *across* injected failures.

Named injection points, threaded through :class:`~paddle_tpu.serving
.EngineCore` (see ``engine.step()``):

======================  ======================================================
``engine_step_raise``   ``step()`` raises :class:`InjectedFault` — the engine
                        thread dies exactly the way a real bug kills it (the
                        ``EngineReplica`` loop's except path, ``engine_death``
                        flight trigger and all)
``pool_exhaust``        one step of temporary allocation refusal: the KV
                        manager reports zero available blocks while the
                        scheduler plans, so decode-slot reservation preempts
                        and admission defers — recompute makes it
                        token-identical, and the preemption telemetry fires
``slow_step``           ``time.sleep(duration_s)`` inside the step, visible
                        to the replica's :class:`~paddle_tpu.distributed
                        .StepWatchdog` (the stall the supervisor escalates)
``kernel_corrupt``      the PR 9 forced-corruption hook: the logits copy
                        handed to the numerics auditor is corrupted (sign-
                        flipped row), driving a ``token`` divergence and the
                        ``degraded`` state that triggers quarantine.  The
                        logits the sampler consumes are untouched, so served
                        tokens stay correct — only the audit net trips.
                        Requires ``EngineConfig.audit`` enabled; fires on
                        the first **sampled** decode/ragged launch at/after
                        the scheduled step (an unsampled launch never runs
                        the shadow compare, so consuming the exactly-once
                        entry there would validate nothing).
======================  ======================================================

Every firing is recorded: the ``serving_faults_injected_total{point}``
counter moves and a ``fault_injected`` lifecycle event (rid-less, so it
lands in the owning replica's flight ring) carries the point, the
scheduled step and the actual firing step — a post-mortem bundle from a
chaos run shows exactly which fault produced it, making the run
replayable from the bundle alone.

Exactly-once: each plan entry fires at most once per
:class:`FaultInjector` view, and the injector is owned by the ROUTER
(one per replica index, surviving engine rebuilds), so a restarted
replica does not re-fire entries the crashed engine already consumed.
An entry fires at the first step ``>= spec.step`` — an idle replica
whose step counter skips the exact value still fires deterministically
at its next step.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

INJECTION_POINTS = ("engine_step_raise", "pool_exhaust", "slow_step",
                    "kernel_corrupt")

# pre-registered metric names this module owns (tools/check_metrics_docs
# lints that each appears in README's metrics table)
METRIC_NAMES = ("serving_faults_injected_total",)


class InjectedFault(RuntimeError):
    """Raised by the ``engine_step_raise`` injection point — the engine
    thread dies through the exact code path a real step failure takes."""


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault: ``point`` fires on replica ``replica`` at
    its first engine step ``>= step`` (1-based, the engine's own
    deterministic step counter — no wall-clock)."""

    point: str
    step: int
    replica: str = "0"
    duration_s: float = 0.25   # slow_step stall length (seconds)

    def __post_init__(self):
        if self.point not in INJECTION_POINTS:
            raise ValueError(
                f"unknown injection point {self.point!r}; expected one "
                f"of {INJECTION_POINTS}")
        if self.step < 1:
            raise ValueError(f"step must be >= 1, got {self.step}")
        if self.duration_s < 0:
            raise ValueError(
                f"duration_s must be >= 0, got {self.duration_s}")
        # JSON plans naturally carry integer replica indexes; normalize
        # so plan equality and replica matching are string-keyed like
        # the flight rings
        object.__setattr__(self, "replica", str(self.replica))


@dataclass(frozen=True)
class FaultPlan:
    """A frozen, ordered fault schedule (fleet-config value: compare by
    ``==`` like :class:`AuditConfig`).  ``seed`` is carried verbatim
    into telemetry so a chaos run's bundles name the plan they ran."""

    faults: Tuple[FaultSpec, ...] = ()
    seed: int = 0

    def __post_init__(self):
        object.__setattr__(self, "faults", tuple(self.faults))

    @classmethod
    def from_obj(cls, obj) -> "FaultPlan":
        """Build from the JSON shape (``--fault-plan`` CLI)::

            {"seed": 0, "faults": [
                {"point": "engine_step_raise", "replica": 1, "step": 6},
                {"point": "kernel_corrupt", "replica": 0, "step": 9}]}

        A bare list is accepted as the ``faults`` array."""
        if isinstance(obj, list):
            obj = {"faults": obj}
        if not isinstance(obj, dict):
            raise ValueError(
                f"fault plan must be a JSON object or list, got "
                f"{type(obj).__name__}")
        faults = []
        for entry in obj.get("faults", ()):
            if not isinstance(entry, dict):
                raise ValueError(
                    f"each fault must be an object, got {entry!r}")
            faults.append(FaultSpec(
                point=entry.get("point", ""),
                step=int(entry.get("step", 0)),
                replica=str(entry.get("replica", "0")),
                duration_s=float(entry.get("duration_s", 0.25))))
        return cls(faults=tuple(faults), seed=int(obj.get("seed", 0)))

    @classmethod
    def from_json(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            return cls.from_obj(json.load(f))

    def to_obj(self) -> Dict:
        return {
            "seed": self.seed,
            "faults": [
                {"point": s.point, "step": s.step, "replica": s.replica,
                 "duration_s": s.duration_s}
                for s in self.faults
            ],
        }

    def for_replica(self, replica) -> List[Tuple[int, FaultSpec]]:
        """(plan-index, spec) entries targeting ``replica``."""
        r = str(replica)
        return [(i, s) for i, s in enumerate(self.faults)
                if s.replica == r]


class FaultInjector:
    """One replica's live view of a :class:`FaultPlan`.

    Owned by the :class:`~paddle_tpu.serving.fleet.FleetRouter` (one per
    replica index) and re-bound onto every engine the supervisor builds
    for that index, so the fired-once bookkeeping survives restarts —
    each plan entry fires exactly once per chaos run, not once per
    engine incarnation.  The engine thread is the only caller of the
    firing hooks; the lock exists for the inspection surface."""

    def __init__(self, plan: FaultPlan, replica,
                 lifecycle=None, registry=None,
                 labels: Optional[Dict[str, str]] = None):
        self.plan = plan
        self.replica = str(replica)
        self.lifecycle = lifecycle
        self._specs = plan.for_replica(self.replica)
        self._fired: set = set()       # plan indexes already consumed
        self._lock = threading.Lock()
        self.pool_exhausted = False    # set for the duration of ONE
        # scheduler-planning pass by begin_step, consumed by the engine
        self._counters = None
        if registry is not None:
            lbls = dict(labels or {}, replica=self.replica)
            self._counters = {
                p: registry.counter(
                    "serving_faults_injected_total",
                    "deterministic fault injections fired",
                    **dict(lbls, point=p))
                for p in INJECTION_POINTS
            }

    # --- firing (engine thread) ---------------------------------------------
    def _take(self, point: str, step: int) -> Optional[FaultSpec]:
        """Consume the first unfired plan entry for ``point`` whose
        scheduled step has arrived; records the firing."""
        with self._lock:
            for idx, spec in self._specs:
                if (spec.point == point and idx not in self._fired
                        and step >= spec.step):
                    self._fired.add(idx)
                    break
            else:
                return None
        if self._counters is not None:
            self._counters[point].inc()
        if self.lifecycle is not None:
            # rid-less event: lands in THIS replica's flight ring, so a
            # post-mortem bundle names the fault that produced it
            self.lifecycle.event(
                None, "fault_injected", replica=self.replica,
                point=point, step=step, scheduled_step=spec.step,
                plan_index=idx, plan_seed=self.plan.seed)
        return spec

    def begin_step(self, step: int) -> None:
        """Engine-step hook (called with the engine's step counter
        BEFORE any scheduling): fires ``slow_step`` (sleeps in place,
        watchdog-visible), arms ``pool_exhaust`` for this step's
        planning pass, and fires ``engine_step_raise`` (raises)."""
        self.pool_exhausted = False
        spec = self._take("slow_step", step)
        if spec is not None:
            time.sleep(spec.duration_s)
        if self._take("pool_exhaust", step) is not None:
            self.pool_exhausted = True
        spec = self._take("engine_step_raise", step)
        if spec is not None:
            raise InjectedFault(
                f"injected engine_step_raise on replica {self.replica} "
                f"at step {step} (scheduled {spec.step}, plan seed "
                f"{self.plan.seed})")

    def corrupt_logits(self, step: int, logits: np.ndarray) -> np.ndarray:
        """``kernel_corrupt``: return a corrupted COPY of the logits the
        engine hands to the numerics auditor (sign-flipped first row —
        a guaranteed greedy-argmax flip, so the shadow oracle reports a
        ``token`` divergence).  The engine samples from the original
        array, so served tokens are untouched."""
        spec = self._take("kernel_corrupt", step)
        if spec is None:
            return logits
        out = np.array(logits, dtype=np.float32, copy=True)
        flat = out.reshape(-1, out.shape[-1])
        flat[0] = -flat[0]
        return out

    def mark_fired(self, indexes) -> None:
        """Record plan ``indexes`` as already consumed WITHOUT counting
        an injection (ISSUE 16): when a worker process is respawned, the
        router transfers the previous incarnation's fired set into the
        fresh worker's injector so each plan entry still fires exactly
        once per chaos run — across process incarnations, not just
        engine rebuilds."""
        with self._lock:
            self._fired.update(int(i) for i in indexes)

    # --- inspection ---------------------------------------------------------
    @property
    def fired_count(self) -> int:
        with self._lock:
            return len(self._fired)

    @property
    def remaining(self) -> int:
        with self._lock:
            return len(self._specs) - len(self._fired)

    def snapshot(self) -> Dict:
        with self._lock:
            fired = sorted(self._fired)
        return {
            "replica": self.replica,
            "plan_seed": self.plan.seed,
            "scheduled": len(self._specs),
            "fired": len(fired),
            "fired_plan_indexes": fired,
        }
