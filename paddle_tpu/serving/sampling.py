"""First-class per-request sampling — host side (ISSUE 18 tentpole a).

The device side is :func:`paddle_tpu.ops.sampling.sample_tokens`: every
traced step program now ends in a per-row sampling reduction and returns
token ids, so the host never touches logits on the emission path.  This
module owns the host half of that contract:

* :class:`SamplingPack` — builds the padded per-row ``(temperature,
  top_k, top_p, key)`` quartet arrays a step program consumes.  Padding
  rows stay all-zero (``temperature == 0`` → greedy argmax over the null
  page's logits, discarded by the host), so packing never perturbs real
  rows and the arrays bucket exactly like every other routing input.
* **The draw-index discipline** (:func:`draw_index`) — the PRNG key for
  a request's draw is the raw u32 pair ``(seed, output_position)``.
  Output position is a pure function of request state, so the sampled
  stream is identical across: preemption-recompute (the replayed
  positions are never re-drawn — they are already in
  ``output_tokens``), dp=1 vs dp=2 placement, server vs offline
  ``LLM.generate``, and spec-decode verify packing (a verify row's
  position ``j`` uses the same key the plain decode path would have
  used when it reached that position).

Greedy requests (``temperature == 0``) never consume a key, matching the
pre-ISSUE-18 host-argmax semantics bit for bit.
"""

from __future__ import annotations

import numpy as np

# pre-registered by the engine at construction (EngineCore._init_sampling)
# so the series exist from the first scrape:
#   serving_sampled_tokens_total — tokens emitted by non-greedy rows
#     (device Gumbel-max draws); greedy emissions are not counted here
#   serving_greedy_tokens_total  — tokens emitted by greedy rows via the
#     same in-trace program (the two together = all emitted tokens)
METRIC_NAMES = (
    "serving_sampled_tokens_total",
    "serving_greedy_tokens_total",
)


def register_metrics(registry):
    """Create the sampling counters on ``registry`` (idempotent: the
    registry's get-or-create contract returns existing series)."""
    return {
        "sampled": registry.counter(
            "serving_sampled_tokens_total",
            help="tokens emitted via in-trace sampled (temperature>0) rows"),
        "greedy": registry.counter(
            "serving_greedy_tokens_total",
            help="tokens emitted via in-trace greedy (temperature==0) rows"),
    }


def draw_index(req, offset: int = 0) -> int:
    """The PRNG draw index for ``req``'s next emitted token (+``offset``
    for speculative positions beyond it): its output position.  THE
    determinism anchor — see the module docstring."""
    return len(req.output_tokens) + offset


class SamplingPack:
    """Padded per-row sampling quartet for one step program launch.

    ``n`` is the padded row count (batch bucket for decode, token bucket
    for the unified ragged program — rows there are PACKED TOKEN
    POSITIONS, one quartet per position, so a verify row's k draft
    positions each carry their own draw index).
    """

    __slots__ = ("temps", "top_ks", "top_ps", "keys")

    def __init__(self, n: int):
        self.temps = np.zeros((n,), np.float32)   # 0 = greedy (padding too)
        self.top_ks = np.zeros((n,), np.int32)
        self.top_ps = np.ones((n,), np.float32)
        self.keys = np.zeros((n, 2), np.uint32)

    def set(self, i: int, sampling, draw: int) -> None:
        """Fill row ``i`` from a ``SamplingParams`` + draw index."""
        self.temps[i] = np.float32(sampling.temperature)
        self.top_ks[i] = np.int32(sampling.top_k)
        self.top_ps[i] = np.float32(sampling.top_p)
        self.keys[i, 0] = np.uint32(int(sampling.seed) & 0xFFFFFFFF)
        self.keys[i, 1] = np.uint32(int(draw) & 0xFFFFFFFF)

    def set_request(self, i: int, req, offset: int = 0) -> None:
        self.set(i, req.sampling, draw_index(req, offset))

    def arrays(self):
        return self.temps, self.top_ks, self.top_ps, self.keys
