"""Asyncio HTTP/SSE frontend over :class:`EngineCore`.

The missing network surface above the continuous-batching engine (ISSUE 3
tentpole): a dependency-free HTTP/1.1 server on stdlib ``asyncio``
streams — no framework — exposing

* ``POST /v1/completions`` — OpenAI-style JSON (``protocol.py``);
  ``stream=true`` answers Server-Sent Events, one ``data:`` event per
  token batch, terminated by ``data: [DONE]``;
* ``GET /healthz`` — liveness (200 while the process runs);
* ``GET /readyz`` — readiness (503 the instant a drain begins, or if the
  engine thread died);
* ``GET /metrics`` — Prometheus text exposition of the engine's
  registry, byte-identical to ``observability.start_metrics_server``
  for the same registry (shared ``metrics_page`` handler).

HTTP/1.1 connections are **persistent** (ISSUE 3 follow-up (a)): a
handler loops request → response on one socket until the client sends
``Connection: close``, goes idle past ``keepalive_timeout_s``, or the
response is an SSE stream (self-delimiting — the socket closes after
``data: [DONE]``).  HTTP/1.0 clients must opt in with
``Connection: keep-alive``.

Threading model — ONE engine thread, N async handlers:

    asyncio loop (handlers)          engine thread (owns EngineCore)
    ───────────────────────          ───────────────────────────────
    parse request ──submit q──────▶  add_request(trace_id=...)
    await handle.event   ◀─notify──  step(): prefill/decode/sample
    read req.output_tokens[cursor:]  retire finished
    deadline hit ──abort q────────▶  abort_request(rid, TIMEOUT)

``EngineCore`` is not thread-safe and its jitted steps block, so the
engine loop runs on one background thread; handlers never touch the
scheduler.  Handlers communicate through two **bounded** stdlib queues
(submit/abort) and read each request's append-only ``output_tokens``
directly (safe under the GIL); the engine thread wakes sleeping handlers
via ``loop.call_soon_threadsafe`` after every step.

The frontend owns three policies the engine deliberately does not:

* **admission control** — at most ``max_queue`` requests in flight
  (pending + running); beyond that a POST gets ``429`` with a
  ``Retry-After`` header and the ``serving_admission_rejected_total``
  counter increments.  Both cross-thread queues are bounded
  (``queue.Queue(maxsize=...)`` — ``tools/check_bounded_metrics.py``
  lints this file).
* **per-request deadlines** — ``timeout`` in the body (clamped to
  ``max_timeout_s``, defaulting to ``default_timeout_s``); on expiry the
  handler propagates ``abort(TIMEOUT)`` into the scheduler, the
  request's blocks are freed, and the partial output is returned with
  ``finish_reason="timeout"``.
* **graceful drain** — ``shutdown()`` (or SIGTERM under the CLI) flips
  ``/readyz`` to 503 immediately and stops admitting; in-flight requests
  run to completion up to the drain deadline, then are aborted with
  TIMEOUT; the engine thread exits only once the pool is empty.

Every request gets a trace id (``cmpl-<n>``) attached to the engine's
prefill/preempt/decode spans, so one request's lifecycle is
reconstructible from a single exported chrome trace.

Self-test (wired into the test suite)::

    JAX_PLATFORMS=cpu python -m paddle_tpu.serving.server --selftest
"""

from __future__ import annotations

import asyncio
import itertools
import json
import queue
import threading
import time
import traceback
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..observability.httpd import PROMETHEUS_CONTENT_TYPE, metrics_page
from .engine import EngineCore
from .protocol import (
    SSE_DONE,
    CompletionRequest,
    ProtocolError,
    chunk_body,
    completion_body,
    error_body,
    parse_completion_request,
    sse_event,
)
from .request import FinishReason

_MAX_HEADER_BYTES = 16384
_ROUTES = ("/v1/completions", "/healthz", "/readyz", "/metrics")


@dataclass
class ServerConfig:
    host: str = "127.0.0.1"
    port: int = 0                 # 0 = ephemeral, read back from .port
    max_queue: int = 64           # in-flight cap (pending + running)
    retry_after_s: int = 1        # 429 Retry-After hint
    default_timeout_s: Optional[float] = None   # None = no deadline
    max_timeout_s: float = 600.0
    drain_timeout_s: float = 5.0  # shutdown(): grace for in-flight work
    keepalive_timeout_s: float = 30.0  # idle wait for the NEXT request on
                                       # a persistent connection (also the
                                       # first-request header deadline)
    model_name: str = "paddle-tpu"
    tokenize: Optional[Callable[[str], List[int]]] = None


class _Handle:
    """One in-flight HTTP completion as both threads see it."""

    __slots__ = ("rid", "creq", "event", "req", "done", "cancel_reason")

    def __init__(self, rid: str, creq: CompletionRequest,
                 event: asyncio.Event):
        self.rid = rid
        self.creq = creq
        self.event = event          # created on the server's loop
        self.req = None             # engine Request, set by engine thread
        self.done = False           # terminal without admission
        self.cancel_reason: Optional[FinishReason] = None


class CompletionServer:
    """HTTP frontend bound to one :class:`EngineCore`.

    ``await start()`` spawns the engine thread and binds the socket;
    ``await shutdown()`` drains gracefully.  ``registry`` defaults to the
    engine's own metrics registry, so ``GET /metrics`` serves the
    ``serving_*`` TTFT/ITL histograms next to whatever else the caller
    registered there."""

    def __init__(self, engine: EngineCore,
                 config: Optional[ServerConfig] = None, registry=None):
        self.engine = engine
        self.cfg = config or ServerConfig()
        self.registry = (registry if registry is not None
                         else engine.metrics.registry)
        self.tracer = engine.tracer
        self._handles: Dict[str, _Handle] = {}
        self._submit_q: "queue.Queue" = queue.Queue(
            maxsize=max(1, self.cfg.max_queue))
        # aborts are bounded by in-flight requests; 2x leaves room for
        # drain-time aborts racing handler-deadline aborts
        self._abort_q: "queue.Queue" = queue.Queue(
            maxsize=2 * max(1, self.cfg.max_queue) + 8)
        self._wake = threading.Event()
        self._ids = itertools.count(1)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._engine_thread: Optional[threading.Thread] = None
        self._draining = False
        self._stop = False
        self._shutdown_done: Optional[asyncio.Event] = None
        self._engine_error: Optional[str] = None
        m = engine.metrics
        self._rejected = m.registry.counter(
            "serving_admission_rejected_total",
            "requests rejected 429 at admission (queue saturated)")
        self.port: Optional[int] = None

    # --- lifecycle ----------------------------------------------------------
    async def start(self) -> "CompletionServer":
        self._loop = asyncio.get_running_loop()
        self._shutdown_done = asyncio.Event()
        self._engine_thread = threading.Thread(
            target=self._engine_loop, name="serving-engine", daemon=True)
        self._engine_thread.start()
        self._server = await asyncio.start_server(
            self._handle_conn, self.cfg.host, self.cfg.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    def request_shutdown(self) -> None:
        """Thread/signal-safe trigger for a graceful drain."""
        if self._loop is None or self._loop.is_closed():
            return
        self._loop.call_soon_threadsafe(
            lambda: self._loop.create_task(self.shutdown()))

    async def shutdown(self, drain_timeout: Optional[float] = None) -> None:
        """Graceful drain: stop admission now (``/readyz`` → 503), let
        in-flight requests finish until the drain deadline, abort the
        stragglers with TIMEOUT, stop the engine thread, close the
        socket.  Idempotent; concurrent callers await the first drain."""
        if self._draining:
            await self._shutdown_done.wait()
            return
        self._draining = True
        deadline = time.monotonic() + (
            drain_timeout if drain_timeout is not None
            else self.cfg.drain_timeout_s)
        while self._handles and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        for h in list(self._handles.values()):
            self._request_abort(h, FinishReason.TIMEOUT)
        # handlers still need loop time to flush their (aborted) responses
        flush_deadline = time.monotonic() + 5.0
        while self._handles and time.monotonic() < flush_deadline:
            await asyncio.sleep(0.01)
        self._stop = True
        self._wake.set()
        if self._engine_thread is not None:
            await self._loop.run_in_executor(
                None, self._engine_thread.join, 10.0)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self._shutdown_done.set()

    async def serve_forever(self) -> None:
        await self._shutdown_done.wait()

    @property
    def ready(self) -> bool:
        return (self._server is not None and not self._draining
                and self._engine_thread is not None
                and self._engine_thread.is_alive())

    # --- engine thread ------------------------------------------------------
    def _engine_loop(self) -> None:
        eng = self.engine
        try:
            while True:
                self._drain_submissions()
                self._drain_aborts()
                if self._stop and not eng.scheduler.has_work():
                    break
                if eng.scheduler.has_work():
                    eng.step()
                    self._notify()
                else:
                    self._wake.wait(timeout=0.02)
                    self._wake.clear()
        except Exception:
            # fail loudly but leave no handler hanging and no block held
            self._engine_error = traceback.format_exc()
            for req in list(eng.requests.values()):
                eng.abort_request(req.request_id)
        finally:
            for h in list(self._handles.values()):
                h.done = True
            self._notify()

    def _drain_submissions(self) -> None:
        while True:
            try:
                h = self._submit_q.get_nowait()
            except queue.Empty:
                return
            if h.cancel_reason is not None or self._stop:
                # deadline fired (or drain ended) before admission: the
                # request never enters the scheduler
                h.done = True
                self._notify()
                continue
            c = h.creq
            h.req = self.engine.add_request(
                c.prompt_ids, sampling=c.sampling(), request_id=h.rid,
                priority=c.priority, trace_id=h.rid)

    def _drain_aborts(self) -> None:
        did = False
        while True:
            try:
                rid, reason = self._abort_q.get_nowait()
            except queue.Empty:
                break
            if self.engine.abort_request(rid, reason):
                did = True
            else:
                h = self._handles.get(rid)
                if h is not None and h.req is None:
                    h.done = True
                    did = True
        if did:
            self._notify()

    def _notify(self) -> None:
        """Wake every waiting handler (engine → loop thread)."""
        loop = self._loop
        if loop is None or loop.is_closed():
            return
        for h in list(self._handles.values()):
            try:
                loop.call_soon_threadsafe(h.event.set)
            except RuntimeError:
                return  # loop shut down mid-iteration

    def _request_abort(self, h: _Handle, reason: FinishReason) -> None:
        h.cancel_reason = reason
        try:
            self._abort_q.put_nowait((h.rid, reason))
        except queue.Full:
            pass  # sized to in-flight bound; a drop only delays cleanup
        self._wake.set()

    # --- HTTP plumbing ------------------------------------------------------
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        """Serve one connection: HTTP/1.1 requests are persistent by
        default (``Connection: close`` or HTTP/1.0 without an explicit
        ``keep-alive`` opts out), so this loops request → response until
        the client closes, opts out, hits the idle timeout, or switches
        to a self-delimiting response (SSE streams close the socket —
        their framing has no length)."""
        try:
            while True:
                try:
                    head = await asyncio.wait_for(
                        reader.readuntil(b"\r\n\r\n"),
                        timeout=self.cfg.keepalive_timeout_s)
                except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                        asyncio.LimitOverrunError, ConnectionError):
                    return  # idle timeout or client closed between requests
                if len(head) > _MAX_HEADER_BYTES:
                    await self._respond(writer, 431, error_body(
                        "headers too large"))
                    return
                lines = head.decode("latin-1").split("\r\n")
                parts = lines[0].split()
                if len(parts) != 3:
                    await self._respond(writer, 400, error_body(
                        "malformed request line"))
                    return
                method, target = parts[0].upper(), parts[1]
                version = parts[2].upper()
                headers = {}
                for ln in lines[1:]:
                    if ":" in ln:
                        k, v = ln.split(":", 1)
                        headers[k.strip().lower()] = v.strip()
                conn_hdr = headers.get("connection", "").lower()
                keep_alive = (conn_hdr != "close" if version == "HTTP/1.1"
                              else conn_hdr == "keep-alive")
                if "transfer-encoding" in headers:
                    # bodies are framed by Content-Length only; a chunked
                    # body left unread would desync the persistent stream
                    # (its bytes would parse as the next request line), so
                    # reject AND close
                    await self._respond(writer, 411, error_body(
                        "Transfer-Encoding unsupported; send "
                        "Content-Length"))
                    return
                body = b""
                clen = int(headers.get("content-length", 0) or 0)
                if clen:
                    if clen > 2 * 1024 * 1024:
                        await self._respond(writer, 413, error_body(
                            "body too large"))
                        return
                    body = await asyncio.wait_for(
                        reader.readexactly(clen), timeout=30.0)
                keep_alive = await self._dispatch(
                    method, target.split("?", 1)[0], body, writer,
                    keep_alive)
                if not keep_alive:
                    return
        except (ConnectionError, asyncio.TimeoutError,
                asyncio.IncompleteReadError):
            pass  # client went away; per-request cleanup already ran
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _count_http(self, route: str, status: int) -> None:
        route = route if route in _ROUTES else "other"
        self.registry.counter(
            "serving_http_requests_total", "HTTP requests served",
            route=route, code=str(status)).inc()

    async def _respond(self, writer: asyncio.StreamWriter, status: int,
                       payload, content_type: str = "application/json",
                       extra: Tuple[Tuple[str, str], ...] = (),
                       keep_alive: bool = False) -> None:
        body = (json.dumps(payload).encode("utf-8") + b"\n"
                if isinstance(payload, dict) else payload)
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed", 411: "Length Required",
                  413: "Payload Too Large",
                  429: "Too Many Requests", 431: "Headers Too Large",
                  500: "Internal Server Error",
                  503: "Service Unavailable"}.get(status, "OK")
        head = [f"HTTP/1.1 {status} {reason}",
                f"Content-Type: {content_type}",
                f"Content-Length: {len(body)}",
                "Connection: keep-alive" if keep_alive
                else "Connection: close"]
        head += [f"{k}: {v}" for k, v in extra]
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin-1"))
        writer.write(body)
        await writer.drain()

    async def _dispatch(self, method: str, path: str, body: bytes,
                        writer: asyncio.StreamWriter,
                        keep_alive: bool = False) -> bool:
        """Route one request; returns whether the connection stays open
        (an SSE stream always closes — its framing is delimited by EOF)."""
        with self.tracer.span("http_request", cat="serving",
                              method=method, path=path) as sp:
            if path == "/healthz":
                status = 200
                await self._respond(writer, status, b"ok\n", "text/plain",
                                    keep_alive=keep_alive)
            elif path == "/readyz":
                status = 200 if self.ready else 503
                # the mesh shape rides the probe body (ISSUE 5): a
                # deployment that came up single-chip when the operator
                # expected mp=N is visible from the readiness check alone
                mp = getattr(self.engine, "mp", 1)
                msg = (f"ok mp={mp}\n".encode() if status == 200 else (
                    b"draining\n" if self._draining else b"not ready\n"))
                await self._respond(writer, status, msg, "text/plain",
                                    keep_alive=keep_alive)
            elif path == "/metrics":
                status = 200
                await self._respond(writer, status,
                                    metrics_page(self.registry),
                                    PROMETHEUS_CONTENT_TYPE,
                                    keep_alive=keep_alive)
            elif path == "/v1/completions":
                if method != "POST":
                    status = 405
                    await self._respond(writer, status, error_body(
                        "use POST", "method_not_allowed"),
                        keep_alive=keep_alive)
                else:
                    status, keep_alive = await self._handle_completion(
                        body, writer, keep_alive)
            else:
                status = 404
                await self._respond(writer, status, error_body(
                    f"no route {path!r}", "not_found"),
                    keep_alive=keep_alive)
            sp.set_attribute("status", status)
        self._count_http(path, status)
        return keep_alive

    # --- the completions route ----------------------------------------------
    async def _handle_completion(self, body: bytes,
                                 writer: asyncio.StreamWriter,
                                 keep_alive: bool = False,
                                 ) -> Tuple[int, bool]:
        """Returns (status, connection-still-open)."""
        if not self.ready:
            # draining OR the engine thread died: either way nobody will
            # ever drain the submit queue, so refuse instead of hanging
            msg = ("server is draining" if self._draining or self._stop
                   else "engine is not running")
            await self._respond(writer, 503, error_body(
                msg, "unavailable_error"), keep_alive=keep_alive)
            return 503, keep_alive
        try:
            creq = parse_completion_request(body, tokenize=self.cfg.tokenize)
        except ProtocolError as e:
            await self._respond(writer, 400, error_body(str(e)),
                                keep_alive=keep_alive)
            return 400, keep_alive

        # admission control: bounded in-flight set, counted rejections
        if len(self._handles) >= self.cfg.max_queue:
            self._rejected.inc()
            await self._respond(
                writer, 429,
                error_body("admission queue is full; retry later",
                           "overloaded_error"),
                extra=(("Retry-After", str(self.cfg.retry_after_s)),),
                keep_alive=keep_alive)
            return 429, keep_alive
        rid = f"cmpl-{next(self._ids)}"
        handle = _Handle(rid, creq, asyncio.Event())
        self._handles[rid] = handle
        try:
            self._submit_q.put_nowait(handle)
        except queue.Full:
            del self._handles[rid]
            self._rejected.inc()
            await self._respond(
                writer, 429,
                error_body("admission queue is full; retry later",
                           "overloaded_error"),
                extra=(("Retry-After", str(self.cfg.retry_after_s)),),
                keep_alive=keep_alive)
            return 429, keep_alive
        self._wake.set()

        timeout = creq.timeout if creq.timeout is not None \
            else self.cfg.default_timeout_s
        if timeout is not None:
            timeout = min(float(timeout), self.cfg.max_timeout_s)
        try:
            if creq.stream:
                status = await self._stream_response(handle, timeout, writer)
                return status, False  # SSE framing is delimited by EOF
            status = await self._json_response(handle, timeout, writer,
                                               keep_alive)
            return status, keep_alive
        except (ConnectionError, asyncio.TimeoutError):
            # client vanished mid-response: free the engine-side work
            self._request_abort(handle, FinishReason.ABORT)
            raise
        finally:
            self._handles.pop(rid, None)

    async def _collect(self, handle: _Handle, timeout: Optional[float],
                       on_tokens=None) -> Tuple[List[int], str]:
        """Wait on the engine until ``handle``'s request finishes (or its
        deadline aborts it); returns (tokens, finish_reason).  Streaming
        passes ``on_tokens`` to flush each batch as it lands."""
        deadline = None if timeout is None else time.monotonic() + timeout
        tokens: List[int] = []
        cursor = 0
        while True:
            req = handle.req
            if req is not None:
                out = req.output_tokens
                if cursor < len(out):
                    new = out[cursor:]
                    cursor = len(out)
                    tokens.extend(new)
                    if on_tokens is not None:
                        await on_tokens(new)
                if req.finished and cursor == len(req.output_tokens):
                    reason = (req.finish_reason.value
                              if req.finish_reason else "abort")
                    return tokens, reason
            elif handle.done:
                reason = (handle.cancel_reason.value
                          if handle.cancel_reason else "abort")
                return tokens, reason
            if deadline is not None and time.monotonic() >= deadline:
                # propagate the deadline into the scheduler, then keep
                # waiting (deadline-free) for the engine to acknowledge
                # so the partial output below is consistent
                self._request_abort(handle, FinishReason.TIMEOUT)
                deadline = None
                continue
            wait = 0.25 if deadline is None \
                else max(0.0, min(0.25, deadline - time.monotonic()))
            try:
                await asyncio.wait_for(handle.event.wait(), wait + 1e-3)
            except asyncio.TimeoutError:
                continue
            handle.event.clear()

    async def _json_response(self, handle: _Handle,
                             timeout: Optional[float],
                             writer: asyncio.StreamWriter,
                             keep_alive: bool = False) -> int:
        tokens, reason = await self._collect(handle, timeout)
        req = handle.req
        await self._respond(writer, 200, completion_body(
            handle.rid, self.cfg.model_name, tokens, reason,
            len(handle.creq.prompt_ids),
            error=getattr(req, "error", None)), keep_alive=keep_alive)
        return 200

    async def _stream_response(self, handle: _Handle,
                               timeout: Optional[float],
                               writer: asyncio.StreamWriter) -> int:
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-store\r\n"
                     b"Connection: close\r\n\r\n")
        await writer.drain()

        async def on_tokens(new: List[int]) -> None:
            writer.write(sse_event(chunk_body(
                handle.rid, self.cfg.model_name, new, None)))
            await writer.drain()

        _, reason = await self._collect(handle, timeout, on_tokens)
        writer.write(sse_event(chunk_body(
            handle.rid, self.cfg.model_name, [], reason)))
        writer.write(SSE_DONE)
        await writer.drain()
        return 200


# --- CLI / selftest ---------------------------------------------------------

def _toy_engine(layers: int = 2, num_blocks: int = 64,
                block_size: int = 4) -> EngineCore:
    import paddle_tpu as paddle
    from ..models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=layers))
    return EngineCore(model, num_blocks=num_blocks, block_size=block_size)


def _http(port: int, method: str, path: str, body: Optional[dict] = None):
    """Blocking loopback request (runs in an executor under asyncio)."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    payload = None if body is None else json.dumps(body)
    conn.request(method, path, payload,
                 {"Content-Type": "application/json"} if payload else {})
    resp = conn.getresponse()
    data = resp.read()
    status = resp.status
    conn.close()
    return status, data


async def _selftest_async() -> int:
    loop = asyncio.get_running_loop()
    engine = _toy_engine()
    server = CompletionServer(engine, ServerConfig(port=0))
    await server.start()
    try:
        status, data = await loop.run_in_executor(
            None, _http, server.port, "GET", "/readyz", None)
        assert status == 200, f"/readyz {status}"
        # readiness must report the mesh shape (ISSUE 5): mp=1 single-chip,
        # mp=N when a tensor-parallel mesh is live
        assert f"mp={engine.mp}".encode() in data, \
            f"/readyz body missing mesh shape: {data!r}"
        status, data = await loop.run_in_executor(
            None, _http, server.port, "POST", "/v1/completions",
            {"prompt": [5, 9, 23, 7], "max_tokens": 4})
        assert status == 200, f"completions {status}: {data!r}"
        obj = json.loads(data)
        choice = obj["choices"][0]
        assert len(choice["token_ids"]) == 4, choice
        assert choice["finish_reason"] == "length", choice
        status, data = await loop.run_in_executor(
            None, _http, server.port, "GET", "/metrics", None)
        assert status == 200 and b"serving_time_to_first_token" in data, \
            "metrics page missing serving histograms"
        assert b"serving_mp_shards" in data, \
            "metrics page missing the mp-shards gauge"
        print(f"selftest: OK (port {server.port}, mp={engine.mp}, "
              f"tokens {choice['token_ids']})")
        return 0
    finally:
        await server.shutdown(drain_timeout=2.0)


async def _serve_cli(args) -> int:
    engine = _toy_engine(layers=args.layers, num_blocks=args.blocks)
    server = CompletionServer(engine, ServerConfig(
        host=args.host, port=args.port,
        max_queue=args.max_queue,
        default_timeout_s=args.timeout))
    await server.start()
    loop = asyncio.get_running_loop()
    try:
        import signal

        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, server.request_shutdown)
    except (NotImplementedError, RuntimeError):
        pass
    print(f"serving on http://{server.cfg.host}:{server.port} mp={engine.mp} "
          "(POST /v1/completions; GET /healthz /readyz /metrics)")
    await server.serve_forever()
    return 0


def main(argv=None) -> int:
    import argparse
    import os

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # the TPU plugin's sitecustomize may pin the platform at startup;
        # mirror tests/conftest.py and override after import
        import jax

        jax.config.update("jax_platforms", "cpu")

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.server",
        description="HTTP/SSE serving frontend (toy model demo + selftest)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--blocks", type=int, default=256)
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--timeout", type=float, default=None,
                   help="default per-request deadline (seconds)")
    p.add_argument("--mp", type=int, default=1,
                   help="tensor-parallel degree: init a mesh with this "
                        "mp axis before building the engine (needs that "
                        "many devices; on CPU set XLA_FLAGS="
                        "--xla_force_host_platform_device_count=N)")
    p.add_argument("--selftest", action="store_true",
                   help="boot on an ephemeral port, serve one completion "
                        "against the toy model, exit 0 on success")
    args = p.parse_args(argv)
    if args.mp > 1:
        # tensor-parallel serving (ISSUE 5): build the mesh BEFORE any
        # engine (selftest included — the probe must exercise the real
        # degree) so parameters and KV pools land sharded.  On CPU this
        # needs XLA_FLAGS=--xla_force_host_platform_device_count=N.
        from ..distributed import topology

        topology.init_mesh(mp=args.mp)
    if args.selftest:
        return asyncio.run(_selftest_async())
    return asyncio.run(_serve_cli(args))


if __name__ == "__main__":
    import sys

    sys.exit(main())
