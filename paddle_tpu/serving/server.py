"""Asyncio HTTP/SSE frontend over :class:`EngineCore`.

The missing network surface above the continuous-batching engine (ISSUE 3
tentpole): a dependency-free HTTP/1.1 server on stdlib ``asyncio``
streams — no framework — exposing

* ``POST /v1/completions`` — OpenAI-style JSON (``protocol.py``);
  ``stream=true`` answers Server-Sent Events, one ``data:`` event per
  token batch, terminated by ``data: [DONE]``;
* ``GET /healthz`` — liveness (200 while the process runs);
* ``GET /readyz`` — readiness (503 the instant a drain begins, or if the
  engine thread died);
* ``GET /metrics`` — Prometheus text exposition of the engine's
  registry, byte-identical to ``observability.start_metrics_server``
  for the same registry (shared ``metrics_page`` handler).

HTTP/1.1 connections are **persistent** (ISSUE 3 follow-up (a)): a
handler loops request → response on one socket until the client sends
``Connection: close``, goes idle past ``keepalive_timeout_s``, or the
response is an SSE stream (self-delimiting — the socket closes after
``data: [DONE]``).  HTTP/1.0 clients must opt in with
``Connection: keep-alive``.

Threading model — a FLEET of engine threads, N async handlers
(ISSUE 6; dp=1 is simply a fleet of one):

    asyncio loop (handlers)          engine thread i (owns replica i)
    ───────────────────────          ───────────────────────────────
    parse ──router──▶ submit q_i ──▶ add_request(trace_id=...)
    await handle.event   ◀─notify──  step(): prefill/decode/sample
    read req.output_tokens[cursor:]  retire finished
    deadline hit ──owner──▶ abort q_i▶ abort_request(rid, TIMEOUT)

``EngineCore`` is not thread-safe and its jitted steps block, so each
replica runs its own background thread (``serving.fleet.EngineReplica``
— the PR 3 bounded submit/abort queue bridge, per replica); handlers
never touch a scheduler.  The :class:`~paddle_tpu.serving.fleet
.FleetRouter` places each request by **prefix-affinity consistent
hashing** over its leading prompt blocks (least-loaded fallback), and
routes aborts through the request→replica owner map so a deadline or
disconnect reaches the replica that actually holds the blocks.
Handlers read each request's append-only ``output_tokens`` directly
(safe under the GIL); engine threads wake sleeping handlers via
``loop.call_soon_threadsafe`` after every step.

The frontend owns three policies the engines deliberately do not:

* **admission control** — per replica: at most ``max_queue`` requests in
  flight on each; a POST gets ``429`` (+ ``Retry-After``,
  ``serving_admission_rejected_total``) only when EVERY eligible replica
  is at its cap.  All cross-thread queues are bounded
  (``queue.Queue(maxsize=...)`` — ``tools/check_bounded_metrics.py``
  lints this package).
* **per-request deadlines** — ``timeout`` in the body (clamped to
  ``max_timeout_s``, defaulting to ``default_timeout_s``); on expiry the
  handler propagates ``abort(TIMEOUT)`` through the router into the
  OWNING replica's scheduler, the request's blocks are freed, and the
  partial output is returned with ``finish_reason="timeout"``.
* **graceful drain** — ``shutdown()`` (or SIGTERM under the CLI) flips
  ``/readyz`` to 503 immediately and stops admitting fleet-wide;
  in-flight requests run to completion up to the drain deadline, then
  are aborted with TIMEOUT; every engine thread exits only once its pool
  is empty.

Per-replica health rides the router: a dead engine thread is excluded
from routing and the fleet serves on; ``/readyz`` (and POSTs) answer 503
only when the WHOLE fleet is down.  ``/readyz``'s body reports the fleet
shape — ``ok dp=N mp=M``.

Every request gets a trace id (``cmpl-<n>``) attached to the engine's
prefill/preempt/decode spans, so one request's lifecycle is
reconstructible from a single exported chrome trace.

Self-test (wired into the test suite)::

    JAX_PLATFORMS=cpu python -m paddle_tpu.serving.server --selftest
"""

from __future__ import annotations

import asyncio
import itertools
import json
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..observability.httpd import PROMETHEUS_CONTENT_TYPE, metrics_page
from .engine import EngineCore
from .fleet import (
    FleetConfig,
    FleetDown,
    FleetRouter,
    FleetSaturated,
    SubmitHandle,
)
from .protocol import (
    SSE_DONE,
    CompletionRequest,
    ProtocolError,
    chunk_body,
    completion_body,
    error_body,
    parse_completion_request,
    sse_event,
    usage_body,
)
from .request import FinishReason

_MAX_HEADER_BYTES = 16384
_ROUTES = ("/v1/completions", "/v1/requests", "/v1/debug/compiles",
           "/v1/debug/profile", "/v1/debug/audit", "/v1/debug/cache",
           "/v1/debug/alerts", "/v1/debug/history", "/v1/debug/wire",
           "/healthz", "/readyz", "/metrics")

# pre-registered metric names this module owns (tools/check_metrics_docs
# lints that each appears in README's metrics table)
METRIC_NAMES = (
    "serving_admission_rejected_total",
    "serving_http_requests_total",
)


@dataclass
class ServerConfig:
    host: str = "127.0.0.1"
    port: int = 0                 # 0 = ephemeral, read back from .port
    max_queue: int = 64           # per-replica engine-side in-flight cap
                                  # (must match FleetConfig.max_queue for
                                  # a pre-built fleet); the HTTP-side
                                  # in-flight set is capped at dp x this
    retry_after_s: int = 1        # 429 Retry-After hint
    default_timeout_s: Optional[float] = None   # None = no deadline
    max_timeout_s: float = 600.0
    drain_timeout_s: float = 5.0  # shutdown(): grace for in-flight work
    keepalive_timeout_s: float = 30.0  # idle wait for the NEXT request on
                                       # a persistent connection (also the
                                       # first-request header deadline)
    model_name: str = "paddle-tpu"
    tokenize: Optional[Callable[[str], List[int]]] = None


class _Handle(SubmitHandle):
    """One in-flight HTTP completion: the fleet's :class:`SubmitHandle`
    (rid / prompt / sampling / req / done / cancel_reason, routed and
    owned by one replica) plus the parsed protocol request and the
    asyncio waker created on the server's loop."""

    __slots__ = ("creq",)

    def __init__(self, rid: str, creq: CompletionRequest,
                 event: asyncio.Event):
        super().__init__(rid, creq.prompt_ids, sampling=creq.sampling(),
                         priority=creq.priority, event=event,
                         slo_ms=creq.slo_ms, retryable=creq.retryable)
        self.creq = creq


class CompletionServer:
    """HTTP frontend bound to a fleet of engine replicas.

    Accepts either a :class:`FleetRouter` (dp ≥ 1, ISSUE 6) or a bare
    :class:`EngineCore` — the latter is wrapped as a fleet of one: its
    ``serving_*`` series stay unlabeled on its own registry as before,
    with the ``serving_fleet_*`` family (a one-replica fleet) added
    alongside.  ``await start()`` spawns the engine threads and binds
    the socket; ``await shutdown()`` drains the whole fleet gracefully.
    ``registry`` defaults to the fleet's shared metrics registry, so
    ``GET /metrics`` serves per-replica-labeled ``serving_*`` series,
    the ``serving_fleet_*`` family, and whatever else the caller
    registered there."""

    def __init__(self, engine,
                 config: Optional[ServerConfig] = None, registry=None):
        self.cfg = config or ServerConfig()
        if isinstance(engine, FleetRouter):
            self.fleet = engine
            if self.cfg.max_queue != self.fleet.cfg.max_queue:
                # admission lives in the router (per-replica caps), so a
                # divergent ServerConfig.max_queue would be silently dead
                # configuration — refuse instead of letting the operator
                # believe their overload cap is enforced
                raise ValueError(
                    f"ServerConfig.max_queue={self.cfg.max_queue} but the "
                    f"fleet was built with FleetConfig.max_queue="
                    f"{self.fleet.cfg.max_queue}; admission is per-replica "
                    "and owned by the fleet — set the cap there (or pass "
                    "matching values)")
        else:
            self.fleet = FleetRouter.from_engine(
                engine, max_queue=self.cfg.max_queue)
        self.registry = (registry if registry is not None
                         else self.fleet.registry)
        self._handles: Dict[str, _Handle] = {}
        self._ids = itertools.count(1)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._draining = False
        self._stop = False
        self._shutdown_done: Optional[asyncio.Event] = None
        self._rejected = self.registry.counter(
            "serving_admission_rejected_total",
            "requests rejected 429 at admission (every replica saturated)")
        self.port: Optional[int] = None

    # --- single-engine compat views (dp=1 tests/tools poke these) -----------
    @property
    def engine(self) -> EngineCore:
        """Replica 0's engine — the single-engine compat surface
        (selftest / existing callers poke ``.engine.mp``, ``.engine.kv``
        ...).  A property, not a snapshot: the supervisor (ISSUE 12) may
        replace replica 0's engine wholesale on restart/quarantine."""
        return self.fleet.replicas[0].engine

    @property
    def tracer(self):
        # follows replica 0's engine like `engine` above — a snapshot
        # would pin a retired engine's tracer after a supervisor rebuild
        return self.engine.tracer

    @property
    def _engine_thread(self) -> Optional[threading.Thread]:
        return self.fleet.replicas[0].thread

    @property
    def _engine_error(self) -> Optional[str]:
        return self.fleet.replicas[0].error

    # --- lifecycle ----------------------------------------------------------
    async def start(self) -> "CompletionServer":
        self._loop = asyncio.get_running_loop()
        self._shutdown_done = asyncio.Event()
        self.fleet.start(notify=self._notify)
        self._server = await asyncio.start_server(
            self._handle_conn, self.cfg.host, self.cfg.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    def request_shutdown(self) -> None:
        """Thread/signal-safe trigger for a graceful drain."""
        if self._loop is None or self._loop.is_closed():
            return
        self._loop.call_soon_threadsafe(
            lambda: self._loop.create_task(self.shutdown()))

    async def shutdown(self, drain_timeout: Optional[float] = None) -> None:
        """Fleet-wide graceful drain: stop admission now (``/readyz`` →
        503 instantly, router refuses), let in-flight requests finish
        until the drain deadline, abort the stragglers with TIMEOUT
        through their owning replicas, stop every engine thread, close
        the socket.  Every replica exits with zero pool occupancy.
        Idempotent; concurrent callers await the first drain."""
        if self._draining:
            await self._shutdown_done.wait()
            return
        self._draining = True
        self.fleet.begin_drain()
        deadline = time.monotonic() + (
            drain_timeout if drain_timeout is not None
            else self.cfg.drain_timeout_s)
        while self._handles and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        stragglers = list(self._handles.values())
        if stragglers:
            # drain-deadline overrun: post-mortem bundle BEFORE the
            # aborts end the stragglers' timelines (flight recorder,
            # ISSUE 8)
            self.fleet.flight.trigger(
                "drain_overrun",
                detail=f"{len(stragglers)} request(s) still in flight "
                       f"at the HTTP drain deadline")
        for h in stragglers:
            self._request_abort(h, FinishReason.TIMEOUT)
        # handlers still need loop time to flush their (aborted) responses
        flush_deadline = time.monotonic() + 5.0
        while self._handles and time.monotonic() < flush_deadline:
            await asyncio.sleep(0.01)
        self._stop = True
        await self._loop.run_in_executor(None, self.fleet.stop)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self._shutdown_done.set()

    async def serve_forever(self) -> None:
        await self._shutdown_done.wait()

    @property
    def ready(self) -> bool:
        # ready while ANY replica's engine thread lives: the router
        # excludes dead replicas, so a partial fleet still serves (503
        # only when the whole fleet is down or draining)
        return (self._server is not None and not self._draining
                and self.fleet.alive)

    # --- fleet bridge -------------------------------------------------------
    def _notify(self, replica=None) -> None:
        """Wake waiting handlers (engine threads → loop thread).  The
        stepping replica passes itself, so only the handlers whose
        requests it owns are woken — wakeup work per step stays
        per-replica instead of dp × fleet-wide.  ``None`` wakes all."""
        loop = self._loop
        if loop is None or loop.is_closed():
            return
        for h in list(self._handles.values()):
            if replica is not None and h.replica is not replica:
                continue
            try:
                loop.call_soon_threadsafe(h.event.set)
            except RuntimeError:
                return  # swallow-ok: loop shut down mid-iteration — the handlers it would wake are being torn down with it

    def _unavailable_503(self) -> Tuple[str, Tuple]:
        """(message, extra headers) for a 503.  A draining server is
        going away (no retry hint); a fleet whose replicas are all
        momentarily down while the supervisor restarts them (ISSUE 12)
        tells the client to come back — 503 **with** ``Retry-After``,
        matching the 429 path."""
        if self._draining or self._stop:
            return "server is draining", ()
        n = self.fleet.restarting_count
        if n:
            return (f"fleet is restarting ({n} replica(s) recovering); "
                    "retry later",
                    (("Retry-After", str(self.cfg.retry_after_s)),))
        return "engine is not running", ()

    def _request_abort(self, h: _Handle, reason: FinishReason) -> None:
        h.cancel_reason = reason
        # the router's request→replica owner map sends the abort to the
        # replica that actually holds the request's blocks
        self.fleet.abort(h.rid, reason)

    # --- HTTP plumbing ------------------------------------------------------
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        """Serve one connection: HTTP/1.1 requests are persistent by
        default (``Connection: close`` or HTTP/1.0 without an explicit
        ``keep-alive`` opts out), so this loops request → response until
        the client closes, opts out, hits the idle timeout, or switches
        to a self-delimiting response (SSE streams close the socket —
        their framing has no length)."""
        try:
            while True:
                try:
                    head = await asyncio.wait_for(
                        reader.readuntil(b"\r\n\r\n"),
                        timeout=self.cfg.keepalive_timeout_s)
                except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                        asyncio.LimitOverrunError, ConnectionError):
                    return  # swallow-ok: idle timeout / client closed between requests — normal keep-alive connection end, not a fault
                if len(head) > _MAX_HEADER_BYTES:
                    await self._respond(writer, 431, error_body(
                        "headers too large"))
                    return
                lines = head.decode("latin-1").split("\r\n")
                parts = lines[0].split()
                if len(parts) != 3:
                    await self._respond(writer, 400, error_body(
                        "malformed request line"))
                    return
                method, target = parts[0].upper(), parts[1]
                version = parts[2].upper()
                headers = {}
                for ln in lines[1:]:
                    if ":" in ln:
                        k, v = ln.split(":", 1)
                        headers[k.strip().lower()] = v.strip()
                conn_hdr = headers.get("connection", "").lower()
                keep_alive = (conn_hdr != "close" if version == "HTTP/1.1"
                              else conn_hdr == "keep-alive")
                if "transfer-encoding" in headers:
                    # bodies are framed by Content-Length only; a chunked
                    # body left unread would desync the persistent stream
                    # (its bytes would parse as the next request line), so
                    # reject AND close
                    await self._respond(writer, 411, error_body(
                        "Transfer-Encoding unsupported; send "
                        "Content-Length"))
                    return
                body = b""
                clen = int(headers.get("content-length", 0) or 0)
                if clen:
                    if clen > 2 * 1024 * 1024:
                        await self._respond(writer, 413, error_body(
                            "body too large"))
                        return
                    body = await asyncio.wait_for(
                        reader.readexactly(clen), timeout=30.0)
                keep_alive = await self._dispatch(
                    method, target, body, writer, keep_alive)
                if not keep_alive:
                    return
        except (ConnectionError, asyncio.TimeoutError,
                asyncio.IncompleteReadError):
            pass  # swallow-ok: client went away; the per-request abort path already freed the engine-side work
        finally:
            try:
                writer.close()
            except Exception:
                pass  # swallow-ok: socket already dead — close() is best-effort teardown of a connection we are done with

    def _count_http(self, route: str, status: int) -> None:
        if route.startswith("/v1/requests"):
            route = "/v1/requests"  # one series for all request ids
        route = route if route in _ROUTES else "other"
        self.registry.counter(
            "serving_http_requests_total", "HTTP requests served",
            route=route, code=str(status)).inc()

    async def _respond(self, writer: asyncio.StreamWriter, status: int,
                       payload, content_type: str = "application/json",
                       extra: Tuple[Tuple[str, str], ...] = (),
                       keep_alive: bool = False) -> None:
        body = (json.dumps(payload).encode("utf-8") + b"\n"
                if isinstance(payload, dict) else payload)
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed", 409: "Conflict",
                  411: "Length Required",
                  413: "Payload Too Large",
                  429: "Too Many Requests", 431: "Headers Too Large",
                  500: "Internal Server Error",
                  503: "Service Unavailable"}.get(status, "OK")
        head = [f"HTTP/1.1 {status} {reason}",
                f"Content-Type: {content_type}",
                f"Content-Length: {len(body)}",
                "Connection: keep-alive" if keep_alive
                else "Connection: close"]
        head += [f"{k}: {v}" for k, v in extra]
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin-1"))
        writer.write(body)
        await writer.drain()

    async def _dispatch(self, method: str, target: str, body: bytes,
                        writer: asyncio.StreamWriter,
                        keep_alive: bool = False) -> bool:
        """Route one request; returns whether the connection stays open
        (an SSE stream always closes — its framing is delimited by EOF)."""
        path, _, query = target.partition("?")
        with self.tracer.span("http_request", cat="serving",
                              method=method, path=path) as sp:
            if path == "/healthz":
                status = 200
                await self._respond(writer, status, b"ok\n", "text/plain",
                                    keep_alive=keep_alive)
            elif path == "/readyz":
                status = 200 if self.ready else 503
                # the fleet shape rides the probe body (ISSUE 5/6): a
                # deployment that came up single-replica or single-chip
                # when the operator expected dp=N / mp=M is visible from
                # the readiness check alone
                mp = getattr(self.engine, "mp", 1)
                # a degraded numerics auditor ANNOTATES readiness but
                # never flips it (ISSUE 10): the fleet still serves —
                # the operator sees the flag on every probe and digs in
                # via /v1/debug/audit
                audit_ann = (" audit=degraded" if any(
                    r.engine.audit.degraded for r in self.fleet.replicas)
                    else "")
                # replicas the supervisor is bringing back (ISSUE 12):
                # annotated while the fleet still serves, and the WHOLE
                # body when every replica is momentarily down but
                # recovery is underway — probes can tell "restarting"
                # from "dead" (and clients get Retry-After on POSTs)
                restarting = self.fleet.restarting_count
                restart_ann = (f" restarting={restarting}" if restarting
                               else "")
                if status == 200:
                    msg = (f"ok dp={self.fleet.dp} mp={mp}{audit_ann}"
                           f"{restart_ann}\n").encode()
                elif self._draining:
                    msg = b"draining\n"
                elif restarting:
                    msg = f"restarting={restarting}\n".encode()
                else:
                    msg = b"not ready\n"
                await self._respond(writer, status, msg, "text/plain",
                                    keep_alive=keep_alive)
            elif path == "/metrics":
                status = 200
                # serving_fleet_* replica gauges refresh via the
                # registry collect hook inside prometheus_text (ISSUE
                # 14) — the same freshness the push gateway and the
                # history sampler observe
                await self._respond(writer, status,
                                    metrics_page(self.registry),
                                    PROMETHEUS_CONTENT_TYPE,
                                    keep_alive=keep_alive)
            elif path == "/v1/completions":
                if method != "POST":
                    status = 405
                    await self._respond(writer, status, error_body(
                        "use POST", "method_not_allowed"),
                        keep_alive=keep_alive)
                else:
                    status, keep_alive = await self._handle_completion(
                        body, writer, keep_alive)
            elif path == "/v1/requests" or path.startswith("/v1/requests/") \
                    or path.startswith("/v1/debug/"):
                if method != "GET":
                    status = 405
                    await self._respond(writer, status, error_body(
                        "use GET", "method_not_allowed"),
                        keep_alive=keep_alive)
                else:
                    # debug surfaces answer JSON for every outcome —
                    # unknown ids are 404 and malformed query params 400
                    # (never a 500 or a dropped connection; satellite
                    # bugfix, protocol-tested)
                    try:
                        if path.startswith("/v1/debug/"):
                            status = await self._handle_debug(
                                path, query, writer, keep_alive)
                        else:
                            status = await self._handle_requests_debug(
                                path, query, writer, keep_alive)
                    except (ConnectionError, asyncio.TimeoutError):
                        raise
                    except Exception as e:
                        status = 500
                        await self._respond(writer, status, error_body(
                            f"debug handler failed: {e}", "internal_error"),
                            keep_alive=keep_alive)
            else:
                status = 404
                await self._respond(writer, status, error_body(
                    f"no route {path!r}", "not_found"),
                    keep_alive=keep_alive)
            sp.set_attribute("status", status)
        self._count_http(path, status)
        return keep_alive

    # --- request-lifecycle debug routes (ISSUE 8) ---------------------------
    async def _handle_requests_debug(self, path: str, query: str,
                                     writer: asyncio.StreamWriter,
                                     keep_alive: bool) -> int:
        """``GET /v1/requests?state=active|recent`` (timeline summaries)
        and ``GET /v1/requests/{id}[?format=chrome]`` (one request's full
        timeline, or its per-request Chrome trace)."""
        import urllib.parse

        params = urllib.parse.parse_qs(query)
        lc = self.fleet.lifecycle
        source, complete = self._timeline_source()
        if path == "/v1/requests":
            state = params.get("state", ["active"])[0]
            if state not in ("active", "recent"):
                await self._respond(writer, 400, error_body(
                    "state must be 'active' or 'recent'"),
                    keep_alive=keep_alive)
                return 400
            await self._respond(
                writer, 200,
                {"object": "list", "state": state,
                 "source": source, "complete": complete,
                 "data": lc.summaries(state)},
                keep_alive=keep_alive)
            return 200
        rid = urllib.parse.unquote(path[len("/v1/requests/"):])
        fmt = params.get("format", [None])[0]
        if fmt not in (None, "json", "chrome"):
            # invalid query param: a crisp JSON 400, not a silently
            # ignored knob (satellite bugfix)
            await self._respond(writer, 400, error_body(
                f"format must be 'json' or 'chrome', got {fmt!r}"),
                keep_alive=keep_alive)
            return 400
        tl = lc.get(rid)
        if tl is None:
            await self._respond(writer, 404, error_body(
                f"no timeline for request {rid!r} (it may have aged out "
                "of the recent ring)", "not_found"),
                keep_alive=keep_alive)
            return 404
        if fmt == "chrome":
            # build from the timeline already in hand — a second lookup
            # could miss (the recent ring is bounded) and return None
            from ..observability.export import chrome_trace_dict

            payload = chrome_trace_dict(tl.chrome_spans(),
                                        epoch_offset=lc.epoch_offset)
        else:
            payload = dict(tl.to_dict(lc.epoch_offset), object="request",
                           source=source, complete=complete)
        await self._respond(writer, 200, payload, keep_alive=keep_alive)
        return 200

    def _timeline_source(self) -> Tuple[str, bool]:
        """Honesty marker for the timeline endpoints (ISSUE 17
        satellite): in ``--workers`` mode WITHOUT telemetry streaming
        the router's tracker holds router-synthesized stand-ins only, so
        the response must say ``complete: false`` instead of presenting
        a router-only view as the whole story."""
        proxies = [r.engine for r in self.fleet.replicas
                   if hasattr(r.engine, "distrib_state")]
        if not proxies:
            return "in-process", True
        if all(getattr(p, "_telemetry", False) for p in proxies):
            return "router+workers", True
        return "router-only", False

    # --- step-level introspection routes (ISSUE 9) --------------------------
    def _debug_int(self, params, name: str, default: int,
                   lo: int, hi: int) -> int:
        """Parse an integer query param in [lo, hi]; raises ValueError
        with an operator-readable message (mapped to a JSON 400)."""
        raw = params.get(name, [None])[0]
        if raw is None:
            return default
        try:
            v = int(raw)
        except ValueError:
            raise ValueError(
                f"{name} must be an integer, got {raw!r}") from None
        if not lo <= v <= hi:
            raise ValueError(f"{name} must be in [{lo}, {hi}], got {v}")
        return v

    def _replica_rows(self, reps, fetch) -> List[Dict]:
        """Per-replica debug rows with mid-restart degradation (ISSUE 16
        satellite bugfix): a replica that is being rebuilt/respawned —
        unhealthy, or whose snapshot fetch fails during the engine swap
        / worker respawn window — contributes a
        ``{"status": "restarting"}`` row instead of 404/500-ing the
        whole endpoint.  Debug surfaces stay useful DURING incidents,
        which is exactly when operators hit them."""
        rows = []
        for r in reps:
            if not r.healthy:
                rows.append({"replica": str(r.index), "enabled": False,
                             "status": "restarting"})
                continue
            try:
                rows.append(dict(fetch(r), replica=str(r.index)))
            except Exception:
                rows.append({"replica": str(r.index), "enabled": False,
                             "status": "restarting"})
        return rows

    async def _handle_debug(self, path: str, query: str,
                            writer: asyncio.StreamWriter,
                            keep_alive: bool) -> int:
        """``GET /v1/debug/compiles`` — per-replica compile-time
        attribution table (every observed trace+compile with its wall
        seconds); ``GET /v1/debug/profile?steps=N[&replica=i]`` — arm a
        bounded capture window on the replica's StepProfiler, wait for
        the next N engine steps, answer the annotated Chrome trace."""
        import urllib.parse

        from ..observability.stepprof import CaptureBusy

        params = urllib.parse.parse_qs(query)
        if path == "/v1/debug/audit":
            # numerics-audit status (ISSUE 10): per-replica auditor
            # snapshots (counters, last divergence, repro paths) plus a
            # fleet-level status roll-up — "ok" only when every enabled
            # auditor is clean, "degraded" the moment any diverged,
            # "disabled" when no replica audits
            try:
                replica = self._debug_int(params, "replica", -1,
                                          -1, 1 << 30)
            except ValueError as e:
                await self._respond(writer, 400, error_body(str(e)),
                                    keep_alive=keep_alive)
                return 400
            if replica >= self.fleet.dp:
                await self._respond(writer, 404, error_body(
                    f"no replica {replica} (fleet has dp="
                    f"{self.fleet.dp})", "not_found"),
                    keep_alive=keep_alive)
                return 404
            reps = (self.fleet.replicas if replica < 0
                    else [self.fleet.replicas[replica]])
            data = self._replica_rows(
                reps, lambda r: r.engine.audit.snapshot())
            enabled = [d for d in data if d.get("enabled")]
            status = ("disabled" if not enabled else
                      "degraded" if any(d.get("status") == "degraded"
                                        for d in enabled) else "ok")
            await self._respond(
                writer, 200,
                {"object": "list", "status": status, "data": data},
                keep_alive=keep_alive)
            return 200
        if path == "/v1/debug/cache":
            # KV-cache & memory observability (ISSUE 13): per-replica
            # pool timelines, prefix-heat tables, hit-depth/eviction
            # reports and per-request attribution, plus a fleet view —
            # per-replica cached-token ratios and the max−min imbalance
            # (the cache-aware rebalancing signal)
            try:
                replica = self._debug_int(params, "replica", -1,
                                          -1, 1 << 30)
            except ValueError as e:
                await self._respond(writer, 400, error_body(str(e)),
                                    keep_alive=keep_alive)
                return 400
            if replica >= self.fleet.dp:
                await self._respond(writer, 404, error_body(
                    f"no replica {replica} (fleet has dp="
                    f"{self.fleet.dp})", "not_found"),
                    keep_alive=keep_alive)
                return 404
            reps = (self.fleet.replicas if replica < 0
                    else [self.fleet.replicas[replica]])
            data = self._replica_rows(
                reps, lambda r: r.engine.cachestat.snapshot())
            # ONE ratio snapshot: the body's imbalance is derived from
            # the very ratios it reports, so the two fields can never
            # disagree under concurrent traffic
            ratios = self.fleet.cached_token_ratios()
            vals = [v for v in ratios.values() if v is not None]
            imbalance = max(vals) - min(vals) if vals else None
            self.fleet.sample_gauges()  # the imbalance gauge tracks it
            await self._respond(
                writer, 200,
                {"object": "list",
                 "status": ("ok" if any(d.get("enabled") for d in data)
                            else "disabled"),
                 "fleet": {
                     "dp": self.fleet.dp,
                     "cached_token_ratios": {
                         k: (None if v is None else round(v, 4))
                         for k, v in ratios.items()},
                     "cache_imbalance": (None if imbalance is None
                                         else round(imbalance, 4)),
                 },
                 "data": data},
                keep_alive=keep_alive)
            return 200
        if path == "/v1/debug/alerts":
            # alert-engine state (ISSUE 14): every rule with its live
            # pending/firing state + recent transitions, plus engine
            # totals; ?rule= filters to one rule (unknown -> 404)
            alerts = self.fleet.alerts
            if alerts is None:
                await self._respond(
                    writer, 200,
                    {"object": "alerts", "status": "disabled",
                     "rules": 0, "data": []}, keep_alive=keep_alive)
                return 200
            snap = alerts.snapshot()
            rule = params.get("rule", [None])[0]
            if rule is not None:
                rows = [d for d in snap["data"]
                        if d["rule"]["name"] == rule]
                if not rows:
                    await self._respond(writer, 404, error_body(
                        f"no alert rule {rule!r}", "not_found"),
                        keep_alive=keep_alive)
                    return 404
                # scope status + firing to the queried rule: an
                # operator asking about an inactive rule must not read
                # "firing" off some OTHER rule's incident
                snap = dict(snap, data=rows, firing=[
                    d["rule"]["name"] for d in rows
                    if d["state"] == "firing"])
            status = ("firing" if snap["firing"] else "ok")
            await self._respond(
                writer, 200,
                dict({"object": "alerts", "status": status}, **snap),
                keep_alive=keep_alive)
            return 200
        if path == "/v1/debug/history":
            # metrics history (ISSUE 14): ?series=<metric name> answers
            # the per-label-set windows (per-replica view) plus a fleet
            # aggregate; without ?series= the series index is returned.
            # ?window=N bounds the returned samples (malformed -> 400,
            # unknown series -> 404 — protocol-clean like /v1/debug/cache)
            history = self.fleet.history
            if history is None:
                await self._respond(
                    writer, 200,
                    {"object": "history", "status": "disabled",
                     "data": []}, keep_alive=keep_alive)
                return 200
            try:
                window = self._debug_int(params, "window",
                                         history.cfg.ring_len, 1,
                                         history.cfg.ring_len)
            except ValueError as e:
                await self._respond(writer, 400, error_body(str(e)),
                                    keep_alive=keep_alive)
                return 400
            series = params.get("series", [None])[0]
            if series is None:
                await self._respond(
                    writer, 200,
                    {"object": "history", "status": "ok",
                     "stats": history.stats(),
                     "series": history.names()}, keep_alive=keep_alive)
                return 200
            keys = history.match(series)
            if not keys:
                await self._respond(writer, 404, error_body(
                    f"no recorded series {series!r} (see "
                    "/v1/debug/history for the index)", "not_found"),
                    keep_alive=keep_alive)
                return 404
            rows = [{"key": k, "kind": history.kind(k),
                     "latest": history.latest(k),
                     "window": history.window(k, window)}
                    for k in keys]
            fleet_view = {"latest_sum": history.name_latest_sum(series)}
            if all(r["kind"] == "counter" for r in rows):
                fleet_view["increase"] = history.name_increase(
                    series, window)
            await self._respond(
                writer, 200,
                {"object": "history", "status": "ok", "series": series,
                 "window": window, "fleet": fleet_view, "data": rows},
                keep_alive=keep_alive)
            return 200
        if path == "/v1/debug/compiles":
            data = []
            totals: Dict[str, Dict] = {}
            aot: Dict[str, Dict] = {}
            for r in self.fleet.replicas:
                if not r.healthy:
                    # mid-restart replica (ISSUE 16 satellite): degrade
                    # its slot instead of failing the fleet-wide table
                    aot[str(r.index)] = {"status": "restarting"}
                    continue
                try:
                    sp = r.engine.stepprof
                    rows = [dict(row, replica=str(r.index))
                            for row in sp.compile_table()]
                    tots = list(sp.compile_totals().items())
                    # AOT attribution (ISSUE 15): per-replica artifact
                    # state — with an artifact loaded the rows above
                    # should be EMPTY (any row carries aot: true, the
                    # bug marker)
                    aot[str(r.index)] = sp.aot_snapshot()
                except Exception:
                    aot[str(r.index)] = {"status": "restarting"}
                    continue
                data.extend(rows)
                for prog, t in tots:
                    agg = totals.setdefault(
                        prog, {"seconds": 0.0, "count": 0})
                    agg["seconds"] = round(agg["seconds"] + t["seconds"], 6)
                    agg["count"] += t["count"]
            await self._respond(
                writer, 200,
                {"object": "list", "data": data, "totals": totals,
                 "aot": aot,
                 "step_profile": self.engine.stepprof.enabled},
                keep_alive=keep_alive)
            return 200
        if path == "/v1/debug/wire":
            # ISSUE 17: per-worker wire-latency attribution + clock-sync
            # + telemetry-merge state.  In-process fleets answer a crisp
            # "disabled" shape (there is no wire), mirroring the other
            # debug endpoints' degrade-not-404 discipline.
            rows: Dict[str, Dict] = {}
            for r in self.fleet.replicas:
                eng = r.engine
                if not hasattr(eng, "distrib_state"):
                    continue
                try:
                    rows[str(r.index)] = eng.distrib_state()
                except Exception:
                    rows[str(r.index)] = {"status": "restarting"}
            if not rows:
                await self._respond(
                    writer, 200,
                    {"object": "wire", "enabled": False,
                     "reason": "in-process fleet: no process wire to "
                               "attribute (use --workers)"},
                    keep_alive=keep_alive)
                return 200
            from ..observability.distrib import WireStats
            agg = {"steps": 0, "wire_s": 0.0, "queue_s": 0.0,
                   "engine_s": 0.0, "total_s": 0.0}
            for state in rows.values():
                w = state.get("wire") or {}
                for k in agg:
                    agg[k] += w.get(k, 0) or 0
            await self._respond(
                writer, 200,
                {"object": "wire", "enabled": True,
                 "shares": WireStats._shares(agg),
                 "steps": agg["steps"],
                 "replicas": rows},
                keep_alive=keep_alive)
            return 200
        if path != "/v1/debug/profile":
            await self._respond(writer, 404, error_body(
                f"no route {path!r}", "not_found"),
                keep_alive=keep_alive)
            return 404
        try:
            timeout_s = self._debug_int(params, "timeout_s", 30, 1, 300)
            replica = self._debug_int(params, "replica", 0,
                                      0, 1 << 30)
        except ValueError as e:
            await self._respond(writer, 400, error_body(str(e)),
                                keep_alive=keep_alive)
            return 400
        if replica >= self.fleet.dp:
            # an unknown id is a 404, not a malformed request
            await self._respond(writer, 404, error_body(
                f"no replica {replica} (fleet has dp={self.fleet.dp})",
                "not_found"), keep_alive=keep_alive)
            return 404
        sp = self.fleet.replicas[replica].engine.stepprof
        try:
            # bound against the TARGET profiler's own cap — one limit,
            # owned by arm_capture, never duplicated here
            steps = self._debug_int(params, "steps", 32, 1,
                                    sp.max_capture_steps)
            window = sp.arm_capture(steps)
        except CaptureBusy as e:
            await self._respond(writer, 409, error_body(
                str(e), "conflict"), keep_alive=keep_alive)
            return 409
        except (RuntimeError, ValueError) as e:
            # step_profile disabled, or a steps value the profiler's
            # own validation refuses — either way a client error
            await self._respond(writer, 400, error_body(str(e)),
                                keep_alive=keep_alive)
            return 400
        try:
            deadline = time.monotonic() + timeout_s
            while not window.done.is_set() \
                    and time.monotonic() < deadline:
                await asyncio.sleep(0.01)
            if not window.done.is_set():
                # idle/slow engine: return what the window captured so
                # far (``complete: false``) instead of hanging.  The
                # finalize runs in an executor — a device stop_trace
                # flushing its XPlane dump must not stall the event
                # loop — and may lose to a concurrent engine-side
                # finalize, so keep polling ``done`` afterwards: never
                # read a half-built result
                await self._loop.run_in_executor(
                    None, sp.cancel_capture, window)
                grace = time.monotonic() + 30.0
                while not window.done.is_set() \
                        and time.monotonic() < grace:
                    await asyncio.sleep(0.01)
            if window.result is None:
                await self._respond(writer, 503, error_body(
                    "capture window did not finalize in time",
                    "unavailable_error"), keep_alive=keep_alive)
                return 503
            await self._respond(writer, 200, window.result,
                                keep_alive=keep_alive)
            return 200
        finally:
            # the handler task can die mid-wait (client disconnect,
            # CancelledError on shutdown): an armed window left behind
            # would 409 every future capture — and on device leave
            # jax.profiler tracing.  No-op when already finalized; runs
            # on its own thread so a slow device stop_trace never
            # stalls the event loop (and cancellation can't skip it).
            threading.Thread(target=sp.cancel_capture, args=(window,),
                             daemon=True).start()

    # --- the completions route ----------------------------------------------
    async def _handle_completion(self, body: bytes,
                                 writer: asyncio.StreamWriter,
                                 keep_alive: bool = False,
                                 ) -> Tuple[int, bool]:
        """Returns (status, connection-still-open)."""
        unavailable_msg, unavailable_extra = self._unavailable_503()
        if not self.ready:
            # draining OR every engine thread died: either way nobody
            # will ever drain a submit queue, so refuse instead of
            # hanging.  A fleet mid-restart (ISSUE 12) answers with
            # Retry-After — the outage is transient by construction.
            await self._respond(writer, 503, error_body(
                unavailable_msg, "unavailable_error"),
                extra=unavailable_extra, keep_alive=keep_alive)
            return 503, keep_alive
        try:
            creq = parse_completion_request(body, tokenize=self.cfg.tokenize)
        except ProtocolError as e:
            await self._respond(writer, 400, error_body(str(e)),
                                keep_alive=keep_alive)
            return 400, keep_alive

        # two admission layers: the router's per-replica caps bound
        # ENGINE-side work (evicted as requests finish computing), while
        # this server-wide cap bounds HTTP-side work — handles, sockets,
        # buffered output still flushing to slow clients — which can
        # outlive the engine's interest in a request
        if len(self._handles) >= self.cfg.max_queue * self.fleet.dp:
            self._rejected.inc()
            self.fleet.flight.note_rejection()
            await self._respond(
                writer, 429,
                error_body("admission queue is full; retry later",
                           "overloaded_error"),
                extra=(("Retry-After", str(self.cfg.retry_after_s)),),
                keep_alive=keep_alive)
            return 429, keep_alive
        # router admission is per replica: prefix-affinity target first,
        # least-loaded fallback; 429 only when EVERY eligible replica is
        # at its in-flight cap
        rid = f"cmpl-{next(self._ids)}"
        handle = _Handle(rid, creq, asyncio.Event())
        try:
            self.fleet.submit(handle)
        except FleetSaturated:
            self._rejected.inc()
            self.fleet.flight.note_rejection()
            await self._respond(
                writer, 429,
                error_body("admission queue is full; retry later",
                           "overloaded_error"),
                extra=(("Retry-After", str(self.cfg.retry_after_s)),),
                keep_alive=keep_alive)
            return 429, keep_alive
        except FleetDown:
            unavailable_msg, unavailable_extra = self._unavailable_503()
            await self._respond(writer, 503, error_body(
                unavailable_msg, "unavailable_error"),
                extra=unavailable_extra, keep_alive=keep_alive)
            return 503, keep_alive
        self._handles[rid] = handle

        timeout = creq.timeout if creq.timeout is not None \
            else self.cfg.default_timeout_s
        if timeout is not None:
            timeout = min(float(timeout), self.cfg.max_timeout_s)
        try:
            if creq.stream:
                status = await self._stream_response(handle, timeout, writer)
                return status, False  # SSE framing is delimited by EOF
            status = await self._json_response(handle, timeout, writer,
                                               keep_alive)
            return status, keep_alive
        except (ConnectionError, asyncio.TimeoutError):
            # client vanished mid-response: free the engine-side work
            self._request_abort(handle, FinishReason.ABORT)
            raise
        finally:
            self._handles.pop(rid, None)

    async def _collect(self, handle: _Handle, timeout: Optional[float],
                       on_tokens=None) -> Tuple[List[int], str]:
        """Wait on the engine until ``handle``'s request finishes (or its
        deadline aborts it); returns (tokens, finish_reason).  Streaming
        passes ``on_tokens`` to flush each batch as it lands."""
        deadline = None if timeout is None else time.monotonic() + timeout
        tokens: List[int] = []
        cursor = 0
        while True:
            req = handle.req
            if req is not None:
                out = req.output_tokens
                if cursor < len(out):
                    new = out[cursor:]
                    cursor = len(out)
                    tokens.extend(new)
                    if on_tokens is not None:
                        await on_tokens(new)
                if req.finished and cursor == len(req.output_tokens):
                    reason = (req.finish_reason.value
                              if req.finish_reason else "abort")
                    return tokens, reason
            if handle.done and (req is None or not req.finished):
                # terminal without an engine finish: cancelled before
                # admission, or the owning replica died and the
                # supervisor closed the handle (ISSUE 12 — ``req`` may
                # still hold the dead engine's frozen partial output,
                # flushed above)
                reason = (handle.cancel_reason.value
                          if handle.cancel_reason else "abort")
                return tokens, reason
            if deadline is not None and time.monotonic() >= deadline:
                # propagate the deadline into the scheduler, then keep
                # waiting (deadline-free) for the engine to acknowledge
                # so the partial output below is consistent
                self._request_abort(handle, FinishReason.TIMEOUT)
                deadline = None
                continue
            wait = 0.25 if deadline is None \
                else max(0.0, min(0.25, deadline - time.monotonic()))
            try:
                await asyncio.wait_for(handle.event.wait(), wait + 1e-3)
            except asyncio.TimeoutError:
                continue  # swallow-ok: the wait IS a poll; timeout means re-check request state, not a fault
            handle.event.clear()

    @staticmethod
    def _prompt_cached(handle: _Handle) -> int:
        """Cached prompt tokens at the request's first admission (the
        usage attribution, ISSUE 13); 0 when never admitted."""
        cached = getattr(handle.req, "prompt_cached_tokens", None)
        return int(cached or 0)

    async def _json_response(self, handle: _Handle,
                             timeout: Optional[float],
                             writer: asyncio.StreamWriter,
                             keep_alive: bool = False) -> int:
        tokens, reason = await self._collect(handle, timeout)
        req = handle.req
        await self._respond(writer, 200, completion_body(
            handle.rid, self.cfg.model_name, tokens, reason,
            len(handle.creq.prompt_ids),
            error=getattr(req, "error", None),
            prompt_cached_tokens=self._prompt_cached(handle)),
            extra=(("X-Request-Id", handle.rid),), keep_alive=keep_alive)
        return 200

    async def _stream_response(self, handle: _Handle,
                               timeout: Optional[float],
                               writer: asyncio.StreamWriter) -> int:
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-store\r\n"
                     + f"X-Request-Id: {handle.rid}\r\n".encode("latin-1")
                     + b"Connection: close\r\n\r\n")
        # id-bearing FIRST chunk, before any token exists: an SSE client
        # learns the request id immediately (for /v1/requests/{id} or an
        # out-of-band abort) instead of only once the first token lands
        writer.write(sse_event(chunk_body(
            handle.rid, self.cfg.model_name, [], None)))
        await writer.drain()

        async def on_tokens(new: List[int]) -> None:
            writer.write(sse_event(chunk_body(
                handle.rid, self.cfg.model_name, new, None)))
            await writer.drain()

        tokens, reason = await self._collect(handle, timeout, on_tokens)
        # the FINAL chunk carries the usage block — SSE clients see the
        # prefix-cache attribution too (ISSUE 13 satellite)
        writer.write(sse_event(chunk_body(
            handle.rid, self.cfg.model_name, [], reason,
            usage=usage_body(len(handle.creq.prompt_ids), len(tokens),
                             self._prompt_cached(handle)))))
        writer.write(SSE_DONE)
        await writer.drain()
        return 200


# --- CLI / selftest ---------------------------------------------------------

def _toy_engine(layers: int = 2, num_blocks: int = 64,
                block_size: int = 4, registry=None,
                metrics_labels=None, audit=None,
                unified: bool = False, aot=None,
                max_tokens_per_step: Optional[int] = None,
                spec=None, burst_steps: int = 0,
                role: str = "unified") -> EngineCore:
    import paddle_tpu as paddle
    from ..models import LlamaConfig, LlamaForCausalLM
    from .engine import EngineConfig
    from .scheduler import SchedulerConfig

    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=layers))
    scheduler = None
    if max_tokens_per_step is not None:
        scheduler = SchedulerConfig(
            max_tokens_per_step=int(max_tokens_per_step))
    return EngineCore(model,
                      config=EngineConfig(num_blocks=num_blocks,
                                          block_size=block_size,
                                          audit=audit,
                                          unified_step=unified,
                                          scheduler=scheduler,
                                          spec=spec,
                                          burst_steps=burst_steps,
                                          aot=aot,
                                          role=role),
                      registry=registry, metrics_labels=metrics_labels)


def _toy_fleet(dp: int = 1, layers: int = 2, num_blocks: int = 64,
               max_queue: int = 64,
               flight_dir: Optional[str] = None,
               audit=None, unified: bool = False,
               fault_plan=None, alert_rules=None,
               aot=None, max_tokens_per_step: Optional[int] = None,
               spec=None, burst_steps: int = 0,
               roles=None) -> FleetRouter:
    """A dp-replica fleet of toy engines on one shared registry: each
    replica gets its OWN model instance (engine threads swap parameter
    values during the traced step — modules must not be shared) with
    per-replica-labeled serving series.  Composes with ``--mp``: build
    the mesh first and every replica's engine runs mesh-spanning.  The
    factory is deterministic (seed before build), so the supervisor can
    rebuild a crashed replica with identical weights.  ``aot`` is ONE
    loaded :class:`~paddle_tpu.serving.aot.AotArtifact` shared by every
    replica (ISSUE 15) — the fleet refuses per-replica loads."""
    return FleetRouter.build(
        lambda i, registry: _toy_engine(
            layers=layers, num_blocks=num_blocks, registry=registry,
            metrics_labels={"replica": str(i)}, audit=audit,
            unified=unified, aot=aot,
            max_tokens_per_step=max_tokens_per_step, spec=spec,
            burst_steps=burst_steps,
            role=(roles[i] if roles else "unified")),
        dp=dp, config=FleetConfig(max_queue=max_queue,
                                  flight_dir=flight_dir,
                                  fault_plan=fault_plan,
                                  alert_rules=alert_rules,
                                  roles=roles))


def _http(port: int, method: str, path: str, body: Optional[dict] = None):
    """Blocking loopback request (runs in an executor under asyncio)."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    payload = None if body is None else json.dumps(body)
    conn.request(method, path, payload,
                 {"Content-Type": "application/json"} if payload else {})
    resp = conn.getresponse()
    data = resp.read()
    status = resp.status
    conn.close()
    return status, data


async def _selftest_async(dp: int = 1, audit_sample: int = 1,
                          unified: bool = False,
                          aot_path: Optional[str] = None,
                          layers: int = 2, blocks: int = 64) -> int:
    from ..observability.audit import AuditConfig

    loop = asyncio.get_running_loop()
    # the selftest always exercises the numerics-audit surface (ISSUE
    # 10): every step sampled by default, so the probe completion runs
    # with the shadow oracle live and must come back divergence-free.
    # --unified routes the probe through the packed ragged step program
    # (ISSUE 11) under the same audit net.  --aot-path loads the saved
    # program set ONCE and the probe must then serve with ZERO traces
    # (ISSUE 15; the audit net stays live — the in-trace logit stats
    # are part of the exported programs).
    aot = None
    if aot_path:
        from .aot import AotArtifact

        aot = AotArtifact.load(aot_path)
    fleet = _toy_fleet(dp=dp, layers=layers, num_blocks=blocks,
                       audit=AuditConfig(
                           enabled=True,
                           sample_every=max(1, audit_sample)),
                       unified=unified, aot=aot)
    server = CompletionServer(fleet, ServerConfig(port=0))
    engine = server.engine
    await server.start()
    try:
        status, data = await loop.run_in_executor(
            None, _http, server.port, "GET", "/readyz", None)
        assert status == 200, f"/readyz {status}"
        # readiness must report the fleet shape (ISSUE 5/6): a deployment
        # that came up single-replica or single-chip when the operator
        # expected dp=N / mp=M is visible from the probe body alone
        assert f"dp={fleet.dp} mp={engine.mp}".encode() in data, \
            f"/readyz body missing fleet shape: {data!r}"
        status, data = await loop.run_in_executor(
            None, _http, server.port, "POST", "/v1/completions",
            {"prompt": [5, 9, 23, 7], "max_tokens": 4})
        assert status == 200, f"completions {status}: {data!r}"
        obj = json.loads(data)
        choice = obj["choices"][0]
        assert len(choice["token_ids"]) == 4, choice
        assert choice["finish_reason"] == "length", choice
        # lifecycle debug surface (ISSUE 8): the completion's timeline is
        # queryable after it finished
        status, data = await loop.run_in_executor(
            None, _http, server.port, "GET", "/v1/requests?state=recent",
            None)
        assert status == 200, f"/v1/requests {status}"
        rows = json.loads(data)["data"]
        assert any(row["id"] == obj["id"] for row in rows), \
            f"finished completion missing from /v1/requests: {rows}"
        status, data = await loop.run_in_executor(
            None, _http, server.port, "GET", "/metrics", None)
        assert status == 200 and b"serving_time_to_first_token" in data, \
            "metrics page missing serving histograms"
        assert b"serving_e2e_seconds" in data, \
            "metrics page missing the SLO breakdown histograms"
        assert b"serving_mp_shards" in data, \
            "metrics page missing the mp-shards gauge"
        # the probe went through the router: fleet series must exist and
        # exactly one routing counter must have counted it
        assert b"serving_fleet_replicas" in data, \
            "metrics page missing the serving_fleet_* family"
        routed = sum(fleet.routing_counts.values())
        assert routed >= 1, "completion did not route through the fleet"
        # numerics-audit surface (ISSUE 10): the completion ran under
        # sample_every=1, so at least one step was shadow-audited with
        # zero divergences and the debug endpoint reports ok
        assert b"serving_audit_steps_total" in data, \
            "metrics page missing the serving_audit_* family"
        status, data = await loop.run_in_executor(
            None, _http, server.port, "GET", "/v1/debug/audit", None)
        assert status == 200, f"/v1/debug/audit {status}"
        audit = json.loads(data)
        assert audit["status"] == "ok", audit
        audited = sum(sum(row["audited_launches"].values())
                      for row in audit["data"])
        assert audited > 0, f"no audited step launches: {audit}"
        assert all(sum(row["divergences"].values()) == 0
                   for row in audit["data"]), audit
        # a crashed shadow oracle must not pass as "audited clean"
        assert all(row["oracle_failures"] == 0
                   for row in audit["data"]), audit
        if aot is not None:
            # zero-trace contract (ISSUE 15): the probe served entirely
            # from the loaded artifact — no engine traced anything
            traces = sum(e.prefill_trace_count + e.decode_trace_count
                         + e.ragged_trace_count for e in fleet.engines)
            assert traces == 0, \
                f"AOT selftest traced {traces} program(s)"
            status, data = await loop.run_in_executor(
                None, _http, server.port, "GET", "/v1/debug/compiles",
                None)
            obj = json.loads(data)
            assert status == 200 and not obj["data"], obj
            assert all(row["loaded"] for row in obj["aot"].values()), obj
        print(f"selftest: OK (port {server.port}, dp={fleet.dp}, "
              f"mp={engine.mp}, tokens {choice['token_ids']}, "
              f"audited launches {audited}"
              + (f", aot programs {aot.program_count}, zero traces"
                 if aot is not None else "") + ")")
        return 0
    finally:
        await server.shutdown(drain_timeout=2.0)


def _spec_dict(args) -> Optional[dict]:
    """SpecConfig kwargs from the CLI (``None`` = spec decoding off)."""
    if not getattr(args, "spec_decode", False):
        return None
    return {"enabled": True, "k": args.spec_k}


def _build_procfleet(args, fault_plan=None, alert_rules=None):
    # cross-process fleet (ISSUE 16): N worker processes behind the
    # SAME router/supervisor stack, reached over the wire protocol.
    # The router process never loads program bytes — workers boot
    # off the shared artifact themselves (--aot-path is forwarded)
    from .procfleet import ProcessFleet, ProcessFleetConfig

    pf = ProcessFleet(ProcessFleetConfig(
        dp=args.workers, layers=args.layers, num_blocks=args.blocks,
        max_num_seqs=8, max_prefill_tokens_per_step=None,
        max_tokens_per_step=args.max_tokens_per_step,
        # multi-chip workers (ISSUE 18): each worker process builds its
        # own mp-way mesh slice; the degree (and the spec-decoding
        # config) is validated at every wire handshake
        mp=args.mp, spec=_spec_dict(args),
        burst_steps=args.burst,
        unified=args.unified,
        audit_enabled=bool(args.audit_sample),
        audit_sample_every=args.audit_sample or 1,
        aot_path=args.aot_path, compile_cache=args.compile_cache,
        warm_boot=args.aot_warm,
        roles=getattr(args, "roles_list", None),
        fleet=FleetConfig(max_queue=args.max_queue,
                          flight_dir=args.flight_dir,
                          fault_plan=fault_plan,
                          alert_rules=alert_rules)))
    # ISSUE 17 satellite: the SLO actuators are now one flag away on the
    # serving CLI instead of library-only calls
    if getattr(args, "autoscale", False):
        from .procfleet import AutoscalerConfig

        pf.enable_autoscaler(AutoscalerConfig(
            min_replicas=args.autoscale_min,
            max_replicas=args.autoscale_max))
        print(f"autoscaler: live (min={pf.autoscaler.min_replicas}, "
              f"max={pf.autoscaler.max_replicas})")
    if getattr(args, "rebalance", False):
        pf.enable_rebalancer()
        print("rebalancer: live")
    return pf


async def _selftest_procfleet_async(args) -> int:
    loop = asyncio.get_running_loop()
    pf = _build_procfleet(args)
    fleet = pf.router
    server = CompletionServer(fleet, ServerConfig(
        port=0, max_queue=args.max_queue))
    await server.start()
    try:
        status, data = await loop.run_in_executor(
            None, _http, server.port, "POST", "/v1/completions",
            {"prompt": [5, 9, 23, 7], "max_tokens": 4})
        assert status == 200, f"completions {status}: {data!r}"
        obj = json.loads(data)
        choice = obj["choices"][0]
        assert len(choice["token_ids"]) == 4, choice
        # honesty markers (ISSUE 17 satellite): --workers mode with
        # telemetry streaming answers /v1/requests with the full
        # cross-process story
        status, data = await loop.run_in_executor(
            None, _http, server.port, "GET", "/v1/requests?state=recent",
            None)
        assert status == 200, f"/v1/requests {status}"
        listing = json.loads(data)
        assert listing.get("source") == "router+workers", listing
        assert listing.get("complete") is True, listing
        # wire-latency attribution is queryable after one completion
        status, data = await loop.run_in_executor(
            None, _http, server.port, "GET", "/v1/debug/wire", None)
        assert status == 200, f"/v1/debug/wire {status}"
        wire = json.loads(data)
        assert wire["enabled"] and wire["steps"] >= 1, wire
        if args.autoscale:
            assert pf.autoscaler is not None \
                and pf.autoscaler._thread.is_alive(), \
                "autoscaler actuator thread is not live"
        print(f"selftest: OK (port {server.port}, workers={args.workers},"
              f" tokens {choice['token_ids']}, wire steps "
              f"{wire['steps']}"
              + (", autoscaler live" if args.autoscale else "") + ")")
        return 0
    finally:
        await server.shutdown(drain_timeout=2.0)
        pf.shared.close_all()


async def _serve_cli(args) -> int:
    audit = None
    if args.audit_sample:
        from ..observability.audit import AuditConfig

        audit = AuditConfig(enabled=True, sample_every=args.audit_sample)
    fault_plan = None
    if args.fault_plan:
        from .faultinject import FaultPlan

        fault_plan = FaultPlan.from_json(args.fault_plan)
    alert_rules = None
    if args.alert_rules:
        from ..observability.alerts import AlertRuleSet

        alert_rules = AlertRuleSet.from_json(args.alert_rules)
    pf = None
    if args.workers:
        pf = _build_procfleet(args, fault_plan=fault_plan,
                              alert_rules=alert_rules)
        fleet = pf.router
        for i in range(args.workers):
            print(f"worker {i}: pid {pf.worker_pid(i)}")
    else:
        aot = None
        if args.aot_path:
            # ONE load for the whole fleet (ISSUE 15): every replica —
            # and every supervisor rebuild — shares this artifact's
            # compiled executables, so each program compiles once per
            # process
            from .aot import AotArtifact

            aot = AotArtifact.load(args.aot_path)
            print(f"aot: loaded {aot.program_count} program(s) from "
                  f"{args.aot_path} in {aot.load_seconds:.3f}s")
        spec = None
        spec_kwargs = _spec_dict(args)
        if spec_kwargs:
            from .spec import SpecConfig

            spec = SpecConfig(**spec_kwargs)
        fleet = _toy_fleet(dp=args.dp, layers=args.layers,
                           num_blocks=args.blocks,
                           max_queue=args.max_queue,
                           flight_dir=args.flight_dir, audit=audit,
                           unified=args.unified, fault_plan=fault_plan,
                           alert_rules=alert_rules, aot=aot,
                           max_tokens_per_step=args.max_tokens_per_step,
                           spec=spec, burst_steps=args.burst,
                           roles=getattr(args, "roles_list", None))
    supervisor = None
    if args.max_restarts > 0:
        # self-healing by default (ISSUE 12): dead replicas restart
        # under capped exponential backoff, audit-degraded replicas are
        # quarantined and replaced, wedged steps are watchdogged.
        # --max-restarts 0 opts out (legacy exclude-forever semantics).
        from .resilience import FleetSupervisor, SupervisorConfig

        supervisor = FleetSupervisor(fleet, config=SupervisorConfig(
            max_restarts=args.max_restarts,
            watchdog_timeout_s=args.watchdog_timeout))
    server = CompletionServer(fleet, ServerConfig(
        host=args.host, port=args.port,
        max_queue=args.max_queue,
        default_timeout_s=args.timeout))
    pusher = None
    if args.push_gateway:
        from ..observability.push import PushGateway

        pusher = PushGateway(args.push_gateway, registry=fleet.registry,
                             interval_s=args.push_interval).start()
    await server.start()
    if supervisor is not None:
        supervisor.start()  # closed by fleet.stop() during shutdown
    loop = asyncio.get_running_loop()
    try:
        import signal

        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, server.request_shutdown)
    except (NotImplementedError, RuntimeError):
        pass  # swallow-ok: platform without signal-handler support (Windows/non-main loop); Ctrl-C still raises KeyboardInterrupt
    print(f"serving on http://{server.cfg.host}:{server.port} "
          f"dp={fleet.dp} mp={server.engine.mp} "
          "(POST /v1/completions; GET /healthz /readyz /metrics "
          "/v1/requests /v1/debug/compiles /v1/debug/profile "
          "/v1/debug/audit /v1/debug/alerts /v1/debug/history "
          "/v1/debug/wire)")
    try:
        await server.serve_forever()
    finally:
        if pusher is not None:
            pusher.close()
        if pf is not None:
            pf.shared.close_all()  # reap the worker processes
    return 0


def main(argv=None) -> int:
    import argparse
    import os

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # the TPU plugin's sitecustomize may pin the platform at startup;
        # mirror tests/conftest.py and override after import
        import jax

        jax.config.update("jax_platforms", "cpu")

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.server",
        description="HTTP/SSE serving frontend (toy model demo + selftest)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--blocks", type=int, default=256)
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--timeout", type=float, default=None,
                   help="default per-request deadline (seconds)")
    p.add_argument("--mp", type=int, default=1,
                   help="tensor-parallel degree: init a mesh with this "
                        "mp axis before building the engines (needs that "
                        "many devices; on CPU set XLA_FLAGS="
                        "--xla_force_host_platform_device_count=N)")
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel fleet degree: N engine replicas "
                        "behind the prefix-affinity router (composes "
                        "with --mp: '--dp 2 --mp 2' is a dp×mp fleet of "
                        "2 replicas, each mesh-spanning 2 shards)")
    p.add_argument("--push-gateway", default=None, metavar="URL",
                   help="POST Prometheus text exposition of the fleet "
                        "registry to this URL on an interval (daemon "
                        "thread, capped exponential backoff on failure)")
    p.add_argument("--push-interval", type=float, default=15.0,
                   help="push-gateway export interval in seconds")
    p.add_argument("--fault-plan", default=None, metavar="FILE",
                   help="JSON fault plan for deterministic chaos runs "
                        "(serving/faultinject.py): named injection "
                        "points scheduled by (replica, engine step) — "
                        "engine_step_raise, pool_exhaust, slow_step, "
                        "kernel_corrupt; each fires exactly once and is "
                        "recorded as lifecycle/flight events")
    p.add_argument("--max-restarts", type=int, default=5, metavar="K",
                   help="self-healing supervisor: restarts allowed per "
                        "replica inside the crash-loop window before "
                        "permanent exclusion (capped exponential "
                        "backoff between attempts; audit-degraded "
                        "replicas are quarantined and replaced).  0 "
                        "disables supervision — a dead replica stays "
                        "excluded until an operator acts")
    p.add_argument("--watchdog-timeout", type=float, default=60.0,
                   metavar="S",
                   help="per-replica step watchdog: a step exceeding "
                        "this marks the replica unhealthy (excluded "
                        "from routing) and escalates to a restart if "
                        "the stall persists; only with supervision on")
    p.add_argument("--alert-rules", default=None, metavar="FILE",
                   help="JSON alert rule set evaluated over the metrics "
                        "history (observability/alerts.py): threshold / "
                        "rate / SLO burn-rate rules with step-indexed "
                        "windows; omitted = the default serving rule "
                        "set (pool exhaustion, goodput burn, compile "
                        "storms, restart/quarantine churn, ...)")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="write flight-recorder post-mortem bundles "
                        "(engine death, preemption storms, 429 bursts, "
                        "drain overruns, numerics divergences) into "
                        "this directory")
    p.add_argument("--audit-sample", type=int, default=None, metavar="N",
                   help="enable online numerics auditing with a shadow-"
                        "oracle re-execution every Nth engine step "
                        "(NaN/Inf sentinel + logit telemetry on every "
                        "step; .npz repros land in --flight-dir); off "
                        "by default")
    p.add_argument("--max-tokens-per-step", type=int, default=None,
                   metavar="T",
                   help="unified ragged packing: per-step token budget "
                        "shared by decode rows, prefill chunks and "
                        "(with --spec-decode) draft verification; "
                        "required by --spec-decode")
    p.add_argument("--spec-decode", action="store_true",
                   help="speculative decoding (ISSUE 18): a host-side "
                        "n-gram proposer drafts tokens per decode-"
                        "resident request and the engine verifies them "
                        "as short chunks packed into the unified ragged "
                        "step — greedy outputs are token-identical with "
                        "strictly fewer engine steps.  Requires "
                        "--unified and --max-tokens-per-step; composes "
                        "with --workers (the spec config rides the wire "
                        "handshake as deployment identity)")
    p.add_argument("--burst", type=int, default=0, metavar="N",
                   help="device-resident decode bursts (ISSUE 19): when "
                        "the running set is a decode-only resident "
                        "cohort, ONE compiled program runs up to N "
                        "decode steps on-device (in-trace KV append + "
                        "sampling + EOS masking) and ships the [B, N] "
                        "token buffer back in one host round-trip; "
                        "token streams are bit-identical to per-step "
                        "decode.  0 disables; mutually inert with "
                        "--spec-decode (spec drafting wins).  Composes "
                        "with --workers (forwarded through the worker "
                        "spec) and --aot-save (the burst bucket lattice "
                        "is enumerated into the artifact)")
    p.add_argument("--spec-k", type=int, default=4, metavar="K",
                   help="--spec-decode: max draft tokens proposed per "
                        "request per step (default 4)")
    p.add_argument("--unified", action="store_true",
                   help="serve through the unified ragged step program "
                        "(one packed prefill+decode launch per engine "
                        "step, collapsed bucket set; at mp>1 the Pallas "
                        "fast path runs mesh-spanning via shard_map)")
    p.add_argument("--aot-save", default=None, metavar="DIR",
                   help="enumerate + jax.export the full bucketed "
                        "program set of the configured engine "
                        "(--layers/--blocks/--unified/--mp) into an AOT "
                        "artifact directory (manifest + StableHLO), "
                        "then exit — the compile-once build step of "
                        "ISSUE 15")
    p.add_argument("--aot-path", default=None, metavar="DIR",
                   help="serve from a saved AOT artifact: every replica "
                        "(and every supervisor rebuild) shares one "
                        "loaded program set and the engines trace "
                        "NOTHING (manifest mismatches fail loudly at "
                        "boot; composes with --selftest, which then "
                        "asserts zero traces)")
    p.add_argument("--aot-max-seq", type=int, default=128, metavar="T",
                   help="--aot-save: bound the saved bucket universe to "
                        "sequences of at most T tokens (default 128; "
                        "the pool capacity caps it either way — a "
                        "serving step past the bound fails loudly "
                        "instead of retracing)")
    p.add_argument("--workers", type=int, default=0, metavar="N",
                   help="cross-process fleet (ISSUE 16): N worker "
                        "PROCESSES (python -m paddle_tpu.serving.worker)"
                        " behind the same prefix-affinity router and "
                        "self-healing supervisor, speaking the length-"
                        "prefixed JSON wire protocol over localhost — "
                        "kill -9 a worker and the fleet reroutes, "
                        "respawns it off the shared --aot-path artifact "
                        "and loses nothing.  0 = in-process replicas "
                        "(--dp)")
    p.add_argument("--roles", default=None, metavar="SPEC",
                   help="prefill/decode disaggregation (ISSUE 20): "
                        "per-replica role counts, e.g. "
                        "'prefill:1,decode:2'.  Counts must sum to the "
                        "fleet size (--dp or --workers).  Admissions "
                        "route to prefill specialists; each request "
                        "migrates (with its computed prompt KV) to a "
                        "decode specialist at its first-token boundary")
    p.add_argument("--autoscale", action="store_true",
                   help="with --workers: enable the SLO-driven "
                        "autoscaler (alert firings → bounded worker "
                        "scale actions).  Bounds via --autoscale-min / "
                        "--autoscale-max")
    p.add_argument("--autoscale-min", type=int, default=1, metavar="N",
                   help="autoscaler floor: never drain below N live "
                        "workers (default 1)")
    p.add_argument("--autoscale-max", type=int, default=0, metavar="N",
                   help="autoscaler ceiling: never provision above N "
                        "workers (0 = the fleet's --workers count; the "
                        "index space is fixed at boot)")
    p.add_argument("--rebalance", action="store_true",
                   help="with --workers: enable the prefix-cache "
                        "rebalancer (hot-prefix replication across "
                        "replicas)")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="JAX persistent compilation cache directory for "
                        "--workers processes: N sibling workers compile "
                        "each (AOT or traced) program once machine-wide "
                        "— every later worker boot hits the cache "
                        "instead of recompiling")
    p.add_argument("--aot-warm", action="store_true",
                   help="with --aot-save: execute every exported "
                        "program once right after saving (device-warms "
                        "the artifact and fills --compile-cache); with "
                        "--workers: each worker warm-executes the "
                        "loaded artifact at boot so the FIRST request "
                        "wave pays zero lazy compiles (wall seconds "
                        "recorded as serving_aot_warm_seconds)")
    p.add_argument("--selftest", action="store_true",
                   help="boot on an ephemeral port, serve one completion "
                        "against the toy fleet through the router path, "
                        "exit 0 on success")
    args = p.parse_args(argv)
    if args.dp < 1:
        p.error(f"--dp must be >= 1, got {args.dp}")
    if args.workers < 0:
        p.error(f"--workers must be >= 0, got {args.workers}")
    if args.workers:
        if args.dp > 1:
            p.error("--workers and --dp are the two fleet modes — pick "
                    "one (cross-process: --workers N; in-process: "
                    "--dp N)")
        if args.autoscale_min < 1:
            p.error(f"--autoscale-min must be >= 1, got "
                    f"{args.autoscale_min}")
        if args.autoscale_max < 0:
            p.error(f"--autoscale-max must be >= 0, got "
                    f"{args.autoscale_max}")
        if args.autoscale_max and args.autoscale_max < args.autoscale_min:
            p.error("--autoscale-max must be >= --autoscale-min")
    elif args.autoscale or args.rebalance:
        p.error("--autoscale/--rebalance act on the cross-process "
                "worker pool; they require --workers N")
    args.roles_list = None
    if args.roles:
        from .fleet import parse_roles

        try:
            args.roles_list = parse_roles(args.roles)
        except ValueError as e:
            p.error(f"--roles: {e}")
        size = args.workers if args.workers else args.dp
        if len(args.roles_list) != size:
            p.error(f"--roles names {len(args.roles_list)} replica(s) "
                    f"but the fleet has {size} (--workers/--dp)")
    if args.audit_sample is not None and args.audit_sample < 1:
        p.error(f"--audit-sample must be >= 1, got {args.audit_sample}")
    if args.max_restarts < 0:
        p.error(f"--max-restarts must be >= 0, got {args.max_restarts}")
    if args.spec_decode:
        if not args.unified:
            p.error("--spec-decode verifies drafts inside the unified "
                    "ragged step program; it requires --unified")
        if args.max_tokens_per_step is None:
            p.error("--spec-decode needs --max-tokens-per-step: drafts "
                    "compete for the step's leftover token budget")
        if args.spec_k < 0:
            p.error(f"--spec-k must be >= 0, got {args.spec_k}")
    if args.burst < 0:
        p.error(f"--burst must be >= 0, got {args.burst}")
    if args.mp > 1 and not args.workers:
        # tensor-parallel serving (ISSUE 5): build the mesh BEFORE any
        # engine (selftest included — the probe must exercise the real
        # degree) so parameters and KV pools land sharded.  On CPU this
        # needs XLA_FLAGS=--xla_force_host_platform_device_count=N.
        # With --workers the mesh lives in each WORKER process (ISSUE
        # 18): the router forwards mp through the worker spec and never
        # builds a mesh of its own.
        from ..distributed import topology

        topology.init_mesh(mp=args.mp)
    if args.aot_save:
        if args.aot_max_seq < 1:
            p.error(f"--aot-max-seq must be >= 1, got {args.aot_max_seq}")
        from .aot import AotArtifact

        eng = _toy_engine(layers=args.layers, num_blocks=args.blocks,
                          unified=args.unified, burst_steps=args.burst)
        art = AotArtifact.save(eng, args.aot_save,
                               max_seq_len=args.aot_max_seq)
        print("aot-save: " + json.dumps(art.describe(), indent=1))
        if args.aot_warm:
            # pre-compile every exported program at SAVE time (ISSUE 16
            # satellite): with --compile-cache set via JAX config /
            # worker flag, this fills the machine-wide persistent cache
            # so every later worker boot compiles nothing
            wall = art.warm()
            print(f"aot-warm: executed {art.program_count} program(s) "
                  f"in {wall:.3f}s")
        return 0
    if args.selftest:
        if args.workers:
            # ISSUE 17 satellite: the selftest now covers the cross-
            # process fleet too — boots N workers, serves one completion
            # over HTTP, and (with --autoscale) asserts the autoscaler
            # actuator thread is live
            return asyncio.run(_selftest_procfleet_async(args))
        return asyncio.run(_selftest_async(
            dp=args.dp, audit_sample=args.audit_sample or 1,
            unified=args.unified, aot_path=args.aot_path,
            layers=args.layers, blocks=args.blocks))
    return asyncio.run(_serve_cli(args))


if __name__ == "__main__":
    import sys

    sys.exit(main())
