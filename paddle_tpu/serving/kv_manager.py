"""Paged KV-cache manager for the serving engine.

Owns the *bookkeeping* of the shared block pool — block tables, sequence
lengths, reference counts — while the pool tensors themselves (one
``[num_blocks, block_size, Hkv, D]`` pair per layer) live on the engine as
:class:`~paddle_tpu.ops.paged_attention.PagedCache` state threaded through
the jitted step.  This is the Ragged-Paged-Attention shape (PAPERS.md): a
ragged batch of sequences at different lengths indexes one block pool
through per-sequence tables, so admission/eviction never reshapes anything
the compiler sees.

Graceful degradation contract: allocation never partially succeeds, and
exhaustion is a *scheduling event*, not an error — the engine preempts the
lowest-priority running request (freeing its blocks for recompute later)
instead of failing anyone.  Block 0 is the reserved null page that padding
rows of a bucketed batch write into.

Multi-chip (ISSUE 5): this manager is **per-process host state and stays
replicated** when the engine serves tensor-parallel over the ``mp`` mesh
axis.  The pool tensors shard along the head dim on device, but a block
index means the same page on every shard, so the same table/refcount/
hash bookkeeping routes all N shards — capacity, admission, preemption
and prefix-cache math are all mp-invariant (per-shard block bytes =
``block_size * Hkv/mp * D * itemsize``).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..ops.paged_attention import (  # noqa: F401  (PoolExhausted re-export)
    BlockPool,
    PoolExhausted,
)


class KVCacheManager(BlockPool):
    """Refcounted block-pool bookkeeping (no device tensors).

    The free-list / refcount / fork core is
    :class:`~paddle_tpu.ops.paged_attention.BlockPool` — the same
    implementation :class:`~paddle_tpu.ops.paged_attention.BlockKVCache`
    uses, so the invariants cannot drift.  Here one pool is shared across
    *all* layers: every layer's tensors use the same block index for a
    given (sequence, position), which is what lets one routing array drive
    the whole decoder stack.  This subclass adds the serving-loop surface:
    decode-slot reservation (``append_slot``/``commit``) and gauges.

    With ``enable_prefix_cache=True`` (the serving default) the base
    pool's automatic prefix caching is active: full prompt blocks are
    content-hashed after prefill, refcount-0 cached blocks park in a
    bounded reuse LRU instead of being clobbered, and admission forks the
    longest cached block-prefix of a new prompt for free
    (``fork_prefix``).  Capacity planning must then use
    :attr:`num_available` (free + evictable-cached), not ``num_free``.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 enable_prefix_cache: bool = True):
        super().__init__(num_blocks, block_size,
                         enable_prefix_cache=enable_prefix_cache)
        # fault injection (ISSUE 12): while True, the pool reports zero
        # available capacity — the `pool_exhaust` injection point.  The
        # engine arms it for exactly ONE scheduler-planning pass, so the
        # refusal surfaces as a preemption/deferral scheduling event
        # (token-identical recompute), never as a failed launch.
        self.refuse_allocations = False

    # --- capacity ----------------------------------------------------------
    @property
    def num_available(self) -> int:
        if self.refuse_allocations:
            return 0
        return super().num_available

    def occupancy(self) -> float:
        """Fraction of the usable pool currently held by sequences.
        Reuse-LRU blocks (cached content, no owner) count as free capacity
        — they are evictable on demand."""
        usable = self.num_blocks - 1
        return (usable - self.num_available) / usable if usable else 0.0

    def burst_capacity(self, rows: int) -> int:
        """Largest per-row decode-burst length N the pool can promise
        ``rows`` concurrent decode rows (ISSUE 19).  Called AFTER the
        scheduler reserved each row's next-token slot (``append_slot``),
        so a row holding blocks for ``p+1`` tokens needs at most
        ``ceil((N-1)/block_size)`` additional blocks for N total burst
        tokens, even when every row sits on the worst-case block
        boundary.  The closed form below is exactly that bound inverted:
        giving each row ``num_available // rows`` whole extra blocks
        supports ``(num_available // rows) * block_size + 1`` tokens.

        ONE accessor shared by the scheduler's plan and the engine's
        launch clamp — the PR 1 promised-blocks lesson: two copies of
        headroom math WILL disagree one preemption later."""
        if rows <= 0:
            return 0
        return (self.num_available // rows) * self.block_size + 1

    # --- allocation --------------------------------------------------------
    def append_slot(self, seq_id) -> Optional[Tuple[int, int]]:
        """(block, offset) slot for the sequence's NEXT token, allocating a
        fresh block on a boundary.  ``None`` on exhaustion — the caller
        preempts and retries.  Does not advance the length: ``commit``
        does, after the model step actually wrote the slot."""
        if not self.allocate(seq_id, 1, cause="decode_slot"):
            return None
        pos = self._lens.get(seq_id, 0)
        table = self._tables[seq_id]
        return table[pos // self.block_size], pos % self.block_size

    def commit(self, seq_id, num_tokens: int = 1):
        self._lens[seq_id] = self._lens.get(seq_id, 0) + num_tokens

    def truncate(self, seq_id, new_len: int) -> int:
        """Roll the sequence back to ``new_len`` committed tokens,
        returning surplus tail blocks to the pool (ISSUE 18: spec-decode
        rejection rollback — the preemption-recompute slot discipline
        aimed at a length instead of zero).  Tail blocks whose refcount
        hits 0 go straight to the free list: their content is a
        rejected-draft suffix, not cacheable prefix material (spec-draft
        blocks are freshly allocated and never hashed; a still-shared
        block just drops this owner's reference).  Stale K/V left in the
        KEPT tail block past ``new_len`` is dead weight the per-row
        ``lens`` routing never attends to, and the next decode/verify
        slot overwrites it.  Returns the number of blocks freed."""
        cur = self._lens.get(seq_id, 0)
        if new_len > cur:
            raise ValueError(
                f"truncate({seq_id!r}, {new_len}) extends past the "
                f"committed length {cur}")
        table = self._tables.get(seq_id)
        freed = 0
        if table is not None:
            keep = self.blocks_for(new_len)
            while len(table) > keep:
                b = table.pop()
                n = self._ref.get(b, 1) - 1
                if n > 0:
                    self._ref[b] = n
                    continue
                self._ref.pop(b, None)
                self._drop_hash(b)  # no-op for never-hashed draft blocks
                self._free.append(b)
                freed += 1
        self._lens[seq_id] = new_len
        return freed

    # --- views -------------------------------------------------------------
    def table(self, seq_id) -> List[int]:
        return self._tables.get(seq_id, [])

    def seq_len(self, seq_id) -> int:
        return self._lens.get(seq_id, 0)

    def has(self, seq_id) -> bool:
        return seq_id in self._tables

    def num_owned_blocks(self, seq_id) -> int:
        return len(self._tables.get(seq_id, ()))
