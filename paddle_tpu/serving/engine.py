"""EngineCore: request-level continuous-batching serving engine.

The piece VERDICT N31 called missing: above ``ops/paged_attention.py``
(block pool) and ``inference.LLMPredictor`` (single-call API) sits an
engine that owns a request queue, admission control, preemption, and a
**fixed-shape** jitted step program — the Ragged-Paged-Attention serving
shape (PAPERS.md) with MPK's compile-once discipline:

* All sequences share ONE paged KV pool per layer
  (``[num_blocks, block_size, Hkv, D]``); per-step routing arrays (block
  tables, lengths, slot indices) are DATA, so joining/leaving requests
  never change a tensor shape.
* Batch size and block-table width are padded to power-of-two buckets
  (``scheduler.bucket_size``), so the jitted decode step compiles at most
  once per (batch-bucket, width-bucket) pair and the jitted prefill at
  most once per prompt-length bucket — never per request.  ``
  decode_trace_count``/``prefill_trace_count`` count actual retraces
  (incremented inside the traced function, so they move only when JAX
  really traces) and are asserted against the bucket sets in tests.
* Pool exhaustion preempts (lowest priority, newest arrival first) and
  recomputes instead of failing the request: the victim's blocks are
  freed, it re-enqueues at the front of the waiting queue, and its next
  prefill runs over ``prompt + output_tokens`` — token-identical
  continuation under greedy decoding (tested).
* Padding rows of a bucketed batch write into block 0, the reserved null
  page, and carry ``seq_len = 1`` so every attention path stays finite.

The model runs *functionally* inside the jitted step: parameters and KV
pools enter as jit arguments (swapped into the eager module for the trace,
restored after), updated pools return as outputs.  On TPU the pool
arguments are donated, so the decode step updates KV in place in HBM.

**Tensor-parallel serving (ISSUE 5):** when the global mesh
(``distributed.topology``) carries an ``mp`` axis > 1, the engine runs the
same loop mesh-spanning: parameters are placed per their
``PartitionSpec`` annotations (the Megatron column→row pairing of
``parallel/mp_layers.py`` — attention heads and MLP width sharded over
``mp``), the KV pools shard along the **head** dim
(``ops.paged_attention.shard_kv_pool``), and the jitted prefill/decode
programs carry explicit in/out shardings: routing arrays (block tables,
seq lens, slot indices, token ids) enter **replicated**, pools and
activations sharded, and GSPMD inserts the collectives.  Everything
host-side — BlockPool bookkeeping, scheduler state, admission math,
prefix-cache hashes — is untouched: one scheduler decision drives N
shards, and only the per-shard pool byte footprint divides by mp.  The
bucket sets (and therefore the jit trace count) are mp-invariant.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..distributed import topology
from ..observability import lifecycle as _lc
from ..observability.audit import AuditConfig, NumericsAuditor, logit_stats
from ..observability.cachestat import CacheStatTracker
from ..observability.lifecycle import LifecycleTracker
from ..observability.stepprof import StepProfiler
from ..ops.paged_attention import (
    KV_POOL_SPEC,
    PagedCache,
    PoolExhausted,
    shard_kv_pool,
)
from ..ops.decode_burst import run_burst
from ..ops.sampling import sample_tokens
from .burst import burst_eligible, clamp_burst
from .burst import register_metrics as _register_burst_metrics
from .kv_manager import KVCacheManager
from .metrics import ServingMetrics, StepTimer
from .request import FinishReason, Request, RequestState, SamplingParams
from .sampling import SamplingPack
from .sampling import register_metrics as _register_sampling_metrics
from .scheduler import (
    ContinuousBatchingScheduler,
    SchedulerConfig,
    bucket_size,
)


# per-step cap on individual prefix_cache_eviction lifecycle events
# (ISSUE 13): counters/histograms/cause series stay exact per eviction,
# but a pool-thrash step (one huge prefill clobbering hundreds of parked
# blocks) must not flood the bounded flight-recorder ring and displace
# the request-lifecycle events a post-mortem needs — evictions past the
# cap collapse into one prefix_cache_eviction_burst summary event.
_EVICT_EVENTS_PER_STEP = 8


@dataclass
class EngineConfig:
    """Engine-level deployment knobs (the config plumb-through of ISSUE 5).

    ``EngineCore(model, config=EngineConfig(...))`` is the one-object
    form; the legacy keyword arguments remain and are folded into one of
    these when no config is passed.
    """

    num_blocks: int = 256
    block_size: int = 16
    dtype: object = None              # pool dtype; None = jnp.float32
    prefix_cache: bool = True
    profile_ops: bool = False
    scheduler: Optional[SchedulerConfig] = None
    # Pallas paged-decode routing (ROADMAP serving follow-up (b)): None =
    # auto dispatch (kernel when TPU-tileable), True = force the kernel
    # (interpret mode off-TPU — the smoke-test path), False = force the
    # XLA gather path.  The on-chip A/B is now a config flip.
    use_pallas_paged: Optional[bool] = None
    # Expected tensor-parallel degree.  None = use whatever ``mp`` axis
    # the global mesh has (1 when no mesh).  An explicit value that does
    # not match the live mesh raises at engine build — a misconfigured
    # deployment fails loudly instead of silently serving single-chip.
    mp: Optional[int] = None
    # Request-lifecycle tracing (ISSUE 8): per-request bounded event
    # timelines (admission, routing handoff, prefill chunks, sampled
    # decode ITL, preemption, finish), queryable via the serving debug
    # endpoints and exportable as per-request chrome traces.  Off =
    # zero per-event work on the hot path.
    lifecycle_events: bool = True
    # share a tracker across engines (the fleet router rebinds replicas
    # onto ONE tracker so router + engine events land in one timeline);
    # None = the engine builds its own on its metrics registry
    lifecycle: Optional[LifecycleTracker] = None
    # record every Nth decode-token EVENT on the timeline (aggregates
    # and the ITL histograms see every token regardless; sampled-out
    # tokens also skip the flight-ring fan-out, so this knob bounds the
    # per-token cost on the decode hot path); 0 = none
    decode_event_sample: int = 8
    # Step-level performance introspection (ISSUE 9): per-program/bucket
    # utilization + padding-waste metrics, compile-time attribution, and
    # on-demand capture windows (StepProfiler).  Default on — O(1)
    # aggregates per program launch, spans only while a capture window
    # is armed; False keeps /metrics free of every serving_step_* /
    # serving_compile_* / serving_padding_* series.
    step_profile: bool = True
    # Online numerics auditing (ISSUE 10): NaN/Inf sentinel + logit-
    # stats telemetry on every step-program launch, and shadow-oracle
    # differential re-execution of sampled decode steps through the XLA
    # gather reference (single-shard replicated re-run under mp>1),
    # with size-capped .npz repro bundles on divergence.  None/default
    # = disabled: zero serving_audit_*/serving_logit_* series on
    # /metrics and no host-side audit work (the in-trace logit stats
    # are computed unconditionally, so audit on vs off is the SAME
    # compiled program — trace counts provably unchanged).
    audit: Optional[AuditConfig] = None
    # KV-cache & memory observability (ISSUE 13): per-step pool-timeline
    # sampling (free/reuse/allocated block counts with the exact
    # free+reuse+allocated == num_blocks invariant asserted every
    # sample), prefix-heat analytics over the chain hashes, reuse-LRU
    # hit-depth / park-lifetime telemetry, and per-request cache
    # attribution — all host-side (CacheStatTracker), so on vs off is
    # provably the same compiled program.  Served at /v1/debug/cache.
    cache_stats: bool = True
    # Metrics history + alerting (ISSUE 14): each engine step ticks the
    # fleet's HistoryStore sampler (bounded per-series rings over the
    # shared registry; the AlertEngine evaluates its threshold / rate /
    # SLO burn-rate rules after every sample).  Host-side only, like
    # cache_stats — on vs off is provably the same compiled program.
    # The store itself is owned by the FleetRouter (one fleet-wide
    # history at dp>1); this gate controls whether THIS engine ticks it.
    history: bool = True
    # Unified ragged step program (ISSUE 11): every engine step runs ONE
    # packed ragged launch (ops/ragged_paged.py) serving mixed prefill
    # chunks and decode rows together, instead of picking from the three
    # legacy program families (one-shot prefill / chunked prefill /
    # decode).  The bucket set collapses to (total-token, table-width)
    # pairs — strictly fewer traces — and at mp>1 the Pallas fast path
    # runs mesh-spanning through shard_map instead of being auto-pinned
    # off.  Default off this PR; token-identical to the legacy dispatch
    # under greedy decoding (tested).
    unified_step: bool = False
    # AOT serving artifacts (ISSUE 15): serve from a pre-lowered
    # program set instead of tracing at runtime.  ``aot_path`` loads a
    # saved :class:`~paddle_tpu.serving.aot.AotArtifact` directory at
    # engine build; ``aot`` binds an already-loaded artifact OBJECT and
    # wins over the path — a dp fleet (and the supervisor's replica
    # rebuilds) must share ONE loaded artifact so each program compiles
    # once fleet-wide.  Any manifest mismatch (mp degree, bucket set,
    # model hash, jax version, ...) fails loudly at build, and the
    # in-trace retrace counters provably stay 0 while serving (a bucket
    # outside the saved universe raises AotBucketMissing instead of
    # silently retracing).
    aot_path: Optional[str] = None
    aot: Optional[object] = None
    # Speculative decoding (ISSUE 18): a host-side n-gram proposer
    # drafts k tokens per decode-resident request and the engine packs
    # them as short verify chunks into the SAME unified ragged bucket
    # lattice (no new program family, no new bucket axes) — accepted
    # runs deliver multiple tokens per engine step.  Requires
    # ``unified_step=True`` and a ``max_tokens_per_step`` budget (draft
    # tokens compete for the step's leftover budget).  None = off;
    # greedy spec-decode is token-identical to baseline (bench-gated).
    spec: Optional[object] = None  # serving.spec.SpecConfig
    # Device-resident decode bursts (ISSUE 19): when the running set is
    # a decode-only resident cohort (no pending admissions, prefill
    # continuations, or spec drafts), launch ONE compiled program that
    # runs up to this many decode steps on-device (in-trace KV slot
    # append, per-row position advance, fused sampling, per-row EOS
    # masking) — only the ``[B, N]`` token buffer crosses back to the
    # host.  The launch clamp (serving/burst.py) shrinks N below this
    # cap per launch; 0/1 = off (per-step decode).  Burst-on is
    # token-identical to burst-off for greedy AND sampled rows (the
    # draw keys advance in-trace along the same output positions).
    burst_steps: int = 0
    # Prefill/decode disaggregation (ISSUE 20): the replica's ROLE in a
    # role-aware fleet.  Pure routing policy — any engine can execute
    # anything (the unified fallback depends on that), so role is NOT
    # part of the fleet's homogeneity gates.  ``prefill`` specialists
    # take admissions and compute prompt KV; at the first-token boundary
    # the router migrates the request plus its computed KV blocks to a
    # ``decode`` specialist (serving/handoff.py); ``unified`` replicas
    # do both (the default, and the single-replica fallback).
    role: str = "unified"


class EngineCore:
    """Continuous-batching engine over one causal-LM model.

    High-level loop: ``add_request`` enqueues; each ``step()`` asks the
    scheduler for a plan (decode-slot reservation with preemption, then
    admission), runs at most one bucketed prefill program and one bucketed
    decode program, samples on the host with each request's own RNG
    stream, and retires finished requests.  ``stream()`` exposes a
    per-request generator that drives ``step()`` on demand.

    Construction: pass ``config=EngineConfig(...)`` (the one-object form
    — it then WINS over the legacy keyword arguments) or the individual
    keywords, which are folded into an :class:`EngineConfig`
    (``self.engine_config``).  ``self.mp`` is the resolved
    tensor-parallel degree (1 single-chip).
    """

    def __init__(self, model, num_blocks: int = 256, block_size: int = 16,
                 dtype=jnp.float32, scheduler_config: Optional[SchedulerConfig] = None,
                 profile_ops: bool = False, registry=None,
                 prefix_cache: bool = True,
                 config: Optional[EngineConfig] = None,
                 use_pallas_paged: Optional[bool] = None,
                 metrics_labels: Optional[Dict[str, str]] = None):
        if config is None:
            config = EngineConfig(
                num_blocks=num_blocks, block_size=block_size, dtype=dtype,
                prefix_cache=prefix_cache, profile_ops=profile_ops,
                scheduler=scheduler_config, use_pallas_paged=use_pallas_paged)
        self.engine_config = config
        if config.role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"EngineConfig.role must be 'unified', 'prefill' or "
                f"'decode'; got {config.role!r}")
        num_blocks, block_size = config.num_blocks, config.block_size
        dtype = config.dtype if config.dtype is not None else jnp.float32
        cfg = model.config
        self.model = model
        self.kv = KVCacheManager(num_blocks, block_size,
                                 enable_prefix_cache=config.prefix_cache)
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.scheduler = ContinuousBatchingScheduler(
            config.scheduler or SchedulerConfig(), self.kv)
        # registry=None keeps counts per-engine; pass
        # observability.get_registry() to publish serving series on the
        # process-wide Prometheus page next to the jit compile counters.
        # metrics_labels (e.g. {"replica": "0"}) lets N fleet replicas
        # share ONE registry with per-replica-labeled serving series.
        self.metrics = ServingMetrics(registry=registry,
                                      labels=metrics_labels)
        self.tracer = self.metrics.tracer
        # in-trace sampling counters (ISSUE 18): every emitted token now
        # comes off the device already sampled; these attribute them to
        # the greedy vs sampled row kinds
        self._sampling_counters = _register_sampling_metrics(
            self.metrics.registry)
        # --- step-level introspection (ISSUE 9) ----------------------------
        # bucket-utilization/padding accounting + compile attribution +
        # capture windows, on the same registry (replica-labeled under a
        # fleet); disabled = the registry never sees a serving_step_*
        # series and every hook below is a cheap early-return
        self.stepprof = StepProfiler(registry=self.metrics.registry,
                                     labels=metrics_labels,
                                     enabled=config.step_profile)
        self.metrics.attach_step_profiler(self.stepprof)
        # --- KV-cache & memory observability (ISSUE 13) --------------------
        # pool timeline + prefix heat + reuse-LRU telemetry + per-request
        # attribution; the pool's event-driven hooks below feed it AND
        # the legacy prefix_cache_evictions counter / lifecycle event
        # (which are no longer lag-batched per step)
        self.cachestat = CacheStatTracker(self.kv,
                                          registry=self.metrics.registry,
                                          labels=metrics_labels,
                                          enabled=config.cache_stats)
        self._evict_events_step = 0  # per-step lifecycle-event budget
        self.kv.on_evict = self._on_pool_evict
        self.kv.on_revive = self._on_pool_revive
        # --- online numerics auditing (ISSUE 10) ---------------------------
        # NaN/Inf sentinel + logit telemetry on every launch, shadow-
        # oracle re-execution of sampled decode steps; the fleet router
        # binds it to the flight recorder keyed by replica index
        self.audit = NumericsAuditor(self, config=config.audit,
                                     registry=self.metrics.registry,
                                     labels=metrics_labels)
        # --- request-lifecycle tracing (ISSUE 8) ---------------------------
        # the fleet router rebinds all replicas onto ONE tracker via
        # set_lifecycle() so router + engine events share a timeline
        self._replica_label = (metrics_labels or {}).get("replica", "0")
        self._lifecycle_on = config.lifecycle_events
        if config.lifecycle is not None:
            self.lifecycle = config.lifecycle
        else:
            self.lifecycle = LifecycleTracker(
                registry=self.metrics.registry,
                enabled=config.lifecycle_events,
                decode_sample=config.decode_event_sample)
        self.requests: Dict[object, Request] = {}
        self._pool_dtype = jnp.dtype(dtype)
        # deterministic fault injection (ISSUE 12): the fleet router
        # binds a per-replica FaultInjector; step_seq is the injector's
        # deterministic clock (counts step() invocations, no wall time)
        self.step_seq = 0
        self._fault = None
        # metrics history (ISSUE 14): the fleet router binds ONE
        # HistoryStore across all replicas via set_history; each step
        # ticks it (gated by EngineConfig.history)
        self.history = None
        # --- tensor-parallel resolution (ISSUE 5) ---------------------------
        mesh = topology.get_mesh()
        from ..parallel.utils import axis_size

        self.mp = axis_size("mp")
        if config.mp is not None and config.mp != self.mp:
            raise ValueError(
                f"EngineConfig.mp={config.mp} but the global mesh has "
                f"mp={self.mp}; call distributed.topology.init_mesh(mp=...) "
                "before building the engine")
        self._unified = bool(config.unified_step)
        self._use_pallas = config.use_pallas_paged
        # the unified ragged program keeps its own routing: its Pallas
        # kernel is expressed through shard_map over the mp axis, so it
        # is NEVER subject to the legacy single-shard pin below
        self._use_pallas_ragged = config.use_pallas_paged
        if self.mp > 1:
            if cfg.num_key_value_heads % self.mp or \
                    cfg.num_attention_heads % self.mp:
                raise ValueError(
                    f"mp={self.mp} must divide num_key_value_heads="
                    f"{cfg.num_key_value_heads} and num_attention_heads="
                    f"{cfg.num_attention_heads} (the KV pools shard along "
                    "the head dim)")
            if self._use_pallas and not self._unified:
                # the ONLY remaining mp>1 kernel restriction (ISSUE 11
                # lifted the silent auto-pin): forcing the LEGACY
                # single-shard decode kernel into a mesh program fails
                # loudly instead of being quietly overridden
                raise ValueError(
                    "use_pallas_paged=True at mp>1 requires "
                    "unified_step=True: the legacy decode kernel is "
                    "single-shard — the unified ragged program runs the "
                    "kernel mesh-spanning via shard_map, or drop the "
                    "force to use the XLA gather path")
            self._use_pallas = False  # legacy three-family programs pin
            # the XLA path inside the mesh program (single-shard kernel);
            # self._use_pallas_ragged keeps the configured routing — the
            # shard_map ragged kernel IS the mp fast path
            from ..parallel.utils import apply_param_shardings

            # place every annotated parameter (column/row/vocab-parallel
            # specs from parallel/mp_layers.py) onto the mesh shard-wise
            apply_param_shardings(model, mesh)
        self.metrics.set_mp_shards(self.mp)
        shape = (num_blocks, block_size, cfg.num_key_value_heads, cfg.head_dim)
        self._k_pools = tuple(shard_kv_pool(jnp.zeros(shape, dtype))
                              for _ in range(cfg.num_hidden_layers))
        self._v_pools = tuple(shard_kv_pool(jnp.zeros(shape, dtype))
                              for _ in range(cfg.num_hidden_layers))
        self._params = list(model.parameters())
        # retrace counters: += 1 runs only while JAX traces the function,
        # so these count COMPILATIONS, not calls (the N31 acceptance hook)
        self.decode_trace_count = 0
        self.prefill_trace_count = 0
        self.ragged_trace_count = 0
        self.burst_trace_count = 0
        self.decode_buckets = set()
        self.prefill_buckets = set()
        self.ragged_buckets = set()
        self.burst_buckets = set()
        # --- device-resident decode bursts (ISSUE 19) -----------------------
        # the burst program's block-table width is pinned to ONE value
        # (the full pool's width bucket; bind_aot narrows it to the
        # artifact's max_seq_len) so the burst lattice stays two-axis —
        # (rows bucket, burst-length bucket) — with no mid-burst width
        # drift as rows cross block boundaries
        self._burst_steps = max(0, int(config.burst_steps or 0))
        self._burst_width = bucket_size(max(1, num_blocks - 1))
        self._burst_counters = _register_burst_metrics(
            self.metrics.registry, labels=self.metrics.labels)
        donate = (1, 2) if jax.default_backend() == "tpu" else ()
        if self.mp > 1:
            jit_kw = self._mesh_jit_shardings(mesh, cfg)
        else:
            jit_kw = {"decode": {}, "prefill": {}, "chunk": {},
                      "ragged": {}, "burst": {}}
        self._jit_decode = jax.jit(self._decode_fn, donate_argnums=donate,
                                   **jit_kw["decode"])
        self._jit_prefill = jax.jit(self._prefill_fn, donate_argnums=donate,
                                    **jit_kw["prefill"])
        self._jit_chunk_prefill = jax.jit(self._chunk_prefill_fn,
                                          donate_argnums=donate,
                                          **jit_kw["chunk"])
        self._jit_unified = jax.jit(self._unified_fn, donate_argnums=donate,
                                    **jit_kw["ragged"])
        self._jit_burst = jax.jit(self._burst_fn, donate_argnums=donate,
                                  **jit_kw["burst"])
        self._profile_ops = config.profile_ops
        model.eval()
        # --- speculative decoding (ISSUE 18) --------------------------------
        # host-side n-gram proposer + verify-row bookkeeping; packs draft
        # tokens into the unified ragged program as short verify chunks,
        # so spec on vs off is the SAME program family and bucket lattice
        self.spec = None
        if config.spec is not None and \
                getattr(config.spec, "enabled", True):
            if not self._unified:
                raise ValueError(
                    "EngineConfig.spec requires unified_step=True: draft "
                    "verification packs into the unified ragged program "
                    "(there is no legacy-family verify path)")
            sched_cfg = self.scheduler.config
            if sched_cfg.max_tokens_per_step is None:
                raise ValueError(
                    "EngineConfig.spec requires "
                    "SchedulerConfig.max_tokens_per_step: draft tokens "
                    "compete for the step's leftover token budget — an "
                    "unbounded budget would unbound the packed bucket")
            from .spec import SpecDecoder

            self.spec = SpecDecoder(config.spec,
                                    registry=self.metrics.registry,
                                    labels=metrics_labels)
        # --- AOT serving artifacts (ISSUE 15) -------------------------------
        # bound LAST: validate() compares against the fully-resolved
        # engine (mp, pools, unified flag).  A pre-loaded artifact
        # object (config.aot — the fleet-sharing form) wins over a path.
        self._aot = None
        art = config.aot
        if art is None and config.aot_path:
            from .aot import AotArtifact

            art = AotArtifact.load(config.aot_path)
        if art is not None:
            self.bind_aot(art)

    # --- AOT artifact binding ----------------------------------------------
    @property
    def aot_artifact(self):
        """The bound :class:`~paddle_tpu.serving.aot.AotArtifact`, or
        ``None`` when this engine traces at runtime."""
        return self._aot

    def bind_aot(self, artifact, record_load: bool = True) -> None:
        """Validate + bind an AOT artifact: every step program now
        dispatches through the artifact's pre-lowered StableHLO instead
        of the engine's jit entry points — the retrace counters can
        never move again.  The supervisor calls this on rebuilt replicas
        (:meth:`FleetSupervisor._rebuild`, with ``record_load=False`` —
        a rebind reuses an already-loaded artifact, so the load
        histogram must not re-observe a disk load that never happened).
        Raises :class:`~paddle_tpu.serving.aot.AotManifestMismatch` on
        any deployment disagreement."""
        artifact.validate(self)
        self._aot = artifact
        # admission-side guard (the loud backstop stays in
        # AotArtifact.call): a request whose target length outgrows the
        # saved universe is rejected honestly at admission instead of
        # raising AotBucketMissing from the engine thread mid-stream
        self.scheduler.seq_len_cap = int(artifact.manifest["max_seq_len"])
        # burst programs (ISSUE 19) were exported with the table width
        # derived from the artifact's max_seq_len; the seq_len_cap set
        # above guarantees no admitted sequence can outgrow it, so the
        # launch-side arrays must build at the SAME width
        cap = self.scheduler.seq_len_cap
        self._burst_width = bucket_size(
            max(1, (cap + self.block_size - 1) // self.block_size))
        # AOT attribution (ISSUE 15 satellite): /v1/debug/compiles and
        # /metrics must show "loaded an artifact" instead of fake
        # compile rows — and flag any later trace as the bug it is.
        # ONE disk load = ONE serving_aot_load_seconds sample per
        # registry: the artifact dedups binds of the same loaded object
        # (dp replicas, rebuild factories that thread it through)
        sp = self.stepprof
        observe = record_load
        if observe and sp.enabled and sp.registry is not None:
            observe = artifact.mark_load_observed(sp.registry)
        sp.record_aot_load(artifact.load_seconds,
                           artifact.program_count, observe=observe)

    def _step_call(self, program: str, bucket, jit_fn, *args):
        """THE aot-vs-jit dispatch choice, shared by all five step
        program families: serve from the bound artifact (counting the
        hit) or fall back to the engine's jit entry point.  Every call
        is exactly one host->device round trip — the denominator of the
        burst saving (ISSUE 19), counted here so per-step and burst
        launches share one ledger."""
        self._burst_counters["roundtrips"].inc()
        if self._aot is None:
            return jit_fn(*args)
        out = self._aot.call(program, bucket, *args)
        self.stepprof.record_aot_hit(program)
        return out

    def _mesh_jit_shardings(self, mesh, cfg) -> Dict[str, dict]:
        """Explicit in/out shardings for the three mesh-spanning jitted
        programs: parameters per their fitted ``PartitionSpec``
        annotations, KV pools head-sharded over ``mp``, every routing
        array (ids, positions, tables, lens, slots) **replicated** — the
        host keeps one logical view and GSPMD splits the compute.  Being
        explicit (rather than letting propagation guess from committed
        inputs) keeps placement deterministic per bucket."""
        from jax.sharding import NamedSharding, PartitionSpec

        from ..parallel.utils import _fit_spec, param_spec

        repl = NamedSharding(mesh, PartitionSpec())
        kv = NamedSharding(mesh, PartitionSpec(*KV_POOL_SPEC))  # matches
        # shard_kv_pool's placement — same constant, cannot drift
        pools = tuple(kv for _ in range(cfg.num_hidden_layers))
        params = tuple(
            NamedSharding(mesh, _fit_spec(param_spec(p), tuple(p.shape), mesh))
            for p in self._params)
        # sampled tokens + logits + audit logit-stats replicated, pools
        # stay sharded.  Every family takes 4 extra replicated inputs —
        # the per-row sampling quartet (temps, top_ks, top_ps, keys) the
        # in-trace sampler consumes (ISSUE 18).
        out = (repl, repl, repl, pools, pools)
        return {
            # (param_vals, k_pools, v_pools, ids, pos, tables, lens,
            #  slot_blocks, slot_offsets, temps, top_ks, top_ps, keys)
            "decode": {"in_shardings": (params, pools, pools) + (repl,) * 10,
                       "out_shardings": out},
            # (param_vals, k_pools, v_pools, ids, last_pos, blocks, offs,
            #  temps, top_ks, top_ps, keys)
            "prefill": {"in_shardings": (params, pools, pools) + (repl,) * 8,
                        "out_shardings": out},
            # (param_vals, k_pools, v_pools, ids, start, last_pos, tables,
            #  lens, slot_blocks, slot_offsets, temps, top_ks, top_ps,
            #  keys)
            "chunk": {"in_shardings": (params, pools, pools) + (repl,) * 11,
                      "out_shardings": out},
            # (param_vals, k_pools, v_pools, ids, pos, seg_ids, last_idx,
            #  tables, lens, slot_blocks, slot_offsets, temps, top_ks,
            #  top_ps, keys) — the unified ragged step (ISSUE 11):
            # packed routing metadata replicated, pools sharded; inside,
            # the ragged kernel re-partitions over mp via shard_map
            "ragged": {"in_shardings": (params, pools, pools) + (repl,) * 12,
                       "out_shardings": out},
            # (param_vals, k_pools, v_pools, ids, pos, tables, lens,
            #  slot_blocks, slot_offsets, n_steps, active, eos_ids,
            #  temps, top_ks, top_ps, keys) — the decode burst
            # (ISSUE 19): the same decode shape looped in-trace; all
            # routing (including the [B, Nb] per-iteration slot arrays
            # and the scalar trip count) replicated, pools sharded
            "burst": {"in_shardings": (params, pools, pools) + (repl,) * 13,
                      "out_shardings": out},
        }

    # --- functional model step (traced) ------------------------------------
    def _call_model(self, ids_val, caches, pos_val, param_vals):
        """Run the eager module under the current trace with parameters
        swapped to the traced ``param_vals`` (and restored after) — the
        same rebinding trick as ``train_batch_1f1b``'s head_apply, so the
        jitted step threads weights as arguments instead of baking them
        in as constants."""
        from .. import no_grad

        saved = [p._value for p in self._params]
        for p, v in zip(self._params, param_vals):
            p._value = v
        try:
            with no_grad():
                out = self.model(Tensor(ids_val), caches=caches,
                                 pos=Tensor(pos_val))
            return out._value
        finally:
            for p, v in zip(self._params, saved):
                p._value = v

    def _decode_fn(self, param_vals, k_pools, v_pools, ids, pos,
                   tables, lens, slot_blocks, slot_offsets,
                   temps, top_ks, top_ps, keys):
        """One batched decode step: write each sequence's token KV into
        its (block, offset) slot, attend through the block tables, sample
        each row's next token in-trace (ISSUE 18) and return tokens +
        last-position logits + updated pools.  Shapes fixed per bucket."""
        self.decode_trace_count += 1
        # host side-effects run only while JAX traces: these count
        # COMPILATIONS (bounded by the bucket sets), not calls
        self.metrics.count("decode_jit_traces")
        self.tracer.instant("decode_jit_trace", cat="jit",
                            batch=int(ids.shape[0]),
                            table_width=int(tables.shape[1]))
        caches = []
        for k, v in zip(k_pools, v_pools):
            c = PagedCache(Tensor(k), Tensor(v))
            c.route(tables, lens, slot_blocks, slot_offsets)
            c.use_pallas = self._use_pallas  # EngineConfig.use_pallas_paged
            caches.append(c)
        logits = self._call_model(ids, caches, pos, param_vals)
        last = logits[:, -1, :].astype(jnp.float32)
        # in-trace sampling epilogue (ISSUE 18): greedy rows (temp 0,
        # padding included) reduce to argmax inside the same program —
        # one compiled program serves greedy and sampled batches
        tokens = sample_tokens(last, temps, top_ks, top_ps, keys)
        # numerics-audit sentinel (ISSUE 10): tiny in-trace reductions
        # over the output logits ride the launch as one extra output —
        # computed unconditionally so audit on/off is the SAME program
        return (tokens, last, logit_stats(last),
                tuple(c.k_pool._value for c in caches),
                tuple(c.v_pool._value for c in caches))

    def _burst_fn(self, param_vals, k_pools, v_pools, ids, pos, tables,
                  lens, slot_blocks, slot_offsets, n_steps, active,
                  eos_ids, temps, top_ks, top_ps, keys):
        """Device-resident decode burst (ISSUE 19): up to ``n_steps``
        chained decode steps in ONE program via
        :func:`~paddle_tpu.ops.decode_burst.run_burst` — each iteration
        is exactly the ``_decode_fn`` body (route → forward → fused
        sampling), with the sampled token fed straight back as the next
        input and only the ``[B, Nb]`` token buffer crossing to the
        host.  Output tuple matches the other families (tokens, last
        logits, logit stats, pools) so ``_step_call``/AOT dispatch is
        unchanged."""
        self.burst_trace_count += 1
        self.metrics.count("burst_jit_traces")
        self.tracer.instant("burst_jit_trace", cat="jit",
                            batch=int(ids.shape[0]),
                            burst_bucket=int(slot_blocks.shape[1]))

        def model_step(ids_j, pos_j, lens_j, sb, so, kp, vp):
            caches = []
            for k, v in zip(kp, vp):
                c = PagedCache(Tensor(k), Tensor(v))
                c.route(tables, lens_j, sb, so)
                c.use_pallas = self._use_pallas
                caches.append(c)
            logits = self._call_model(ids_j, caches, pos_j, param_vals)
            return (logits[:, -1, :].astype(jnp.float32),
                    tuple(c.k_pool._value for c in caches),
                    tuple(c.v_pool._value for c in caches))

        buf, last, k_out, v_out = run_burst(
            model_step, n_steps, self.model.config.vocab_size, ids, pos,
            lens, active, eos_ids, slot_blocks, slot_offsets, temps,
            top_ks, top_ps, keys, k_pools, v_pools)
        return buf, last, logit_stats(last), k_out, v_out

    def _prefill_fn(self, param_vals, k_pools, v_pools, ids, last_pos,
                    blocks, offs, temps, top_ks, top_ps, keys):
        """Bucketed prefill: dense-cache forward over the (padded) prompt,
        then scatter every layer's K/V into the sequence's pages.  Pad
        positions scatter into block 0 (the null page).  Returns the
        logits row of the LAST REAL token + updated pools."""
        self.prefill_trace_count += 1
        self.metrics.count("prefill_jit_traces")
        self.tracer.instant("prefill_jit_trace", cat="jit",
                            prompt_bucket=int(ids.shape[1]))
        cfg = self.model.config
        Tb = ids.shape[1]
        dense = [
            (Tensor(jnp.zeros((1, Tb, cfg.num_key_value_heads, cfg.head_dim),
                              self._pool_dtype)),
             Tensor(jnp.zeros((1, Tb, cfg.num_key_value_heads, cfg.head_dim),
                              self._pool_dtype)))
            for _ in range(cfg.num_hidden_layers)
        ]
        logits = self._call_model(ids, dense, jnp.int32(0), param_vals)
        last = jnp.take(logits[0], last_pos, axis=0).astype(jnp.float32)
        tokens = sample_tokens(last[None], temps, top_ks, top_ps, keys)
        new_k = tuple(
            kp.at[blocks, offs].set(kb._value[0].astype(kp.dtype))
            for kp, (kb, _) in zip(k_pools, dense))
        new_v = tuple(
            vp.at[blocks, offs].set(vb._value[0].astype(vp.dtype))
            for vp, (_, vb) in zip(v_pools, dense))
        return tokens, last, logit_stats(last), new_k, new_v

    def _chunk_prefill_fn(self, param_vals, k_pools, v_pools, ids, start,
                          last_pos, tables, lens, slot_blocks,
                          slot_offsets, temps, top_ks, top_ps, keys):
        """Chunked/resumed prefill: run ``ids`` (one bucketed chunk of a
        prompt, starting at absolute position ``start``) straight through
        the PAGED pool — the chunk's K/V scatters into its (block, offset)
        slots and attention covers the already-computed prefix (cached
        fork or earlier chunks) plus the chunk itself.  Shapes are fixed
        per (chunk-bucket, table-bucket) pair.  Returns the logits row of
        the chunk's LAST REAL token + updated pools."""
        self.prefill_trace_count += 1
        self.metrics.count("prefill_jit_traces")
        self.tracer.instant("prefill_jit_trace", cat="jit",
                            chunk_bucket=int(ids.shape[1]),
                            table_bucket=int(tables.shape[1]))
        caches = []
        for k, v in zip(k_pools, v_pools):
            c = PagedCache(Tensor(k), Tensor(v))
            c.route(tables, lens, slot_blocks, slot_offsets, q_start=start)
            caches.append(c)
        logits = self._call_model(ids, caches, start, param_vals)
        last = jnp.take(logits[0], last_pos, axis=0).astype(jnp.float32)
        tokens = sample_tokens(last[None], temps, top_ks, top_ps, keys)
        return (tokens, last, logit_stats(last),
                tuple(c.k_pool._value for c in caches),
                tuple(c.v_pool._value for c in caches))

    def _unified_fn(self, param_vals, k_pools, v_pools, ids, pos, seg_ids,
                    last_idx, tables, lens, slot_blocks, slot_offsets,
                    temps, top_ks, top_ps, keys):
        """ONE packed ragged step (ISSUE 11): ``ids`` is a flat
        ``[1, Tb]`` token batch mixing decode rows (1 token each) and
        prefill chunks, with per-token absolute positions ``pos``
        ([1, Tb]), per-token row routing ``seg_ids`` ([Tb]) and per-ROW
        block tables / KV lengths ([Tb, TWb] / [Tb]; rows past the real
        count are null-page pads).  Every token scatters its K/V into its
        own (block, offset) slot and attends causally over its row's
        pages — the single fused program that replaces the three legacy
        families.  Returns each row's last-real-token logits (gathered
        at ``last_idx``) + updated pools.  Shapes fixed per
        (token-bucket, table-bucket) pair."""
        self.ragged_trace_count += 1
        self.metrics.count("ragged_jit_traces")
        self.tracer.instant("ragged_jit_trace", cat="jit",
                            token_bucket=int(ids.shape[1]),
                            table_bucket=int(tables.shape[1]))
        caches = []
        for k, v in zip(k_pools, v_pools):
            c = PagedCache(Tensor(k), Tensor(v))
            c.route(tables, lens, slot_blocks, slot_offsets,
                    q_start=pos[0], seg_ids=seg_ids)
            c.use_pallas = self._use_pallas_ragged  # shard_map kernel —
            # the mp>1 auto-pin does NOT apply to the ragged program
            caches.append(c)
        logits = self._call_model(ids, caches, pos, param_vals)
        last = jnp.take(logits[0], last_idx, axis=0).astype(jnp.float32)
        # sample at EVERY packed token position (ISSUE 18): the sampling
        # quartet is per-TOKEN here, so a spec-decode verify row gets its
        # per-position target tokens from the very same reduction a plain
        # decode row's single position uses — no new program family
        tokens = sample_tokens(logits[0].astype(jnp.float32),
                               temps, top_ks, top_ps, keys)
        return (tokens, last, logit_stats(last),
                tuple(c.k_pool._value for c in caches),
                tuple(c.v_pool._value for c in caches))

    # --- request lifecycle --------------------------------------------------
    def set_lifecycle(self, tracker: LifecycleTracker,
                      replica: Optional[str] = None) -> None:
        """Rebind this engine onto a shared lifecycle tracker (the fleet
        router calls this before any request exists, so router-thread
        routing events and engine-thread execution events land in ONE
        timeline per request).  ``replica`` pins the identity this
        engine stamps on every event — the router passes the replica
        INDEX so flight-recorder rings and the ``engine_death`` trigger
        key always agree, regardless of what the metrics labels say.
        The engine's own ``EngineConfig.lifecycle_events`` gate still
        applies."""
        self.lifecycle = tracker
        if replica is not None:
            self._replica_label = str(replica)

    def _lc(self, rid, name: str, **attrs) -> None:
        """One lifecycle event, replica-stamped; no-op when gated off."""
        if self._lifecycle_on:
            self.lifecycle.event(rid, name, replica=self._replica_label,
                                 **attrs)

    def _on_pool_evict(self, block: int, depth: int, lifetime: int,
                       cause: str) -> None:
        """BlockPool eviction hook (ISSUE 13): a reuse-parked cached
        block was clobbered for an allocation.  Event-driven — the
        counter, the lifecycle ``prefix_cache_eviction`` event (with the
        clobbered chain depth and the allocation cause), and the
        eviction-cause series all fire HERE, at the eviction, instead of
        being lag-batched by a per-step counter diff."""
        self.metrics.count("prefix_cache_evictions")
        self.cachestat.record_eviction(depth, lifetime, cause)
        # engine-level event (no single owning request): rid=None goes
        # to the flight-recorder rings only.  Per-step event budget:
        # counters above stay exact, but eviction N+1.. of one step
        # collapse into the burst summary _flush_evict_burst emits —
        # a thrashing step must not wash the flight ring.
        self._evict_events_step += 1
        if self._evict_events_step <= _EVICT_EVENTS_PER_STEP:
            self._lc(None, "prefix_cache_eviction", block=int(block),
                     depth=int(depth), lifetime_steps=int(lifetime),
                     cause=cause)

    def _flush_evict_burst(self) -> None:
        """End-of-step: one summary event for evictions past the
        per-step lifecycle-event budget, then reset the budget."""
        suppressed = self._evict_events_step - _EVICT_EVENTS_PER_STEP
        self._evict_events_step = 0
        if suppressed > 0:
            self._lc(None, "prefix_cache_eviction_burst",
                     suppressed=suppressed,
                     total=suppressed + _EVICT_EVENTS_PER_STEP)

    def _on_pool_revive(self, block: int, depth: int, lru_depth: int,
                        lifetime: int) -> None:
        """BlockPool revive hook (ISSUE 13): a prefix fork revived a
        reuse-parked block — the LRU position it sat at feeds the
        hit-depth histogram (the reuse-LRU saturation early-warning)."""
        self.cachestat.record_revive(lru_depth, lifetime)

    def set_history(self, history) -> None:
        """Bind a :class:`~paddle_tpu.observability.history.HistoryStore`
        (ISSUE 14).  The fleet router owns the store (one fleet-wide
        sampling cadence); each engine step ticks it.  Ignored when
        ``EngineConfig.history`` is off — the fleet refuses
        heterogeneous gates, so a half-sampled fleet cannot exist."""
        if self.engine_config.history:
            self.history = history

    def set_fault_injector(self, injector) -> None:
        """Bind a :class:`~paddle_tpu.serving.faultinject.FaultInjector`
        (ISSUE 12).  The injector is consulted at the named injection
        points inside :meth:`step`; the fleet router owns the instance
        so its exactly-once schedule survives supervisor rebuilds."""
        self._fault = injector

    def add_request(self, prompt_ids, sampling: Optional[SamplingParams] = None,
                    request_id=None, priority: int = 0,
                    trace_id: Optional[str] = None,
                    prefix_hashes: Optional[List[bytes]] = None,
                    slo_ms: Optional[float] = None,
                    resume_tokens: Optional[List[int]] = None) -> Request:
        """Enqueue a request (admission happens inside ``step``).

        ``trace_id`` (defaults to ``str(request_id)``) is attached to every
        span/instant the engine records for this request, so a frontend can
        reconstruct one request's prefill/preempt/decode lifecycle from the
        exported chrome trace.

        ``prefix_hashes`` (ISSUE 6) carries leading-block chain hashes a
        router already computed for prefix-affinity placement
        (``ops.paged_attention.prefix_chain_hashes`` over THIS prompt and
        THIS engine's block size); the admission probe reuses them
        instead of re-hashing the same blocks.

        ``resume_tokens`` (ISSUE 20) seeds already-emitted output tokens
        for a request migrating IN mid-stream (prefill→decode hand-off):
        the prefill target becomes prompt+outputs and the recompute
        discipline continues the stream from the next position — with
        the donor's KV imported first, the seeded tail is a cache hit,
        not a recompute."""
        req = Request(prompt_ids=list(np.asarray(prompt_ids).reshape(-1)),
                      sampling=sampling or SamplingParams(),
                      request_id=request_id, priority=priority,
                      trace_id=trace_id, prefix_hashes=prefix_hashes,
                      slo_ms=slo_ms)
        if req.request_id in self.requests:
            raise ValueError(f"request id {req.request_id!r} already exists")
        if resume_tokens:
            req.output_tokens.extend(int(t) for t in resume_tokens)
        req.arrival_time = time.perf_counter()
        self.requests[req.request_id] = req
        self.scheduler.add(req)
        self.metrics.count("requests_admitted")
        self._lc(req.request_id, _lc.EV_ENQUEUED, trace_id=req.trace_id,
                 prompt_tokens=len(req.prompt_ids), slo_ms=slo_ms,
                 queue_depth=self.scheduler.queue_depth)
        return req

    def abort_request(self, request_id,
                      reason: FinishReason = FinishReason.ABORT) -> bool:
        """Abort: frees blocks immediately, ends any stream with
        ``reason`` (default ABORT; the HTTP frontend passes TIMEOUT for
        deadline/drain aborts).  True if the request was still live."""
        req = self.requests.get(request_id)
        if req is None or req.finished:
            return False
        self.scheduler.remove(req)
        self.kv.free(req.request_id)
        self._finish(req, reason)
        self.requests.pop(request_id, None)
        return True

    def _finish(self, req: Request, reason: FinishReason) -> None:
        req.state = RequestState.FINISHED
        req.finish_reason = reason
        req.finish_time = time.perf_counter()
        self.metrics.count(f"requests_finished_{reason.value}")
        e2e = req.finish_time - req.arrival_time
        self.metrics.observe_finish(e2e, req.slo_ms)
        self._lc(req.request_id, _lc.EV_FINISH, reason=reason.value,
                 e2e_s=round(e2e, 6), generated=len(req.output_tokens),
                 preemptions=req.num_preemptions)
        # park the attribution row in the bounded recent ring (ISSUE 13)
        self.cachestat.close_request(req.request_id)

    def _emit(self, req: Request, tok: int) -> None:
        """Append one sampled token + finish-state bookkeeping."""
        now = time.perf_counter()
        if req.first_token_time is None:
            req.first_token_time = now
            ttft = now - req.arrival_time
            self.metrics.observe_ttft(ttft)
            if req.prefill_start_time is not None:
                # the whole prefill PHASE (chunks + recomputes), the
                # middle leg of the SLO breakdown
                self.metrics.observe_prefill_phase(
                    now - req.prefill_start_time)
            self._lc(req.request_id, _lc.EV_FIRST_TOKEN,
                     ttft_s=round(ttft, 6))
        else:
            itl = now - req._last_emit
            self.metrics.observe_inter_token(itl)
            self._lc(req.request_id, _lc.EV_DECODE_TOKEN,
                     itl_s=round(itl, 6))
        req._last_emit = now
        req.append_token(tok)
        if req.hit_eos(tok):
            self._finish(req, FinishReason.EOS)
        elif len(req.output_tokens) >= req.sampling.max_new_tokens:
            self._finish(req, FinishReason.LENGTH)

    def _emit_device(self, req: Request, tok: int) -> None:
        """Emit one DEVICE-sampled token (ISSUE 18): the step program
        already ran the greedy/sampled reduction in-trace; the host only
        attributes the emission to the right counter.  The request's
        legacy host RNG is never consumed — the device key is the pure
        ``(seed, output_position)`` pair, so determinism needs no host
        stream at all."""
        kind = "greedy" if req.sampling.temperature == 0.0 else "sampled"
        self._sampling_counters[kind].inc()
        self._emit(req, int(tok))

    def _retire(self, req: Request) -> None:
        self.scheduler.remove(req)
        self.kv.free(req.request_id)
        # drop the engine's handle so a long-lived server never accumulates
        # finished Requests; the caller keeps the object from add_request
        self.requests.pop(req.request_id, None)

    # --- execution ----------------------------------------------------------
    def _param_vals(self):
        return tuple(p._value for p in self._params)

    def _collective_phase(self, phase: str) -> Optional[str]:
        """StepTimer's extra label for the mesh-spanning step: the wall
        time also lands in ``serving_collective_seconds{phase=...}`` —
        only when the step actually spans shards (mp > 1); the series
        itself is pre-registered so it shows on ``/metrics`` either
        way."""
        return phase if self.mp > 1 else None

    def _begin_prefill_chunk(self, req: Request, t0: float):
        """Resolve + reserve this step's prefill chunk for ``req`` — the
        host bookkeeping shared row-for-row by the legacy prefill
        programs and the unified packed step (sharing it is what keeps
        the two paths' metrics and greedy tokens identical).  Returns
        ``(ids_full, target, start, n, recompute)``."""
        rid = req.request_id
        ids_full = req.prompt_ids + req.output_tokens
        target = len(ids_full)
        start = self.kv.seq_len(rid)  # cached fork + earlier chunks
        n = req._chunk_tokens if req._chunk_tokens else target - start
        req._chunk_tokens = None
        recompute = bool(req.output_tokens
                         and start == req.num_cached_tokens)
        if req.prefill_start_time is None:
            # first prefill work for this request: the queue-wait leg of
            # the SLO breakdown ends here
            req.prefill_start_time = t0
            self.metrics.observe_queue_wait(t0 - req.arrival_time)
        if recompute:
            self.metrics.count("recompute_prefills")  # first chunk only
        if not self.kv.allocate(rid, n, cause="prefill_chunk"):
            raise PoolExhausted(  # scheduler planning guarantees room
                f"prefill chunk of {n} tokens for {rid!r} after admission")
        return ids_full, target, start, n, recompute

    def _finish_prefill_chunk(self, req: Request, ids_full, target: int,
                              start: int, n: int, recompute: bool,
                              t0: float, tok: int) -> None:
        """Post-launch bookkeeping for one prefill chunk, shared by both
        program paths: commit, lifecycle event, counters, prefix-hash
        registration, and the completion emission — ``tok`` is the
        device-sampled token off the final chunk's last-position logits,
        emitted only when the prefill completes."""
        rid = req.request_id
        self.kv.commit(rid, n)
        self._lc(rid, _lc.EV_PREFILL_CHUNK, start=start, tokens=n,
                 target=target, chunk=bool(start or n != target),
                 recompute=recompute,
                 duration_s=round(time.perf_counter() - t0, 6))
        self.metrics.count("prefill_tokens_computed", n)
        if self.kv.prefix_cache_enabled:
            # index the fully-written blocks NOW, so a same-prefix request
            # admitted next step shares them even mid-prefill
            self.kv.record_block_hashes(rid, ids_full, start + n)
        if start + n >= target:
            self._emit_device(req, tok)

    def _prefill(self, req: Request) -> None:
        """Run one bucketed prefill program for ``req`` — the whole
        prompt (cold one-shot), or one chunk of it (token-budgeted
        chunked prefill and/or resume past a prefix-cache hit).  Samples
        the request's next token only when the prefill completes (the
        final chunk's last-position logits ARE that token)."""
        rid = req.request_id
        t_chunk0 = time.perf_counter()
        ids, target, start, n, recompute = \
            self._begin_prefill_chunk(req, t_chunk0)
        table = self.kv.table(rid)
        pos = np.arange(start, start + n)
        # one sampling quartet row: the final chunk's last-position draw
        # (output position len(output_tokens) — on recompute the replayed
        # positions are already in output_tokens and never re-drawn)
        pack = SamplingPack(1)
        pack.set_request(0, req)
        if start == 0 and n == target:
            # cold one-shot: dense-cache forward + scatter (the cheapest
            # program when nothing is cached and no budget splits it)
            Tb = bucket_size(target)
            ids_arr = np.zeros((1, Tb), np.int64)
            ids_arr[0, :target] = ids
            blocks = np.zeros((Tb,), np.int32)  # pads -> null page
            blocks[:target] = [table[p // self.block_size] for p in pos]
            offs = (np.arange(Tb) % self.block_size).astype(np.int32)
            self.prefill_buckets.add(("prefill", Tb))
            traces0 = self.prefill_trace_count
            with self.tracer.span("prefill_step", cat="serving",
                                  request=str(rid), trace=req.trace_id,
                                  tokens=target, bucket=Tb,
                                  recompute=bool(req.output_tokens)):
                with StepTimer(self.metrics, "prefill_step",
                               self._collective_phase("prefill")) as st:
                    toks, last, stats, self._k_pools, self._v_pools = \
                        self._step_call(
                            "prefill", (Tb,), self._jit_prefill,
                            self._param_vals(), self._k_pools,
                            self._v_pools, ids_arr, np.int32(target - 1),
                            blocks, offs, *pack.arrays())
                    logits = np.asarray(last, np.float32)
                    tok = int(np.asarray(toks, np.int32)[0])
            if self.prefill_trace_count > traces0:
                # the in-trace counter advanced during THIS launch, so
                # its wall time is the trace+compile of this bucket
                self.stepprof.record_compile("prefill", (Tb,), st.dt)
            self.stepprof.record_program(
                "prefill", (Tb,), scheduled=n, capacity=Tb, wall_s=st.dt,
                request=str(rid))
            if self.audit.enabled:
                self.audit.observe_program(
                    "prefill", np.asarray(stats, np.float32), (Tb,),
                    logits=logits[None, :],
                    inputs={"ids": ids_arr, "blocks": blocks,
                            "offs": offs},
                    requests=[{"id": str(rid),
                               "greedy": req.sampling.temperature == 0.0}])
        else:
            # chunk / resume: the chunk scatters into its pages and
            # attends over the paged prefix, so earlier chunks and
            # prefix-cache forks need no recompute.  Two buckets bound
            # the trace count: chunk width and block-table width.
            Wb = bucket_size(n)
            TWb = bucket_size(len(table))
            ids_arr = np.zeros((1, Wb), np.int64)
            ids_arr[0, :n] = ids[start:start + n]
            blocks = np.zeros((1, Wb), np.int32)  # pads -> null page
            blocks[0, :n] = [table[p // self.block_size] for p in pos]
            offs = np.zeros((1, Wb), np.int32)
            offs[0, :n] = pos % self.block_size
            tables = np.zeros((1, TWb), np.int32)
            tables[0, :len(table)] = table
            lens = np.array([start + n], np.int32)
            self.prefill_buckets.add(("chunk", Wb, TWb))
            self.metrics.count("chunked_prefill_steps")
            traces0 = self.prefill_trace_count
            with self.tracer.span("prefill_step", cat="serving",
                                  request=str(rid), trace=req.trace_id,
                                  tokens=n, bucket=Wb, chunk=True,
                                  start=start,
                                  cached=req.num_cached_tokens,
                                  recompute=bool(req.output_tokens)):
                with StepTimer(self.metrics, "prefill_step",
                               self._collective_phase("prefill")) as st:
                    toks, last, stats, self._k_pools, self._v_pools = \
                        self._step_call(
                            "chunk", (Wb, TWb), self._jit_chunk_prefill,
                            self._param_vals(), self._k_pools,
                            self._v_pools, ids_arr, np.int32(start),
                            np.int32(n - 1), tables, lens, blocks, offs,
                            *pack.arrays())
                    logits = np.asarray(last, np.float32)
                    tok = int(np.asarray(toks, np.int32)[0])
            if self.prefill_trace_count > traces0:
                self.stepprof.record_compile("chunk", (Wb, TWb), st.dt)
            self.stepprof.record_program(
                "chunk", (Wb, TWb), scheduled=n, capacity=Wb,
                wall_s=st.dt, request=str(rid), start=start,
                table_width=len(table))
            if self.audit.enabled:
                self.audit.observe_program(
                    "chunk", np.asarray(stats, np.float32), (Wb, TWb),
                    logits=logits[None, :],
                    inputs={"ids": ids_arr, "start": np.int32(start),
                            "tables": tables, "lens": lens,
                            "slot_blocks": blocks, "slot_offsets": offs},
                    requests=[{"id": str(rid),
                               "greedy": req.sampling.temperature == 0.0}])
        self._finish_prefill_chunk(req, ids, target, start, n, recompute,
                                   t_chunk0, tok)

    def _decode(self, reqs: List[Request]) -> Dict[object, int]:
        """One bucketed decode step for ``reqs`` (slots already reserved
        by the scheduler on ``req._slot``)."""
        B = len(reqs)
        Bb = bucket_size(B)
        width = max(len(self.kv.table(r.request_id)) for r in reqs)
        Wb = bucket_size(width)
        ids = np.zeros((Bb, 1), np.int64)
        poss = np.zeros((Bb,), np.int32)
        tables = np.zeros((Bb, Wb), np.int32)
        lens = np.ones((Bb,), np.int32)   # pad rows: 1 token of null page
        slot_blocks = np.zeros((Bb,), np.int32)
        slot_offsets = np.zeros((Bb,), np.int32)
        pack = SamplingPack(Bb)  # pad rows stay temp=0 → argmax, ignored
        for i, r in enumerate(reqs):
            rid = r.request_id
            t = self.kv.table(rid)
            p = self.kv.seq_len(rid)
            ids[i, 0] = r.last_token
            poss[i] = p
            tables[i, :len(t)] = t
            lens[i] = p + 1               # cache length AFTER this token
            slot_blocks[i], slot_offsets[i] = r._slot
            pack.set_request(i, r)
        self.decode_buckets.add(("decode", Bb, Wb))
        traces0 = self.decode_trace_count
        # shadow-oracle capture (ISSUE 10): on sampled audit steps the
        # PRE-step pools are snapshotted so the auditor can re-execute
        # this exact step through the XLA gather reference program
        pre_pools = self.audit.snapshot_pools(self._k_pools,
                                              self._v_pools)
        with self.tracer.span("decode_step", cat="serving", batch=B,
                              batch_bucket=Bb, width_bucket=Wb,
                              requests=",".join(str(r.request_id)
                                                for r in reqs),
                              traces=",".join(str(r.trace_id)
                                              for r in reqs)):
            with StepTimer(self.metrics, "decode_step",
                           self._collective_phase("decode")) as st:
                toks, out, stats, self._k_pools, self._v_pools = \
                    self._step_call(
                        "decode", (Bb, Wb), self._jit_decode,
                        self._param_vals(), self._k_pools, self._v_pools,
                        ids, poss, tables, lens, slot_blocks,
                        slot_offsets, *pack.arrays())
                out = np.asarray(out, np.float32)
                toks = np.asarray(toks, np.int32)
        if self.decode_trace_count > traces0:
            self.stepprof.record_compile("decode", (Bb, Wb), st.dt)
        # token/row accounting only: scheduled = B real rows (one token
        # each) vs the Bb row bucket — this is the axis the scheduler's
        # tokens_planned ledger counts, so the invariant stays exact.
        # Width-bucket padding (tables padded `width` -> Wb with null
        # pages) is NOT in these counters; it rides the record as the
        # table_width attr next to the bucket shape.
        self.stepprof.record_program(
            "decode", (Bb, Wb), scheduled=B, capacity=Bb, wall_s=st.dt,
            table_width=width,
            requests=",".join(str(r.request_id) for r in reqs))
        if self.audit.enabled:
            # sentinel over the REAL rows (pad rows attend the null page
            # — their logits are not part of the serving contract), plus
            # the shadow re-execution when this step is sampled.
            # kernel_corrupt (ISSUE 12) corrupts ONLY this audit copy —
            # the sampler below reads the untouched `out`, so served
            # tokens stay correct while the divergence net trips.  Only
            # SAMPLED steps run the shadow compare, so the exactly-once
            # plan entry must not be consumed by a launch the oracle
            # never checks.
            audit_logits = out[:B]
            if self._fault is not None and self.audit.sampled:
                audit_logits = self._fault.corrupt_logits(
                    self.step_seq, audit_logits)
            self.audit.observe_program(
                "decode", np.asarray(stats, np.float32)[:B], (Bb, Wb),
                logits=audit_logits,
                inputs={"ids": ids, "pos": poss, "tables": tables,
                        "lens": lens, "slot_blocks": slot_blocks,
                        "slot_offsets": slot_offsets},
                pre_pools=pre_pools,
                requests=[{"id": str(r.request_id),
                           "greedy": r.sampling.temperature == 0.0}
                          for r in reqs])
        result = {}
        for i, r in enumerate(reqs):
            self.kv.commit(r.request_id, 1)
            tok = int(toks[i])
            self._emit_device(r, tok)
            result[r.request_id] = tok
        return result

    def _burst_exec(self, reqs: List[Request],
                    n_steps: int) -> Dict[object, int]:
        """Launch ONE device-resident burst covering ``n_steps`` decode
        steps for a decode-only resident cohort (ISSUE 19).  The host
        pre-extends every row's block table to its worst-case burst
        length (the clamp guaranteed the pool can back it), launches the
        looped program, then reconciles the whole burst after the fact:
        per-token emission through the normal ``_emit`` bookkeeping
        (stream cursor, lifecycle decode_token events, ITL aggregates),
        KV commit of what was actually written, and truncation of the
        unused pre-allocated tail."""
        B = len(reqs)
        Bb = bucket_size(B)
        Nb = bucket_size(n_steps)
        W = self._burst_width
        starts: Dict[object, int] = {}
        for r in reqs:
            rid = r.request_id
            starts[rid] = self.kv.seq_len(rid)
            # positions p..p+n-1 all get slots up front (the decode slot
            # reservation already covers p); exact need is <= the
            # conservative per-row bound burst_capacity promised, so
            # failure here means the shared accessor broke — fail loudly
            if not self.kv.allocate(rid, n_steps, cause="burst"):
                raise PoolExhausted(
                    f"burst pre-allocation failed for {rid!r}: "
                    f"burst_capacity promised {n_steps} steps "
                    f"x {B} rows")
        ids = np.zeros((Bb, 1), np.int64)
        poss = np.zeros((Bb,), np.int32)
        tables = np.zeros((Bb, W), np.int32)
        lens = np.ones((Bb,), np.int32)   # pad rows: 1 token of null page
        slot_blocks = np.zeros((Bb, Nb), np.int32)
        slot_offsets = np.zeros((Bb, Nb), np.int32)
        active = np.zeros((Bb,), np.bool_)
        eos_ids = np.full((Bb,), -1, np.int32)
        pack = SamplingPack(Bb)
        bs = self.block_size
        for i, r in enumerate(reqs):
            rid = r.request_id
            t = self.kv.table(rid)
            p = starts[rid]
            ids[i, 0] = r.last_token
            poss[i] = p
            tables[i, :len(t)] = t
            lens[i] = p + 1
            for j in range(n_steps):
                q = p + j
                slot_blocks[i, j] = t[q // bs]
                slot_offsets[i, j] = q % bs
            active[i] = True
            if r.sampling.eos_token_id is not None:
                eos_ids[i] = int(r.sampling.eos_token_id)
            pack.set_request(i, r)
        self.burst_buckets.add(("burst", Bb, Nb))
        traces0 = self.burst_trace_count
        with self.tracer.span("burst_step", cat="serving", batch=B,
                              batch_bucket=Bb, burst_len=n_steps,
                              burst_bucket=Nb,
                              requests=",".join(str(r.request_id)
                                                for r in reqs),
                              traces=",".join(str(r.trace_id)
                                              for r in reqs)):
            with StepTimer(self.metrics, "burst_step",
                           self._collective_phase("burst")) as st:
                buf, _out, _stats, self._k_pools, self._v_pools = \
                    self._step_call(
                        "burst", (Bb, Nb), self._jit_burst,
                        self._param_vals(), self._k_pools, self._v_pools,
                        ids, poss, tables, lens, slot_blocks,
                        slot_offsets, np.int32(n_steps), active,
                        eos_ids, *pack.arrays())
                buf = np.asarray(buf, np.int32)
        if self.burst_trace_count > traces0:
            self.stepprof.record_compile("burst", (Bb, Nb), st.dt)
        result = {}
        emitted_total = 0
        for i, r in enumerate(reqs):
            rid = r.request_id
            e = 0
            for j in range(n_steps):
                tok = int(buf[i, j])
                if tok < 0:   # -1 sentinel: row went inactive (EOS)
                    break
                self._emit_device(r, tok)
                result[rid] = tok
                e += 1
                if r.finished:
                    break
            emitted_total += e
            # iteration j wrote the KV of its input token at p+j, so e
            # emissions committed e positions — identical to e per-step
            # decode commits; unfinished rows hand back the unused
            # pre-allocated tail (finished rows free wholesale in retire)
            self.kv.commit(rid, e)
            if not r.finished:
                self.kv.truncate(rid, starts[rid] + e)
        # scheduled-token ledger (ISSUE 9): the scheduler planned one
        # decode token per row; the burst's extra emissions are decode
        # work the ENGINE added — mirror them into the ledger so the
        # EXACT invariant (profiler scheduled == scheduler planned)
        # holds when one launch covers N steps
        self.scheduler.tokens_planned_decode += emitted_total - B
        self.stepprof.record_program(
            "burst", (Bb, Nb), scheduled=emitted_total, capacity=Bb * Nb,
            wall_s=st.dt, burst_len=n_steps,
            requests=",".join(str(r.request_id) for r in reqs))
        c = self._burst_counters
        c["launches"].inc()
        c["tokens"].inc(emitted_total)
        c["length"].observe(float(n_steps))
        return result

    def _unified_exec(self, prefills: List[Request],
                      decodes: List[Request],
                      draft_budget: int = 0) -> Dict[object, int]:
        """Pack this step's whole plan — decode rows + prefill chunks —
        into ONE ragged program launch (``EngineConfig.unified_step``).
        The token dim buckets on the TOTAL scheduled token count and the
        row/table arrays are padded to the same bucket, so the compile
        bound is (token-bucket × table-bucket) for the one family —
        strictly fewer shapes than the legacy three.  Host bookkeeping
        (allocation, commits, hash registration, sampling, lifecycle
        events) matches the legacy paths row-for-row, which is what
        keeps greedy tokens identical.

        Speculative decoding (ISSUE 18): with ``EngineConfig.spec`` set,
        decode rows may be upgraded to ``verify`` rows — the n-gram
        proposer's k draft tokens ride as a short chunk
        ``[last_token, d1..dk]`` at positions ``p..p+k``, inside the
        step's leftover ``draft_budget``.  The per-position in-trace
        sampler yields target tokens T_j at every position; the longest
        ``d_{j+1} == T_j`` prefix is accepted, ``T_0..T_a`` are emitted
        (a+1 tokens in ONE engine step) and the KV tail past the last
        accepted position rolls back via :meth:`KVCacheManager.truncate`
        (the preemption-recompute slot discipline, pointed at a length
        instead of zero)."""
        rows: List[Dict] = []
        t0 = time.perf_counter()
        for r in decodes:
            p = self.kv.seq_len(r.request_id)
            rows.append({"req": r, "kind": "decode", "start": p, "n": 1,
                         "tokens": [r.last_token], "slot": r._slot})
        drafts_packed = 0
        if self.spec is not None and draft_budget > 0:
            # upgrade decode rows to verify rows in-place (proposer +
            # draft-slot allocation; a row whose slots cannot be covered
            # stays a plain decode row — pool pressure, not an error)
            drafts_packed = self.spec.plan_drafts(self.kv, rows,
                                                  draft_budget)
            if drafts_packed:
                # keep the scheduled-token ledger exact (ISSUE 9): the
                # scheduler planned 1 token per decode row; the drafts
                # the engine packs on top are decode-side work too
                self.scheduler.tokens_planned_decode += drafts_packed
        for req in prefills:
            # the SAME pre-launch bookkeeping the legacy programs run
            # (queue-wait, recompute accounting, all-or-nothing allocate)
            ids_full, target, start, n, recompute = \
                self._begin_prefill_chunk(req, t0)
            rows.append({"req": req, "kind": "chunk", "start": start,
                         "n": n, "tokens": ids_full[start:start + n],
                         "target": target, "recompute": recompute,
                         "ids_full": ids_full})
        R = len(rows)
        T = sum(row["n"] for row in rows)
        Tb = bucket_size(T)
        width = max(len(self.kv.table(row["req"].request_id))
                    for row in rows)
        TWb = bucket_size(width)
        ids = np.zeros((1, Tb), np.int64)
        pos = np.zeros((1, Tb), np.int32)
        # pad tokens route to a pad row (all-null table, kv_len 1); when
        # R == Tb every row is real and no pad token exists
        seg = np.full((Tb,), min(R, Tb - 1), np.int32)
        last_idx = np.zeros((Tb,), np.int32)
        tables = np.zeros((Tb, TWb), np.int32)
        lens = np.ones((Tb,), np.int32)   # pad rows: 1 token of null page
        slot_blocks = np.zeros((Tb,), np.int32)  # pad tokens -> null page
        slot_offsets = np.zeros((Tb,), np.int32)
        # per-TOKEN sampling quartet (ISSUE 18): pad positions stay
        # temp=0 (argmax over the null page, discarded); a verify row's
        # k+1 positions each carry their own output-position draw index
        pack = SamplingPack(Tb)
        cursor = 0
        for i, row in enumerate(rows):
            req = row["req"]
            table = self.kv.table(req.request_id)
            n, start = row["n"], row["start"]
            row["cursor"] = cursor
            ids[0, cursor:cursor + n] = row["tokens"]
            pp = np.arange(start, start + n)
            pos[0, cursor:cursor + n] = pp
            seg[cursor:cursor + n] = i
            tables[i, :len(table)] = table
            lens[i] = start + n           # cache length AFTER this step
            if row["kind"] == "decode":
                slot_blocks[cursor], slot_offsets[cursor] = row["slot"]
                pack.set_request(cursor, req)
            else:
                # chunk AND verify rows: every position scatters into its
                # own table-derived slot (a verify row's draft slots were
                # just allocated by spec.plan_drafts, so its table covers
                # start+n like any mid-prefill chunk's does)
                slot_blocks[cursor:cursor + n] = [
                    table[x // self.block_size] for x in pp]
                slot_offsets[cursor:cursor + n] = pp % self.block_size
                if row["kind"] == "verify":
                    for j in range(n):
                        pack.set_request(cursor + j, req, offset=j)
                else:
                    # only the final chunk's last position is ever read
                    pack.set_request(cursor + n - 1, req)
            cursor += n
            last_idx[i] = cursor - 1
        self.ragged_buckets.add(("ragged", Tb, TWb))
        self.metrics.count("unified_steps")
        traces0 = self.ragged_trace_count
        pre_pools = self.audit.snapshot_pools(self._k_pools,
                                              self._v_pools)
        with self.tracer.span("unified_step", cat="serving", tokens=T,
                              rows=R, token_bucket=Tb, table_bucket=TWb,
                              requests=",".join(
                                  str(row["req"].request_id)
                                  for row in rows)):
            with StepTimer(self.metrics, "unified_step",
                           self._collective_phase("ragged")) as st:
                toks, out, stats, self._k_pools, self._v_pools = \
                    self._step_call(
                        "ragged", (Tb, TWb), self._jit_unified,
                        self._param_vals(), self._k_pools, self._v_pools,
                        ids, pos, seg, last_idx, tables, lens,
                        slot_blocks, slot_offsets, *pack.arrays())
                out = np.asarray(out, np.float32)
                toks = np.asarray(toks, np.int32)
        if self.ragged_trace_count > traces0:
            self.stepprof.record_compile("ragged", (Tb, TWb), st.dt)
        # scheduled = T real tokens (decode rows count 1 each) vs the Tb
        # token bucket — the same axis the scheduler's tokens_planned
        # ledger counts, so the PR 8 invariant stays exact in unified
        # mode.  Table-width padding rides the record as attrs.
        self.stepprof.record_program(
            "ragged", (Tb, TWb), scheduled=T, capacity=Tb, wall_s=st.dt,
            rows=R, table_width=width,
            requests=",".join(str(row["req"].request_id) for row in rows))
        if self.audit.enabled:
            # sentinel over the REAL rows; the shadow oracle re-executes
            # sampled packed steps through the independently jitted XLA
            # ragged reference (audit._reference_ragged).  kernel_corrupt
            # corrupts only this audit copy, on sampled steps only — see
            # _decode.
            audit_logits = out[:R]
            if self._fault is not None and self.audit.sampled:
                audit_logits = self._fault.corrupt_logits(
                    self.step_seq, audit_logits)
            self.audit.observe_program(
                "ragged", np.asarray(stats, np.float32)[:R], (Tb, TWb),
                logits=audit_logits,
                inputs={"ids": ids, "pos": pos, "seg_ids": seg,
                        "last_idx": last_idx, "tables": tables,
                        "lens": lens, "slot_blocks": slot_blocks,
                        "slot_offsets": slot_offsets},
                pre_pools=pre_pools,
                requests=[{"id": str(row["req"].request_id),
                           "greedy":
                           row["req"].sampling.temperature == 0.0}
                          for row in rows])
        emitted: Dict[object, int] = {}
        for i, row in enumerate(rows):
            req = row["req"]
            rid = req.request_id
            n, start = row["n"], row["start"]
            c0 = row["cursor"]
            if row["kind"] == "decode":
                self.kv.commit(rid, 1)
                tok = int(toks[c0])
                self._emit_device(req, tok)
                emitted[rid] = tok
                continue
            if row["kind"] == "verify":
                # spec accept/rollback (ISSUE 18): position j's target
                # T_j = toks[c0+j] is exactly the token the plain decode
                # path would have sampled at that output position (same
                # logits prefix, same (seed, draw) key) — so exact-match
                # acceptance keeps spec-on token-identical to spec-off
                # for greedy AND seeded sampling
                drafts = row["drafts"]
                accepted = 0
                for j, d in enumerate(drafts):
                    if int(toks[c0 + j]) == int(d):
                        accepted += 1
                    else:
                        break
                emitted_n = 0
                for j in range(accepted + 1):
                    self._emit_device(req, int(toks[c0 + j]))
                    emitted[rid] = int(toks[c0 + j])
                    emitted_n += 1
                    if req.finished:
                        break  # eos/length mid-run: later targets are
                        # tokens the plain path would never have drawn
                # KV valid prefix: the emitted tokens' consumed inputs
                # (last_token + the accepted drafts actually consumed) —
                # the newest emitted token's KV is, as ever, written by
                # the step that consumes it
                self.kv.commit(rid, emitted_n)
                if not req.finished:
                    # roll back the rejected/unconsumed draft tail (the
                    # preemption-recompute slot discipline, aimed at a
                    # length): surplus freshly-allocated blocks go back
                    # to the free list
                    self.kv.truncate(rid, start + emitted_n)
                self.spec.record(len(drafts), accepted)
                self._lc(rid, "spec_verify", drafted=len(drafts),
                         accepted=accepted, emitted=emitted_n)
                continue
            # the SAME post-launch bookkeeping the legacy programs run
            # (commit, lifecycle event, counters, hash registration,
            # completion emission)
            before = len(req.output_tokens)
            self._finish_prefill_chunk(req, row["ids_full"],
                                       row["target"], start, n,
                                       row["recompute"], t0,
                                       int(toks[c0 + n - 1]))
            if len(req.output_tokens) > before:  # prefill completed
                emitted[rid] = req.output_tokens[-1]
        return emitted

    def step(self) -> Dict[object, int]:
        """One engine iteration: schedule → prefill(s) → decode batch →
        retire.  Returns {request_id: token} emitted this step."""
        remove_timer = (self.metrics.install_dispatch_timer()
                        if self._profile_ops else lambda: None)
        self.step_seq += 1
        self.kv.clock = self.step_seq  # park lifetimes tick in steps
        self.stepprof.begin_step()
        self.audit.begin_step()
        fi = self._fault
        try:
            if fi is not None:
                # named injection points (ISSUE 12): slow_step sleeps
                # here (inside the replica's watchdog-watched section),
                # engine_step_raise raises (the thread dies through the
                # real death path — INSIDE this try, so the finally
                # still unhooks the dispatch timer from the global op
                # bus), pool_exhaust arms one planning pass of
                # allocation refusal consumed just below
                fi.begin_step(self.step_seq)
            with self.tracer.span("engine_step", cat="serving") as sp:
                if fi is not None and fi.pool_exhausted:
                    self.kv.refuse_allocations = True
                try:
                    plan = self.scheduler.schedule()
                finally:
                    # refusal applies to PLANNING only: the launches
                    # below must still allocate the chunks the (starved)
                    # plan actually contains
                    self.kv.refuse_allocations = False
                self.metrics.count("engine_steps")
                self.metrics.count("preemptions", len(plan.preempted))
                for req in plan.preempted:
                    self.tracer.instant(
                        "preemption", cat="serving",
                        request=str(req.request_id), trace=req.trace_id,
                        generated=len(req.output_tokens))
                    self._lc(req.request_id, _lc.EV_PREEMPTED,
                             generated=len(req.output_tokens))
                for req in plan.aborted:
                    # unservable at admission: scheduler set state/reason,
                    # the engine owns finish bookkeeping (timestamp +
                    # counter)
                    self._lc(req.request_id, _lc.EV_ADMISSION_REJECTED,
                             reason="abort", error=req.error)
                    self._finish(req, FinishReason.ABORT)
                    self.requests.pop(req.request_id, None)
                for req in plan.admitted:
                    cached = req.num_cached_tokens
                    total = len(req.prompt_ids) + len(req.output_tokens)
                    self.metrics.count("prefix_cache_hit_tokens", cached)
                    self.metrics.count("prefix_cache_miss_tokens",
                                       total - cached)
                    if req.prompt_cached_tokens is None:
                        # FIRST admission (output empty, so cached <=
                        # prompt): the client-facing usage attribution
                        req.prompt_cached_tokens = cached
                    # per-request attribution (ISSUE 13): accumulated at
                    # the SAME points as the counters above, so
                    # sum(per-request cached) == prefix_cache_hit_tokens
                    # exactly (asserted in tests and bench)
                    self.cachestat.record_admission(
                        req.request_id, cached, total - cached,
                        len(req.prompt_ids),
                        recompute=bool(req.output_tokens))
                    self._lc(req.request_id, _lc.EV_ADMITTED,
                             cached_tokens=cached,
                             computed_tokens=total - cached,
                             recompute=bool(req.output_tokens))
                    if cached:
                        self.tracer.instant(
                            "prefix_cache_hit", cat="serving",
                            request=str(req.request_id),
                            trace=req.trace_id, cached_tokens=cached)
                    if cached and self.cachestat.enabled:
                        # prefix-heat (ISSUE 13): keyed by the DEEPEST
                        # matched block's chain hash — it commits to the
                        # whole cached prefix.  Guarded: the table copy
                        # + hash lookup must cost nothing when the
                        # tracker is disabled.
                        depth = cached // self.block_size
                        table = self.kv.table(req.request_id)
                        self.cachestat.record_prefix_hit(
                            self.kv.block_chain_hash(table[depth - 1])
                            if 0 < depth <= len(table) else None,
                            depth, cached, self.step_seq)
                emitted: Dict[object, int] = {}
                decodes = [r for r in plan.decodes
                           if r.state is RequestState.RUNNING]
                # device-resident decode burst (ISSUE 19): a decode-only
                # resident cohort with a clamped horizon >= 2 runs ONE
                # looped launch covering N steps; any pending admission,
                # prefill continuation or spec drafting falls through to
                # the normal per-step paths (host decisions stay at
                # burst boundaries)
                burst_n = 0
                if self._burst_steps >= 2 and burst_eligible(
                        self.scheduler, plan, decodes, self.spec):
                    burst_n = clamp_burst(self._burst_steps, decodes,
                                          plan.burst_capacity)
                if burst_n >= 2:
                    emitted = self._burst_exec(decodes, burst_n)
                elif self._unified:
                    # unified ragged step (ISSUE 11): the whole plan —
                    # decode rows + prefill chunks — is ONE packed launch
                    # (draft tokens compete for the leftover budget,
                    # ISSUE 18)
                    if plan.prefills or decodes:
                        emitted = self._unified_exec(plan.prefills,
                                                     decodes,
                                                     plan.draft_budget)
                else:
                    for req in plan.prefills:
                        before = len(req.output_tokens)
                        self._prefill(req)
                        if len(req.output_tokens) > before:  # done —
                            # a partial chunk emits nothing yet
                            emitted[req.request_id] = req.output_tokens[-1]
                    if decodes:
                        emitted.update(self._decode(decodes))
                for req in list(self.scheduler.running):
                    if req.finished:
                        self._retire(req)
                # (prefix-cache evictions are event-driven now: the
                # pool's on_evict hook fires the counter, the lifecycle
                # event and the cause/depth series at the eviction;
                # past the per-step event budget they collapse into one
                # burst summary here)
                self._flush_evict_burst()
                self.metrics.set_cached_token_ratio()
                # pool timeline (ISSUE 13): one sample per engine step,
                # invariant-checked inside
                self.cachestat.sample_pool(
                    self.step_seq,
                    promised=self.scheduler.promised_blocks)
                self.metrics.sample_gauges(self.scheduler.queue_depth,
                                           self.scheduler.num_running,
                                           self.kv.occupancy())
                if self.history is not None:
                    # metrics history + alert evaluation (ISSUE 14):
                    # deterministic engine-step cadence, host-side only
                    self.history.on_step(self.step_seq)
                sp.set_attribute(
                    "step", int(self.metrics._counter("engine_steps").value))
                sp.set_attribute("emitted", len(emitted))
                sp.set_attribute("kv_occupancy",
                                 round(self.kv.occupancy(), 4))
            return emitted
        finally:
            # runs on the death path too: the partial step record still
            # reaches the last-K ring the flight bundle embeds
            self.stepprof.end_step()
            remove_timer()

    def run(self, max_steps: Optional[int] = None) -> None:
        """Drive ``step()`` until every request finishes."""
        steps = 0
        while self.scheduler.has_work():
            self.step()
            steps += 1
            if (max_steps is not None and steps >= max_steps
                    and self.scheduler.has_work()):
                raise RuntimeError(
                    f"engine did not drain within {max_steps} steps")

    # --- streaming ----------------------------------------------------------
    def stream(self, request_id) -> Iterator[int]:
        """Per-request token generator: yields tokens as they are
        produced, driving the shared engine loop when it runs dry.  Ends
        when the request finishes (its ``finish_reason`` says why); an
        abort mid-stream simply ends the iteration.  The handle is
        resolved eagerly, so the stream stays valid after the engine
        retires the finished request from ``self.requests``.

        Closing the generator early (``.close()`` / ``GeneratorExit`` /
        garbage collection) aborts the underlying request and frees its
        KV blocks — an abandoned stream must not leak scheduled work."""
        req = self.requests[request_id]

        def _gen():
            cursor = 0
            try:
                while True:
                    while cursor < len(req.output_tokens):
                        yield req.output_tokens[cursor]
                        cursor += 1
                    if req.finished:
                        return
                    self.step()
            finally:
                # reached on GeneratorExit too: a consumer that walks away
                # mid-stream must not leave the request running in the
                # scheduler holding pool blocks
                if not req.finished:
                    self.abort_request(req.request_id)

        return _gen()

    # --- manual (predictor-compat) mode -------------------------------------
    def prefill_now(self, req: Request) -> int:
        """Admission-bypassing immediate prefill (LLMPredictor's
        ``add_request`` contract: the caller owns scheduling).  Raises
        :class:`PoolExhausted` when the prompt cannot be covered."""
        if not self.kv.can_allocate(req.request_id, req.num_computed_tokens):
            raise PoolExhausted(
                f"prompt of {req.num_computed_tokens} tokens needs "
                f"{self.kv.blocks_needed(req.request_id, req.num_computed_tokens)}"
                f" blocks, {self.kv.num_free} free")
        if not req.arrival_time:
            req.arrival_time = time.perf_counter()
        req.state = RequestState.RUNNING
        self.scheduler.running.append(req)
        self._prefill(req)
        return req.output_tokens[-1]

    def decode_ids(self, request_ids: Sequence[object]) -> Dict[object, int]:
        """Manual decode for explicit ids (LLMPredictor's ``step``): the
        caller picked the batch, so exhaustion here raises instead of
        preempting."""
        reqs = []
        for rid in request_ids:
            req = self.requests[rid]
            slot = self.kv.append_slot(rid)
            if slot is None:
                raise PoolExhausted(
                    f"no free block for decode slot of {rid!r}")
            req._slot = slot
            reqs.append(req)
        return self._decode(reqs)

    def release(self, request_id) -> None:
        """Drop a request and free its blocks (no finish bookkeeping —
        the predictor's ``free``).  The timeline IS closed: an active
        timeline with no owner would sit in the tracker forever."""
        req = self.requests.pop(request_id, None)
        if req is not None:
            self.scheduler.remove(req)
            self._lc(request_id, _lc.EV_FINISH, reason="released")
        self.cachestat.close_request(request_id)
        self.kv.free(request_id)

    # --- KV hand-off (ISSUE 20) ---------------------------------------------
    def export_kv_run(self, request_id):
        """Serialize ``request_id``'s computed prompt KV (its hashed
        leading blocks) as a hand-off run; ``None`` when nothing is
        transferable.  Pure read — the request keeps running here until
        :meth:`detach_request`."""
        from . import handoff

        return handoff.export_request_run(self, request_id)

    def export_prefix_chain(self, chain_hash, max_blocks=None):
        """Serialize the cached prefix chain addressed by its deepest
        digest (hot-prefix migration); ``None`` on a broken chain."""
        from . import handoff

        return handoff.export_prefix_run(self, chain_hash,
                                         max_blocks=max_blocks)

    def hot_prefixes(self, top_k=None):
        """Heat-table-hot cached prefixes with full chain digests
        (hot-prefix migration; see
        :meth:`~paddle_tpu.observability.cachestat.CacheStatTracker.hot_prefixes`).
        Engine-thread callers only."""
        return self.cachestat.hot_prefixes(top_k)

    def import_kv_run(self, run):
        """Admit a hand-off run into this engine's pool (verified,
        atomic; see :func:`~paddle_tpu.serving.handoff.import_run`).
        Returns fresh-block count, or ``None`` on capacity refusal."""
        from . import handoff

        return handoff.import_run(self, run)

    def detach_request(self, request_id) -> bool:
        """Drop a request WITHOUT finishing it — the donor half of a
        hand-off: the request migrates (same rid, open timeline) to
        another replica, so no finish event fires here.  Its blocks are
        freed; with the prefix cache on, the hashed prompt blocks park
        WARM in the reuse LRU — a failed migration that re-admits here
        revives them at zero recompute."""
        req = self.requests.pop(request_id, None)
        if req is None:
            return False
        self.scheduler.remove(req)
        self.cachestat.close_request(request_id)
        self.kv.free(request_id)
        return True
