"""Request objects for the serving engine.

One :class:`Request` is the unit the engine schedules: a prompt, sampling
parameters, a deterministic per-request RNG stream, and the request's
lifecycle state.  The state machine is the vLLM-style one the Ragged Paged
Attention serving shape implies (PAPERS.md):

    WAITING ──admit──> RUNNING ──(eos/length/abort)──> FINISHED
       ▲                  │
       └────preempt───────┘   (blocks freed; recompute re-enqueues at the
                               FRONT of the waiting queue so a preempted
                               request never starves behind new arrivals)

Preemption-with-recompute keeps ``output_tokens``: the recompute prefill
runs over ``prompt + output_tokens`` and decoding continues where it
stopped, so a preempted request produces token-identical output to an
uninterrupted run (greedy; for sampling, the per-request RNG has already
consumed exactly ``len(output_tokens)`` draws, so the stream also lines up).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from enum import Enum
from typing import List, Optional

import numpy as np


class RequestState(Enum):
    WAITING = "waiting"
    RUNNING = "running"
    PREEMPTED = "preempted"
    FINISHED = "finished"


class FinishReason(Enum):
    EOS = "eos"          # emitted the eos token
    LENGTH = "length"    # hit max_new_tokens
    ABORT = "abort"      # caller abort / unservable request
    TIMEOUT = "timeout"  # per-request deadline / drain deadline hit
    REPLICA_FAILED = "replica_failed"  # the owning fleet replica died
    # (or was quarantined) mid-flight and the request was not
    # re-dispatchable (tokens already streamed, retryable not set) —
    # the supervisor's honest verdict instead of a hang (ISSUE 12)


@dataclass
class SamplingParams:
    """Per-request decoding knobs (greedy when ``temperature == 0``)."""

    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0

    def sample(self, logits: np.ndarray, rng: np.random.Generator) -> int:
        """One token from a [vocab] logits row.  Greedy is RNG-free; a
        sampled draw consumes exactly one ``rng`` event, which is what
        makes recompute resume the stream at the right point.

        NOTE: since ISSUE 18 the engine samples on device (Gumbel-max
        keyed by ``(seed, draw_index)`` inside the traced step — see
        ``ops/sampling.py``); this host implementation stays as the
        reference semantics (the filtering pipeline matches: temperature
        scale -> top-k mask -> top-p nucleus mask -> draw)."""
        if self.temperature == 0.0:
            return int(logits.argmax(-1))
        x = logits.astype(np.float64) / max(self.temperature, 1e-6)
        if self.top_k > 0:
            kth = np.sort(x)[-min(self.top_k, x.shape[-1])]
            x = np.where(x < kth, -np.inf, x)
        p = np.exp(x - x.max())
        p /= p.sum()
        if 0.0 < self.top_p < 1.0:
            # nucleus filter: keep the smallest prob mass >= top_p.  The
            # max-prob token always survives (its cumsum entry is first),
            # so the filtered distribution is never empty.
            order = np.argsort(-p)
            csum = np.cumsum(p[order])
            cut = int(np.argmax(csum >= self.top_p))
            keep = np.zeros_like(p, dtype=bool)
            keep[order[:cut + 1]] = True
            p = np.where(keep, p, 0.0)
            p /= p.sum()
        return int(rng.choice(p.shape[-1], p=p))


_req_counter = itertools.count()


@dataclass
class Request:
    """One generation request, engine-owned after :meth:`EngineCore.add_request`."""

    prompt_ids: List[int]
    sampling: SamplingParams = field(default_factory=SamplingParams)
    request_id: object = None
    trace_id: Optional[str] = None   # rides every span/instant the engine
                                     # records for this request, so one
                                     # request's lifecycle is a filter over
                                     # the exported chrome trace
    priority: int = 0            # lower = more important; ties break by
                                 # arrival order (newest preempted first)
    state: RequestState = RequestState.WAITING
    finish_reason: Optional[FinishReason] = None
    output_tokens: List[int] = field(default_factory=list)
    num_preemptions: int = 0
    error: Optional[str] = None
    # prefix-cache accounting (scheduler-owned): tokens restored for free
    # from the prefix cache at the LAST admission (fork, zero recompute).
    # Reset on preemption (blocks freed), re-filled on re-admission.
    # Prefill *progress* has no mirror here — kv.seq_len(request_id) is
    # the single source of truth.
    num_cached_tokens: int = 0
    # client-facing cache attribution (ISSUE 13): cached tokens at the
    # FIRST admission — output is empty there, so this is always a count
    # of PROMPT tokens served for free, the number the completions
    # ``usage.prompt_cached_tokens`` field reports.  num_cached_tokens
    # above tracks the LAST admission and resets on preemption.
    prompt_cached_tokens: Optional[int] = None
    # externally-computed leading-block chain hashes (ISSUE 6): the fleet
    # router hashes the prompt's leading full blocks once for
    # prefix-affinity placement and hands them down, so the scheduler's
    # admission probe (kv.match_prefix) does not re-hash those blocks.
    # None = the probe hashes everything itself (single-engine path).
    prefix_hashes: Optional[List[bytes]] = None
    # per-request latency objective (ISSUE 8): when set, the engine
    # scores the finished request against it — serving_slo_total /
    # serving_slo_good_total are the fleet's goodput pair.  None = the
    # request carries no objective and is not scored.
    slo_ms: Optional[float] = None
    # engine-stamped timing (perf_counter seconds)
    arrival_time: float = 0.0
    prefill_start_time: Optional[float] = None  # first prefill chunk ran
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None

    def __post_init__(self):
        self.arrival_seq = next(_req_counter)
        if self.request_id is None:
            self.request_id = self.arrival_seq
        if self.trace_id is None:
            self.trace_id = str(self.request_id)
        self.prompt_ids = [int(t) for t in np.asarray(self.prompt_ids).reshape(-1)]
        self._rng = np.random.default_rng(self.sampling.seed)
        self._chunk_tokens = None  # this step's planned prefill chunk width
                                   # (scheduler-stamped, engine-consumed)
        self._probe_blocks = None  # memoized prefix-cache match for this
        self._probe_epoch = -1     # prompt, valid while kv.cache_epoch is
                                   # unchanged — a head-of-queue request
                                   # blocked on capacity is not re-hashed
                                   # every engine step

    # --- views --------------------------------------------------------------
    @property
    def finished(self) -> bool:
        return self.state == RequestState.FINISHED

    @property
    def num_computed_tokens(self) -> int:
        """Tokens whose KV must live in the pool while RUNNING: the prompt
        plus every generated token except the newest (whose KV is written
        by the decode step that consumes it)."""
        return len(self.prompt_ids) + len(self.output_tokens)

    @property
    def last_token(self) -> int:
        return (self.output_tokens[-1] if self.output_tokens
                else self.prompt_ids[-1])

    def append_token(self, tok: int) -> None:
        self.output_tokens.append(int(tok))

    def hit_eos(self, tok: int) -> bool:
        eos = self.sampling.eos_token_id
        return eos is not None and int(tok) == int(eos)

    @property
    def preempt_key(self):
        """Victim ordering: highest (priority, arrival_seq) goes first —
        least important, most recently arrived."""
        return (self.priority, self.arrival_seq)
