"""``paddle.sparse.nn`` layer classes (``python/paddle/sparse/nn/layer/``)
over :mod:`paddle_tpu.sparse.nn.functional`."""

from __future__ import annotations

import numpy as np

from ...core.tensor import Parameter
from ...nn import initializer as init_mod
from ...nn.layers import Layer
from .. import SparseCooTensor
from . import functional  # noqa: F401
from .functional import attention  # noqa: F401


class ReLU(Layer):
    def forward(self, x):
        return functional.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return functional.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return functional.leaky_relu(x, self._slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return functional.softmax(x, self._axis)


class _ConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        ks = ((kernel_size,) * 3 if isinstance(kernel_size, int)
              else tuple(kernel_size))
        self._cfg = dict(stride=stride, padding=padding, dilation=dilation,
                         groups=groups, data_format=data_format)
        w_init = init_mod.XavierUniform()
        self.weight = Parameter(
            w_init(ks + (in_channels // groups, out_channels), np.float32))
        self.bias = (Parameter(np.zeros(out_channels, np.float32))
                     if bias_attr is not False else None)


class Conv3D(_ConvBase):
    """(``sparse/nn/layer/conv.py`` Conv3D)."""

    def forward(self, x):
        return functional.conv3d(x, self.weight, self.bias, **self._cfg)


class SubmConv3D(_ConvBase):
    """(``sparse/nn/layer/conv.py`` SubmConv3D)."""

    def forward(self, x):
        return functional.subm_conv3d(x, self.weight, self.bias, **self._cfg)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._cfg = dict(kernel_size=kernel_size, stride=stride,
                         padding=padding, ceil_mode=ceil_mode,
                         data_format=data_format)

    def forward(self, x):
        return functional.max_pool3d(x, **self._cfg)


class BatchNorm(Layer):
    """Per-channel batchnorm over ACTIVE SITES only
    (``sparse/nn/layer/norm.py`` BatchNorm — the reference normalizes the
    nnz value rows, not the dense grid)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum, self._eps = momentum, epsilon
        self.weight = Parameter(np.ones(num_features, np.float32))
        self.bias = Parameter(np.zeros(num_features, np.float32))
        from ...core.tensor import to_tensor

        self.register_buffer("_mean", to_tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", to_tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse

        assert isinstance(x, SparseCooTensor)
        v = x.bcoo.data  # (nnz, C)
        if self.training:
            # under jit, conv/pool outputs carry zero-valued padding lanes at
            # OOB sites (functional.py padded-lane contract) — mask them out
            # of the statistics or clustered clouds skew toward zero
            rows = functional.valid_site_rows(
                x.bcoo.indices, x.bcoo.shape[:x.bcoo.indices.shape[-1]])
            n = jnp.maximum(jnp.sum(rows), 1)
            vm = jnp.where(rows[:, None], v, 0.0)
            mean = jnp.sum(vm, axis=0) / n
            var = jnp.sum(
                jnp.where(rows[:, None], (v - mean) ** 2, 0.0), axis=0) / n
            m = self._momentum
            self._mean._value = m * self._mean._value + (1 - m) * mean
            self._variance._value = m * self._variance._value + (1 - m) * var
        else:
            mean, var = self._mean._value, self._variance._value
        out = ((v - mean) / jnp.sqrt(var + self._eps) * self.weight._value
               + self.bias._value)
        return SparseCooTensor(
            jsparse.BCOO((out, x.bcoo.indices), shape=x.bcoo.shape))


SyncBatchNorm = BatchNorm  # GSPMD batch stats are already global under jit
