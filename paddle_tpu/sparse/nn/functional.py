"""``paddle.sparse.nn.functional`` (N9 capability): sparse attention,
sparse conv3d, activations and pooling over sparse layouts.

Reference counterparts: ``python/paddle/sparse/nn/functional/*`` and the
CUDA kernels in ``paddle/phi/kernels/sparse/`` (conv3d gather-scatter,
``fluid/operators/sparse_attention_op.cu``).  TPU-first notes per op below:
attention is genuinely sparse (segment softmax over the CSR pattern,
O(nnz·d) compute); conv3d lowers to a dense ``lax.conv_general_dilated``
over the bounding grid — on TPU the MXU conv on a dense block IS the fast
path; the sparse layout is preserved at the boundary (submanifold output
keeps the input's active sites, as in the reference's SubmConv3D).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, to_tensor
from .. import SparseCooTensor, SparseCsrTensor, _value_map, sparse_coo_tensor


def relu(x, name=None):
    return _value_map(x, jax.nn.relu)


def relu6(x, name=None):
    return _value_map(x, lambda v: jnp.clip(v, 0.0, 6.0))


def leaky_relu(x, negative_slope=0.01, name=None):
    return _value_map(x, lambda v: jax.nn.leaky_relu(v, negative_slope))


def _segment_softmax(v, rows, n_rows):
    """Numerically-stable softmax over stored values grouped by segment id."""
    mx = jax.ops.segment_max(v, rows, num_segments=n_rows)
    e = jnp.exp(v - mx[rows])
    z = jax.ops.segment_sum(e, rows, num_segments=n_rows)
    return e / z[rows]


def softmax(x, axis=-1, name=None):
    """Sparse softmax: per-row over stored values only
    (``sparse/nn/functional/activation.py`` softmax; axis must be the last,
    CSR row semantics).  Batched [B, L, L] CSR gets a distinct segment id
    per (batch, row) pair so batches never mix."""
    from jax.experimental import sparse as jsparse

    if isinstance(x, SparseCsrTensor):
        indptr = np.asarray(x.bcsr.indptr)
        ip = indptr if indptr.ndim == 2 else indptr[None]
        B, Lp1 = ip.shape
        n_rows = B * (Lp1 - 1)
        if x.bcsr.data.ndim == 2:
            # batched BCSR stores a fixed nnz_max lane per batch; ragged
            # batches carry pad entries past indptr[b][-1] — give pads a
            # dummy segment id so they never enter any real row's softmax
            width = x.bcsr.data.shape[1]
            per_batch = []
            for b in range(B):
                rb = np.full(width, n_rows, np.int32)  # dummy segment
                real = np.repeat(np.arange(Lp1 - 1), np.diff(ip[b]))
                rb[: real.size] = real + b * (Lp1 - 1)
                per_batch.append(rb)
            rows = jnp.asarray(np.concatenate(per_batch))
        else:
            rows = jnp.asarray(np.repeat(
                np.arange(Lp1 - 1), np.diff(ip[0])).astype(np.int32))
        out = _segment_softmax(
            x.bcsr.data.reshape(-1), rows, n_rows + 1)
        return SparseCsrTensor(jsparse.BCSR(
            (out.reshape(x.bcsr.data.shape), x.bcsr.indices, x.bcsr.indptr),
            shape=x.bcsr.shape), stop_gradient=x.stop_gradient)
    if isinstance(x, SparseCooTensor):
        idx = np.asarray(x.bcoo.indices)
        rows = jnp.asarray(idx[:, 0].astype(np.int32))
        out = _segment_softmax(x.bcoo.data, rows, x.bcoo.shape[0])
        return SparseCooTensor(jsparse.BCOO(
            (out, x.bcoo.indices), shape=x.bcoo.shape),
            stop_gradient=x.stop_gradient)
    return Tensor(jax.nn.softmax(x._value, axis=axis))


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse attention over a CSR connectivity pattern
    (``sparse/nn/functional/transformer.py`` attention).

    query/key/value: (B, H, L, D) dense; ``sparse_mask`` a SparseCsrTensor
    of shape (B*H, L, L) — batched CSR like the reference — or (L, L)
    shared across heads.  Scores are computed ONLY at nnz positions
    (O(nnz·D)), softmax is a segment-softmax per query row, and the output
    is the per-row weighted sum of gathered V rows."""
    q = query._value if isinstance(query, Tensor) else jnp.asarray(query)
    k = key._value if isinstance(key, Tensor) else jnp.asarray(key)
    v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
    B, H, L, D = q.shape
    scale = 1.0 / np.sqrt(D)

    if isinstance(sparse_mask, SparseCsrTensor):
        bcsr = sparse_mask.bcsr
        if len(bcsr.shape) == 2:
            indptr = np.broadcast_to(
                np.asarray(bcsr.indptr), (B * H, L + 1))
            cols = np.broadcast_to(
                np.asarray(bcsr.indices), (B * H, np.asarray(bcsr.indices).shape[-1]))
        else:
            indptr = np.asarray(bcsr.indptr).reshape(B * H, L + 1)
            cols = np.asarray(bcsr.indices).reshape(B * H, -1)
    else:
        raise TypeError("sparse_mask must be a SparseCsrTensor")

    qf = q.reshape(B * H, L, D)
    kf = k.reshape(B * H, L, D)
    vf = v.reshape(B * H, L, D)
    kpm = (key_padding_mask._value if isinstance(key_padding_mask, Tensor)
           else key_padding_mask)
    am = attn_mask._value if isinstance(attn_mask, Tensor) else attn_mask
    if am is not None and (am.ndim != 2 or am.shape != (L, L)):
        raise ValueError(
            f"attn_mask must be 2-D [seq_len, seq_len]=({L}, {L}) shared "
            f"across batch/heads (got shape {tuple(am.shape)})")

    outs = []
    for bh in range(B * H):
        rows = jnp.asarray(np.repeat(
            np.arange(L), np.diff(indptr[bh])).astype(np.int32))
        cc = jnp.asarray(cols[bh].astype(np.int32))
        s = jnp.einsum("nd,nd->n", qf[bh][rows], kf[bh][cc]) * scale
        # Reference kernel (fluid/operators/sparse_attention_op.cu) masks a
        # score where the mask value EQUALS 0 (paddle convention: 0 = masked
        # out, nonzero = attend); attn_mask is a single [L, L] tensor shared
        # across batch and heads.
        if kpm is not None:
            b = bh // H
            s = jnp.where(kpm[b][cc] == 0, jnp.float32(-1e9), s)
        if am is not None:
            s = jnp.where(am[rows, cc] == 0, jnp.float32(-1e9), s)
        mx = jax.ops.segment_max(s, rows, num_segments=L)
        e = jnp.exp(s - mx[rows])
        z = jax.ops.segment_sum(e, rows, num_segments=L)
        p = e / jnp.maximum(z[rows], 1e-9)
        o = jax.ops.segment_sum(p[:, None] * vf[bh][cc], rows, num_segments=L)
        outs.append(o)
    return Tensor(jnp.stack(outs).reshape(B, H, L, D))


def _dense_conv3d(dense, weight, bias, stride, padding, dilation, groups):
    """NDHWC conv over the dense grid via lax (MXU path)."""
    dn = jax.lax.conv_dimension_numbers(
        dense.shape, weight.shape, ("NDHWC", "DHWIO", "NDHWC"))
    if isinstance(padding, int):
        padding = [(padding, padding)] * 3
    elif isinstance(padding, (list, tuple)) and padding and isinstance(
            padding[0], int):
        padding = [(p, p) for p in padding]
    out = jax.lax.conv_general_dilated(
        dense, weight,
        window_strides=(stride,) * 3 if isinstance(stride, int) else tuple(stride),
        padding=padding,
        rhs_dilation=(dilation,) * 3 if isinstance(dilation, int) else tuple(dilation),
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        out = out + bias
    return out


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse conv3d (``sparse/nn/functional/conv.py``): SparseCooTensor in
    (N,D,H,W,C) → SparseCooTensor out; dense MXU conv over the grid, output
    re-sparsified at nonzero sites."""
    w = weight._value if isinstance(weight, Tensor) else jnp.asarray(weight)
    b = bias._value if isinstance(bias, Tensor) else (
        jnp.asarray(bias) if bias is not None else None)
    dense = x.to_dense()._value if isinstance(x, SparseCooTensor) else x._value
    out = _dense_conv3d(dense, w, b, stride, padding, dilation, groups)
    arr = np.asarray(out)
    # COO over (N,D,H,W) sites with dense C-vector values per site
    idx = np.argwhere(np.abs(arr).sum(-1) > 0)
    vals = out[tuple(idx.T)]
    from jax.experimental import sparse as jsparse

    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.astype(np.int32))),
                        shape=out.shape)
    return SparseCooTensor(bcoo)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold conv3d: output restricted to the INPUT's active sites
    (``sparse/nn/functional/conv.py`` subm_conv3d — prevents active-site
    dilation across layers, the signature property of submanifold sparse
    CNNs)."""
    w = weight._value if isinstance(weight, Tensor) else jnp.asarray(weight)
    b = bias._value if isinstance(bias, Tensor) else (
        jnp.asarray(bias) if bias is not None else None)
    assert isinstance(x, SparseCooTensor), "subm_conv3d needs a sparse input"
    dense = x.to_dense()._value
    out = _dense_conv3d(dense, w, b, stride, padding, dilation, groups)
    in_sites = np.asarray(x.bcoo.indices)[:, :4]
    sites = np.unique(in_sites, axis=0)
    vals = out[tuple(sites.T)]
    from jax.experimental import sparse as jsparse

    bcoo = jsparse.BCOO((vals, jnp.asarray(sites.astype(np.int32))),
                        shape=out.shape)
    return SparseCooTensor(bcoo)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """(``sparse/nn/functional/pooling.py``) max pool over the dense grid,
    re-sparsified."""
    dense = x.to_dense()._value if isinstance(x, SparseCooTensor) else x._value
    ks = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
    st = ks if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    out = jax.lax.reduce_window(
        dense, -jnp.inf, jax.lax.max,
        window_dimensions=(1,) + ks + (1,),
        window_strides=(1,) + st + (1,),
        padding=((0, 0),) + tuple((p, p) for p in pd) + ((0, 0),))
    arr = np.asarray(out)
    idx = np.argwhere(np.abs(arr).sum(-1) > 0)
    vals = out[tuple(idx.T)]
    from jax.experimental import sparse as jsparse

    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.astype(np.int32))),
                        shape=out.shape)
    return SparseCooTensor(bcoo)
