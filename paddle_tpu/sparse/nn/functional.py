"""``paddle.sparse.nn.functional`` (N9 capability): sparse attention,
sparse conv3d, activations and pooling over sparse layouts.

Reference counterparts: ``python/paddle/sparse/nn/functional/*`` and the
CUDA kernels in ``paddle/phi/kernels/sparse/`` (conv3d gather-scatter,
``fluid/operators/sparse_attention_op.cu``).  TPU-first notes per op below:
attention is genuinely sparse (segment softmax over the CSR pattern,
O(nnz·d) compute); conv3d/subm_conv3d/max_pool3d are O(nnz·K)
gather-GEMM-scatter over active sites — the reference's rulebook design
(``conv_kernel.cu``) rebuilt as jnp sort/searchsorted site lookups (static
shapes, jit-traceable) with all K kernel-offset GEMMs batched into one
einsum for the MXU.  Compute and memory never scale with the dense grid
volume.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, to_tensor
from .. import SparseCooTensor, SparseCsrTensor, _value_map, sparse_coo_tensor


def relu(x, name=None):
    return _value_map(x, jax.nn.relu)


def relu6(x, name=None):
    return _value_map(x, lambda v: jnp.clip(v, 0.0, 6.0))


def leaky_relu(x, negative_slope=0.01, name=None):
    return _value_map(x, lambda v: jax.nn.leaky_relu(v, negative_slope))


def _segment_softmax(v, rows, n_rows):
    """Numerically-stable softmax over stored values grouped by segment id."""
    mx = jax.ops.segment_max(v, rows, num_segments=n_rows)
    e = jnp.exp(v - mx[rows])
    z = jax.ops.segment_sum(e, rows, num_segments=n_rows)
    return e / z[rows]


def softmax(x, axis=-1, name=None):
    """Sparse softmax: per-row over stored values only
    (``sparse/nn/functional/activation.py`` softmax; axis must be the last,
    CSR row semantics).  Batched [B, L, L] CSR gets a distinct segment id
    per (batch, row) pair so batches never mix."""
    from jax.experimental import sparse as jsparse

    if isinstance(x, SparseCsrTensor):
        indptr = np.asarray(x.bcsr.indptr)
        ip = indptr if indptr.ndim == 2 else indptr[None]
        B, Lp1 = ip.shape
        n_rows = B * (Lp1 - 1)
        if x.bcsr.data.ndim == 2:
            # batched BCSR stores a fixed nnz_max lane per batch; ragged
            # batches carry pad entries past indptr[b][-1] — give pads a
            # dummy segment id so they never enter any real row's softmax
            width = x.bcsr.data.shape[1]
            per_batch = []
            for b in range(B):
                rb = np.full(width, n_rows, np.int32)  # dummy segment
                real = np.repeat(np.arange(Lp1 - 1), np.diff(ip[b]))
                rb[: real.size] = real + b * (Lp1 - 1)
                per_batch.append(rb)
            rows = jnp.asarray(np.concatenate(per_batch))
        else:
            rows = jnp.asarray(np.repeat(
                np.arange(Lp1 - 1), np.diff(ip[0])).astype(np.int32))
        out = _segment_softmax(
            x.bcsr.data.reshape(-1), rows, n_rows + 1)
        return SparseCsrTensor(jsparse.BCSR(
            (out.reshape(x.bcsr.data.shape), x.bcsr.indices, x.bcsr.indptr),
            shape=x.bcsr.shape), stop_gradient=x.stop_gradient)
    if isinstance(x, SparseCooTensor):
        idx = np.asarray(x.bcoo.indices)
        rows = jnp.asarray(idx[:, 0].astype(np.int32))
        out = _segment_softmax(x.bcoo.data, rows, x.bcoo.shape[0])
        return SparseCooTensor(jsparse.BCOO(
            (out, x.bcoo.indices), shape=x.bcoo.shape),
            stop_gradient=x.stop_gradient)
    return Tensor(jax.nn.softmax(x._value, axis=axis))


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse attention over a CSR connectivity pattern
    (``sparse/nn/functional/transformer.py`` attention).

    query/key/value: (B, H, L, D) dense; ``sparse_mask`` a SparseCsrTensor
    of shape (B*H, L, L) — batched CSR like the reference — or (L, L)
    shared across heads.  Scores are computed ONLY at nnz positions
    (O(nnz·D)), softmax is a segment-softmax per query row, and the output
    is the per-row weighted sum of gathered V rows."""
    q = query._value if isinstance(query, Tensor) else jnp.asarray(query)
    k = key._value if isinstance(key, Tensor) else jnp.asarray(key)
    v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
    B, H, L, D = q.shape
    scale = 1.0 / np.sqrt(D)

    if isinstance(sparse_mask, SparseCsrTensor):
        bcsr = sparse_mask.bcsr
        if len(bcsr.shape) == 2:
            indptr = np.broadcast_to(
                np.asarray(bcsr.indptr), (B * H, L + 1))
            cols = np.broadcast_to(
                np.asarray(bcsr.indices), (B * H, np.asarray(bcsr.indices).shape[-1]))
        else:
            indptr = np.asarray(bcsr.indptr).reshape(B * H, L + 1)
            cols = np.asarray(bcsr.indices).reshape(B * H, -1)
    else:
        raise TypeError("sparse_mask must be a SparseCsrTensor")

    qf = q.reshape(B * H, L, D)
    kf = k.reshape(B * H, L, D)
    vf = v.reshape(B * H, L, D)
    kpm = (key_padding_mask._value if isinstance(key_padding_mask, Tensor)
           else key_padding_mask)
    am = attn_mask._value if isinstance(attn_mask, Tensor) else attn_mask
    if am is not None and (am.ndim != 2 or am.shape != (L, L)):
        raise ValueError(
            f"attn_mask must be 2-D [seq_len, seq_len]=({L}, {L}) shared "
            f"across batch/heads (got shape {tuple(am.shape)})")

    outs = []
    for bh in range(B * H):
        rows = jnp.asarray(np.repeat(
            np.arange(L), np.diff(indptr[bh])).astype(np.int32))
        cc = jnp.asarray(cols[bh].astype(np.int32))
        s = jnp.einsum("nd,nd->n", qf[bh][rows], kf[bh][cc]) * scale
        # Reference kernel (fluid/operators/sparse_attention_op.cu) masks a
        # score where the mask value EQUALS 0 (paddle convention: 0 = masked
        # out, nonzero = attend); attn_mask is a single [L, L] tensor shared
        # across batch and heads.
        if kpm is not None:
            b = bh // H
            s = jnp.where(kpm[b][cc] == 0, jnp.float32(-1e9), s)
        if am is not None:
            s = jnp.where(am[rows, cc] == 0, jnp.float32(-1e9), s)
        mx = jax.ops.segment_max(s, rows, num_segments=L)
        e = jnp.exp(s - mx[rows])
        z = jax.ops.segment_sum(e, rows, num_segments=L)
        p = e / jnp.maximum(z[rows], 1e-9)
        o = jax.ops.segment_sum(p[:, None] * vf[bh][cc], rows, num_segments=L)
        outs.append(o)
    return Tensor(jnp.stack(outs).reshape(B, H, L, D))



# ---------------------------------------------------------------------------
# Sparse conv3d / pooling: O(nnz) gather-GEMM-scatter over active sites
# (the reference's rulebook design, ``phi/kernels/sparse/gpu/conv_kernel.cu``,
# rebuilt TPU-first: the rulebook is jnp sort/searchsorted over linearized
# site keys — static shapes, fully jit-traceable — and the per-kernel-offset
# GEMMs are batched into ONE einsum so the MXU sees a single large
# contraction.  Compute and memory scale with nnz·K, never with the dense
# grid volume.)
#
# Padded-lane contract: under jit, output nnz lanes are static (input nnz
# for subm, nnz·K for conv/pool), so lanes that don't correspond to a real
# output site carry OUT-OF-RANGE indices (BCOO's padding convention — they
# are dropped by ``to_dense`` and can never match a chained rulebook
# lookup) and zero values.  Row-wise consumers must mask by
# :func:`valid_site_rows` (sparse BatchNorm does).  Eagerly the lanes are
# compacted away and nnz is exact.
# ---------------------------------------------------------------------------

_INT32_MAX = 2**31 - 1


def _triple(v):
    return (v,) * 3 if isinstance(v, int) else tuple(v)


def _key_dtype(total: int):
    """Site keys must cover the linearized grid volume."""
    if total <= _INT32_MAX:
        return jnp.int32
    if jax.config.jax_enable_x64:
        return jnp.int64
    raise ValueError(
        f"sparse conv/pool site-key space ({total} sites) exceeds int32 and "
        "jax_enable_x64 is off — enable it (jax.config.update("
        "'jax_enable_x64', True)) to use grids this large")


def _site_keys(sites, dims, dtype):
    """Linearize (n, d, h, w) int sites into sortable scalar keys."""
    N, D, H, W = dims
    s = sites.astype(dtype)
    return ((s[..., 0] * D + s[..., 1]) * H + s[..., 2]) * W + s[..., 3]


def _is_traced(*vals) -> bool:
    return any(isinstance(v, jax.core.Tracer) for v in vals)


def valid_site_rows(indices, dims):
    """Mask of stored rows whose site is in range (False = padding lane)."""
    return jnp.all(indices < jnp.asarray(dims, indices.dtype), axis=-1)


def _coalesce(bcoo, traced: bool):
    """Sum duplicate indices (the replaced dense path summed them via
    ``to_dense``; the rulebook lookup needs one row per site).  Under jit
    the nse stays static (padded); eagerly it compacts to the true nse."""
    from jax.experimental import sparse as jsparse

    if traced:
        return jsparse.bcoo_sum_duplicates(bcoo, nse=bcoo.nse)
    return jsparse.bcoo_sum_duplicates(bcoo)


def _prep_conv(x, weight, bias, stride, padding, dilation, groups):
    w = weight._value if isinstance(weight, Tensor) else jnp.asarray(weight)
    b = bias._value if isinstance(bias, Tensor) else (
        jnp.asarray(bias) if bias is not None else None)
    assert isinstance(x, SparseCooTensor), "sparse conv3d needs a sparse input"
    kd, kh, kw, cin_g, cout = w.shape
    cin = x.bcoo.data.shape[-1]
    if cin != cin_g * groups or cout % groups:
        raise ValueError(
            f"conv3d channel mismatch: input C={cin}, weight expects "
            f"{cin_g}×{groups} in and {cout} out (groups={groups})")
    bcoo = _coalesce(x.bcoo, _is_traced(x.bcoo.indices, x.bcoo.data, w))
    # static kernel-offset table (the rulebook's K axis)
    dil = _triple(dilation)
    offs = np.array([(i * dil[0], j * dil[1], k * dil[2])
                     for i in range(kd) for j in range(kh) for k in range(kw)],
                    np.int32)
    return (bcoo.indices, bcoo.data, w.reshape(-1, cin_g, cout), b, groups,
            _triple(stride), _triple(padding), offs)


def _grouped_matmul(gathered, wk, groups):
    """All K kernel-offset GEMMs as one MXU contraction, grouped conv aware.

    gathered: (K, nnz, Cin) neighbor features; wk: (K, Cin/g, Cout).
    Output channels are group-major (standard conv groups semantics)."""
    K, nnz, cin = gathered.shape
    cout = wk.shape[-1]
    g = groups
    gg = gathered.reshape(K, nnz, g, cin // g)
    wg = wk.reshape(K, cin // g, g, cout // g)
    return jnp.einsum("kngc,kcgo->ngo", gg, wg).reshape(nnz, cout)


def _gather_neighbors(in_sites, feats, query_sites, valid, dims, kdtype):
    """For each (K, M, 4) query site, the input feature row at that site (0
    where absent/invalid): sort + searchsorted over linearized keys — the
    jnp rulebook lookup.  Requires coalesced input (one row per site);
    padding lanes carry OOB sites whose keys can never match a query."""
    keys = _site_keys(in_sites, dims, kdtype)
    order = jnp.argsort(keys)
    sorted_keys = keys[order]
    qkeys = _site_keys(query_sites, dims, kdtype)
    pos = jnp.clip(jnp.searchsorted(sorted_keys, qkeys), 0, keys.shape[0] - 1)
    found = valid & (sorted_keys[pos] == qkeys)
    gathered = jnp.take(feats, order[pos.reshape(-1)], axis=0)
    gathered = gathered.reshape(*qkeys.shape, feats.shape[-1])
    return jnp.where(found[..., None], gathered, 0.0)


def _candidate_outputs(in_sites, offs, pd, st, out_sp, odims, kdtype):
    """Candidate output site keys for every (input site, kernel offset):
    o = (site + pad - δ) / stride where divisible and in range; invalid
    candidates get the sentinel key ``total`` (sorts last)."""
    num = in_sites[None, :, 1:4] + jnp.asarray(
        np.array(pd, np.int32) - offs)[:, None, :]             # (K, nnz, 3)
    div_ok = jnp.all(num % jnp.asarray(st, jnp.int32) == 0, axis=-1)
    osp = num // jnp.asarray(st, jnp.int32)
    in_range = jnp.all(
        (osp >= 0) & (osp < jnp.asarray(out_sp, jnp.int32)), axis=-1)
    valid = div_ok & in_range
    batch = jnp.broadcast_to(in_sites[None, :, :1], osp[..., :1].shape)
    cand_sites = jnp.concatenate([batch, osp], axis=-1)        # (K, nnz, 4)
    total = int(np.prod(odims))
    keys = jnp.where(valid, _site_keys(cand_sites, odims, kdtype),
                     jnp.asarray(total, kdtype))
    return keys, total


def _scatter_to_sites(cand_keys, flat_rows, odims, total, reduce, kdtype):
    """Combine candidate rows landing on the same output site (the
    rulebook's scatter): sort by key, segment-reduce, decode keys back to
    sites.  Returns (vals, out_sites, real) with padded lanes at OOB
    sites."""
    n_lanes = flat_rows.shape[0]
    flat_keys = cand_keys.reshape(-1)
    order = jnp.argsort(flat_keys)
    skeys = flat_keys[order]
    srows = flat_rows[order]
    head = jnp.concatenate([jnp.ones((1,), bool), skeys[1:] != skeys[:-1]])
    seg = jnp.cumsum(head) - 1
    vals = reduce(srows, seg, n_lanes)
    seg_keys = jax.ops.segment_min(
        jnp.where(skeys < total, skeys, total), seg, num_segments=n_lanes)
    real = seg_keys < total
    sk = jnp.where(real, seg_keys, 0)
    No, Do, Ho, Wo = odims
    out_sites = jnp.stack(
        [sk // (Wo * Ho * Do), (sk // (Wo * Ho)) % Do,
         (sk // Wo) % Ho, sk % Wo], axis=-1).astype(jnp.int32)
    out_sites = jnp.where(real[:, None], out_sites,
                          jnp.asarray(odims, jnp.int32))
    vals = jnp.where(real[:, None], vals, 0.0)
    return vals, out_sites, real


def _maybe_compact(vals, out_sites, real, traced):
    if traced:
        return vals, out_sites
    realn = np.asarray(real)
    return (jnp.asarray(np.asarray(vals)[realn]),
            jnp.asarray(np.asarray(out_sites)[realn]))


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold conv3d: output restricted to the INPUT's active sites
    (``sparse/nn/functional/conv.py`` subm_conv3d — prevents active-site
    dilation across layers, the signature property of submanifold sparse
    CNNs).  O(nnz·K): for each active site and kernel offset, the neighbor
    feature is looked up in the site table, and all K GEMMs run as one
    batched (grouped) einsum."""
    in_sites, feats, wk, b, g, st, pd, offs = _prep_conv(
        x, weight, bias, stride, padding, dilation, groups)
    if st != (1, 1, 1):
        raise ValueError("subm_conv3d requires stride 1 "
                         "(active sites must be preserved)")
    dims = x.shape[:4]
    kdtype = _key_dtype(int(np.prod(dims)))
    # neighbor of output site o at kernel offset δ: o + δ - padding
    shift = jnp.asarray(offs - np.array(pd, np.int32))        # (K, 3)
    qsp = in_sites[None, :, 1:4] + shift[:, None, :]          # (K, nnz, 3)
    valid = jnp.all((qsp >= 0) & (qsp < jnp.asarray(dims[1:], jnp.int32)),
                    axis=-1)
    query = jnp.concatenate(
        [jnp.broadcast_to(in_sites[None, :, :1], qsp[..., :1].shape), qsp],
        axis=-1)
    gathered = _gather_neighbors(in_sites, feats, query, valid, dims, kdtype)
    out = _grouped_matmul(gathered, wk, g)
    rows = valid_site_rows(in_sites, dims)  # coalesce padding lanes
    if b is not None:
        out = out + b
    out = jnp.where(rows[:, None], out, 0.0)
    from jax.experimental import sparse as jsparse

    bcoo = jsparse.BCOO((out, in_sites),
                        shape=tuple(dims) + (wk.shape[-1],))
    return SparseCooTensor(bcoo)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse conv3d (``sparse/nn/functional/conv.py``): SparseCooTensor in
    (N,D,H,W,C) → SparseCooTensor out over the sites REACHED by any active
    input (the rulebook's output set).  O(nnz·K): each (input site, kernel
    offset) pair contributes ``feats[i] @ W[k]`` to one candidate output
    site; candidates are combined by a sort + segment-sum scatter.  See the
    module-level padded-lane contract for jit behavior."""
    in_sites, feats, wk, b, g, st, pd, offs = _prep_conv(
        x, weight, bias, stride, padding, dilation, groups)
    dims = x.shape[:4]
    out_sp = tuple(
        (dims[1 + i] + 2 * pd[i] - (int(offs[:, i].max()) + 1)) // st[i] + 1
        for i in range(3))
    odims = (dims[0],) + out_sp
    kdtype = _key_dtype(int(np.prod(odims)))
    K = offs.shape[0]

    cand_keys, total = _candidate_outputs(
        in_sites, offs, pd, st, out_sp, odims, kdtype)
    # contribution of each candidate: feats[i] @ W[k] (grouped, one einsum)
    nnz = feats.shape[0]
    contrib = _conv_contrib(feats, wk, g, K)
    traced = _is_traced(in_sites, feats, wk)
    vals, out_sites, real = _scatter_to_sites(
        cand_keys, contrib.reshape(K * nnz, -1), odims, total,
        lambda r, s, n: jax.ops.segment_sum(r, s, num_segments=n), kdtype)
    if b is not None:
        vals = jnp.where(real[:, None], vals + b, vals)
    vals, out_sites = _maybe_compact(vals, out_sites, real, traced)
    from jax.experimental import sparse as jsparse

    bcoo = jsparse.BCOO((vals, out_sites),
                        shape=odims + (wk.shape[-1],))
    return SparseCooTensor(bcoo)


def _conv_contrib(feats, wk, groups, K):
    """(K, nnz, Cout) per-candidate contributions, grouped-conv aware."""
    nnz, cin = feats.shape
    cout = wk.shape[-1]
    g = groups
    fg = feats.reshape(nnz, g, cin // g)
    wg = wk.reshape(K, cin // g, g, cout // g)
    return jnp.einsum("ngc,kcgo->kngo", fg, wg).reshape(K, nnz, cout)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """(``sparse/nn/functional/pooling.py``) sparse max pool: per output
    site, the max over the PRESENT input sites in its window (the
    reference's rulebook pool, ``pool_kernel.cu``) — O(nnz·K), traced.  See
    the module-level padded-lane contract for jit behavior."""
    assert isinstance(x, SparseCooTensor), "sparse max_pool3d needs sparse input"
    ks = _triple(kernel_size)
    st = ks if stride is None else _triple(stride)
    pd = _triple(padding)
    traced = _is_traced(x.bcoo.indices, x.bcoo.data)
    bcoo = _coalesce(x.bcoo, traced)
    in_sites, feats = bcoo.indices, bcoo.data
    dims = x.shape[:4]
    offs = np.array([(i, j, k) for i in range(ks[0])
                     for j in range(ks[1]) for k in range(ks[2])], np.int32)
    out_sp = tuple((dims[1 + i] + 2 * pd[i] - ks[i]) // st[i] + 1
                   for i in range(3))
    odims = (dims[0],) + out_sp
    kdtype = _key_dtype(int(np.prod(odims)))
    K, nnz = offs.shape[0], feats.shape[0]

    cand_keys, total = _candidate_outputs(
        in_sites, offs, pd, st, out_sp, odims, kdtype)
    flat_feats = jnp.broadcast_to(
        feats[None], (K,) + feats.shape).reshape(K * nnz, -1)
    vals, out_sites, real = _scatter_to_sites(
        cand_keys, flat_feats, odims, total,
        lambda r, s, n: jax.ops.segment_max(r, s, num_segments=n), kdtype)
    vals, out_sites = _maybe_compact(vals, out_sites, real, traced)
    from jax.experimental import sparse as jsparse

    bcoo = jsparse.BCOO((vals, out_sites), shape=odims + (feats.shape[-1],))
    return SparseCooTensor(bcoo)
