"""``paddle.sparse`` over jax.experimental.sparse (N9 capability).

COO/CSR tensors ride JAX's BCOO/BCSR; sparse matmul lowers to XLA
scatter/gather (TPU has no sparse MXU path — same position as the reference's
cuSPARSE fallback for unsupported shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor, to_tensor


class SparseCooTensor(Tensor):
    """Wrapper marking a Tensor as sparse COO; holds a BCOO internally."""

    __slots__ = ("bcoo",)

    def __init__(self, bcoo, stop_gradient=True):
        self.bcoo = bcoo
        super().__init__(bcoo.todense(), stop_gradient=stop_gradient)

    def indices(self):
        return Tensor(self.bcoo.indices.T)

    def values(self):
        return Tensor(self.bcoo.data)

    def to_dense(self):
        return Tensor(self.bcoo.todense())

    @property
    def nnz(self):
        return int(self.bcoo.nse)


class SparseCsrTensor(Tensor):
    __slots__ = ("bcsr",)

    def __init__(self, bcsr, stop_gradient=True):
        self.bcsr = bcsr
        super().__init__(bcsr.todense(), stop_gradient=stop_gradient)

    def crows(self):
        return Tensor(self.bcsr.indptr)

    def cols(self):
        return Tensor(self.bcsr.indices)

    def values(self):
        return Tensor(self.bcsr.data)

    def to_dense(self):
        return Tensor(self.bcsr.todense())


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    idx = indices._value if isinstance(indices, Tensor) else jnp.asarray(indices)
    val = values._value if isinstance(values, Tensor) else jnp.asarray(values)
    bcoo = jsparse.BCOO((val, idx.T.astype(jnp.int32)), shape=tuple(shape))
    return SparseCooTensor(bcoo, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    cr = crows._value if isinstance(crows, Tensor) else jnp.asarray(crows)
    cc = cols._value if isinstance(cols, Tensor) else jnp.asarray(cols)
    val = values._value if isinstance(values, Tensor) else jnp.asarray(values)
    bcsr = jsparse.BCSR((val, cc.astype(jnp.int32), cr.astype(jnp.int32)), shape=tuple(shape))
    return SparseCsrTensor(bcsr, stop_gradient)


def matmul(x, y, name=None):
    if isinstance(x, SparseCooTensor):
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(x.bcoo @ yv)
    if isinstance(x, SparseCsrTensor):
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(x.bcsr @ yv)
    from ..tensor import matmul as dense_matmul

    return dense_matmul(x, y)


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return Tensor(x.bcoo.todense() + y.bcoo.todense())
    return Tensor(x._value + y._value)


def relu(x, name=None):
    if isinstance(x, SparseCooTensor):
        bcoo = jsparse.BCOO((jax.nn.relu(x.bcoo.data), x.bcoo.indices), shape=x.bcoo.shape)
        return SparseCooTensor(bcoo)
    return Tensor(jax.nn.relu(x._value))


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)
