"""``paddle.sparse`` over jax.experimental.sparse (N9 capability).

COO/CSR tensors ride JAX's BCOO/BCSR; sparse matmul lowers to XLA
scatter/gather (TPU has no sparse MXU path — same position as the reference's
cuSPARSE fallback for unsupported shapes).
"""

from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor, to_tensor


class _SparseTensorBase(Tensor):
    """Shared sparse facade: Tensor bookkeeping WITHOUT a dense payload.

    A sparse tensor holds only its BCOO/BCSR (``phi/core/
    sparse_coo_tensor.h:32`` stores indices+values, never a dense mirror).
    ``_value`` is rebound to None after the canonical ``Tensor.__init__``
    so any accidental dense-op path fails loudly instead of silently
    costing O(dense) memory; materialization is explicit via
    ``.to_dense()``."""

    __slots__ = ()

    def _init_meta(self, stop_gradient):
        Tensor.__init__(self, jnp.zeros((0,)), stop_gradient=stop_gradient)
        self._value = None

    def _sp(self):  # the underlying jax sparse object
        raise NotImplementedError

    @property
    def shape(self):
        return list(self._sp().shape)

    @property
    def dtype(self):
        return self._sp().data.dtype

    @property
    def ndim(self):
        return len(self._sp().shape)

    @property
    def dim(self):
        return len(self._sp().shape)

    @property
    def size(self):
        shp = self._sp().shape
        return int(np.prod(shp)) if shp else 1

    def _no_dense(self):
        raise RuntimeError(
            f"{type(self).__name__} holds no dense buffer; call "
            ".to_dense() to materialize explicitly")

    def numpy(self):
        self._no_dense()

    def __array__(self, dtype=None):
        self._no_dense()

    def tolist(self):
        self._no_dense()

    def item(self, *args):
        self._no_dense()

    def values(self):
        return Tensor(self._sp().data)

    def to_dense(self):
        return Tensor(self._sp().todense())


class SparseCooTensor(_SparseTensorBase):
    """Sparse COO tensor riding jax BCOO; no dense materialization."""

    __slots__ = ("bcoo",)

    def __init__(self, bcoo, stop_gradient=True):
        self.bcoo = bcoo
        self._init_meta(stop_gradient)

    def _sp(self):
        return self.bcoo

    def __repr__(self):
        return (f"SparseCooTensor(shape={list(self.bcoo.shape)}, "
                f"dtype={self.bcoo.data.dtype}, nnz={int(self.bcoo.nse)})")

    def indices(self):
        return Tensor(self.bcoo.indices.T)

    @property
    def nnz(self):
        return int(self.bcoo.nse)


class SparseCsrTensor(_SparseTensorBase):
    """Sparse CSR tensor riding jax BCSR; no dense materialization."""

    __slots__ = ("bcsr",)

    def __init__(self, bcsr, stop_gradient=True):
        self.bcsr = bcsr
        self._init_meta(stop_gradient)

    def _sp(self):
        return self.bcsr

    def __repr__(self):
        return (f"SparseCsrTensor(shape={list(self.bcsr.shape)}, "
                f"dtype={self.bcsr.data.dtype})")

    def crows(self):
        return Tensor(self.bcsr.indptr)

    def cols(self):
        return Tensor(self.bcsr.indices)

    @property
    def nnz(self):
        return int(np.asarray(self.bcsr.indices).size)


def _to_coo(x):
    """CSR → COO view in O(nnz) (host indptr expansion); COO passes through."""
    if isinstance(x, SparseCooTensor):
        return x
    bcsr = x.bcsr
    indptr = np.asarray(bcsr.indptr)
    if indptr.ndim != 1:
        raise ValueError("batched CSR → COO not supported here")
    rows = np.repeat(np.arange(len(indptr) - 1), np.diff(indptr))
    idx = np.stack([rows, np.asarray(bcsr.indices)], 1).astype(np.int32)
    return SparseCooTensor(jsparse.BCOO(
        (bcsr.data, jnp.asarray(idx)), shape=bcsr.shape),
        stop_gradient=x.stop_gradient)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    # jnp.array (copy) for external buffers: ingestion semantics are copy
    idx = indices._value if isinstance(indices, Tensor) else jnp.array(indices)
    val = values._value if isinstance(values, Tensor) else jnp.array(values)
    if shape is None:
        # reference semantics: infer the dense shape from the indices
        # (max coordinate + 1 per sparse dim, plus any dense value dims);
        # nnz == 0 means size-0 sparse dims, like torch/paddle
        if idx.shape[1] == 0:
            sparse_shape = (0,) * idx.shape[0]
        else:
            sparse_shape = tuple(int(d) + 1 for d in jnp.max(idx, axis=1))
        shape = sparse_shape + tuple(val.shape[1:])
    bcoo = jsparse.BCOO((val, idx.T.astype(jnp.int32)), shape=tuple(shape))
    return SparseCooTensor(bcoo, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    cr = crows._value if isinstance(crows, Tensor) else jnp.array(crows)
    cc = cols._value if isinstance(cols, Tensor) else jnp.array(cols)
    val = values._value if isinstance(values, Tensor) else jnp.array(values)
    bcsr = jsparse.BCSR((val, cc.astype(jnp.int32), cr.astype(jnp.int32)), shape=tuple(shape))
    return SparseCsrTensor(bcsr, stop_gradient)


def matmul(x, y, name=None):
    if isinstance(x, SparseCooTensor):
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(x.bcoo @ yv)
    if isinstance(x, SparseCsrTensor):
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(x.bcsr @ yv)
    from ..tensor import matmul as dense_matmul

    return dense_matmul(x, y)


def _coo_to_csr(coo, assume_canonical=False):
    """2-D COO → CSR in O(nnz) (host row-sort + bincount indptr).
    ``assume_canonical`` skips the dedup when the indices are already
    unique (e.g. a union-op output)."""
    c = coo.bcoo if assume_canonical else jsparse.bcoo_sum_duplicates(coo.bcoo)
    idx = np.asarray(c.indices)
    n_rows = c.shape[0]
    order = np.lexsort((idx[:, 1], idx[:, 0]))
    counts = np.bincount(idx[:, 0], minlength=n_rows)
    indptr = np.zeros(n_rows + 1, np.int32)
    np.cumsum(counts, out=indptr[1:])
    return SparseCsrTensor(jsparse.BCSR(
        (c.data[jnp.asarray(order)],
         jnp.asarray(idx[order, 1].astype(np.int32)), jnp.asarray(indptr)),
        shape=c.shape), stop_gradient=coo.stop_gradient)


def _binary_dispatch(x, y, fn):
    """Sparse∘sparse → union op over COO views (O(nnz)); sparse∘dense →
    dense result via explicit materialization; dense∘dense → dense.
    CSR∘CSR round-trips back to CSR (paddle's binary ops are
    format-preserving)."""
    xs = isinstance(x, _SparseTensorBase)
    ys = isinstance(y, _SparseTensorBase)
    if xs and ys:
        out = _coo_union_binary(_to_coo(x), _to_coo(y), fn)
        if (isinstance(x, SparseCsrTensor) and isinstance(y, SparseCsrTensor)
                and out.ndim == 2):
            return _coo_to_csr(out, assume_canonical=True)
        return out
    xv = x.to_dense()._value if xs else (
        x._value if isinstance(x, Tensor) else jnp.asarray(x))
    yv = y.to_dense()._value if ys else (
        y._value if isinstance(y, Tensor) else jnp.asarray(y))
    return Tensor(fn(xv, yv))


def add(x, y, name=None):
    return _binary_dispatch(x, y, jnp.add)


def relu(x, name=None):
    return _value_map(x, jax.nn.relu)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


# ---------------------------------------------------------------------------
# Unary value ops: applied to stored values, sparsity preserved
# (``python/paddle/sparse/unary.py`` surface)
# ---------------------------------------------------------------------------

def _coo_map(x, fn):
    bcoo = jsparse.BCOO((fn(x.bcoo.data), x.bcoo.indices), shape=x.bcoo.shape)
    return SparseCooTensor(bcoo, stop_gradient=x.stop_gradient)


def _csr_map(x, fn):
    bcsr = jsparse.BCSR((fn(x.bcsr.data), x.bcsr.indices, x.bcsr.indptr),
                        shape=x.bcsr.shape)
    return SparseCsrTensor(bcsr, stop_gradient=x.stop_gradient)


def _value_map(x, fn):
    if isinstance(x, SparseCooTensor):
        return _coo_map(x, fn)
    if isinstance(x, SparseCsrTensor):
        return _csr_map(x, fn)
    return Tensor(fn(x._value))


def sin(x, name=None):
    return _value_map(x, jnp.sin)


def tan(x, name=None):
    return _value_map(x, jnp.tan)


def asin(x, name=None):
    return _value_map(x, jnp.arcsin)


def atan(x, name=None):
    return _value_map(x, jnp.arctan)


def sinh(x, name=None):
    return _value_map(x, jnp.sinh)


def tanh(x, name=None):
    return _value_map(x, jnp.tanh)


def asinh(x, name=None):
    return _value_map(x, jnp.arcsinh)


def atanh(x, name=None):
    return _value_map(x, jnp.arctanh)


def sqrt(x, name=None):
    return _value_map(x, jnp.sqrt)


def square(x, name=None):
    return _value_map(x, jnp.square)


def abs(x, name=None):
    return _value_map(x, jnp.abs)


def log1p(x, name=None):
    return _value_map(x, jnp.log1p)


def expm1(x, name=None):
    return _value_map(x, jnp.expm1)


def neg(x, name=None):
    return _value_map(x, jnp.negative)


def pow(x, factor, name=None):
    return _value_map(x, lambda v: jnp.power(v, factor))


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..core import dtype as dtype_mod

    vd = dtype_mod.convert_dtype(value_dtype) if value_dtype else None
    return _value_map(x, (lambda v: v.astype(vd)) if vd else (lambda v: v))


def deg2rad(x, name=None):
    return _value_map(x, jnp.deg2rad)


def rad2deg(x, name=None):
    return _value_map(x, jnp.rad2deg)


def coalesce(x, name=None):
    """Sum duplicate COO indices (``sparse/unary.py`` coalesce)."""
    bcoo = jsparse.bcoo_sum_duplicates(x.bcoo)
    return SparseCooTensor(bcoo, stop_gradient=x.stop_gradient)


def transpose(x, perm, name=None):
    if isinstance(x, _SparseTensorBase):
        was_csr = isinstance(x, SparseCsrTensor)
        coo = _to_coo(x)
        out = SparseCooTensor(
            jsparse.bcoo_transpose(coo.bcoo, permutation=tuple(perm)),
            stop_gradient=coo.stop_gradient)
        return _coo_to_csr(out) if was_csr and out.ndim == 2 else out
    return Tensor(jnp.transpose(x._value, tuple(perm)))


def reshape(x, shape, name=None):
    if isinstance(x, _SparseTensorBase):
        was_csr = isinstance(x, SparseCsrTensor)
        coo = _to_coo(x)
        out = SparseCooTensor(
            jsparse.bcoo_reshape(coo.bcoo, new_sizes=tuple(shape)),
            stop_gradient=coo.stop_gradient)
        return _coo_to_csr(out) if was_csr and out.ndim == 2 else out
    return Tensor(jnp.reshape(x._value, tuple(shape)))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    if axis is None and isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        # full reduction touches only the stored values: O(nnz)
        v = x.bcoo.data if isinstance(x, SparseCooTensor) else x.bcsr.data
        return Tensor(jnp.sum(v))
    dense = x.to_dense()._value if isinstance(
        x, (SparseCooTensor, SparseCsrTensor)) else x._value
    return Tensor(jnp.sum(dense, axis=axis, keepdims=keepdim))


# ---------------------------------------------------------------------------
# Binary ops over matching layouts (``sparse/binary.py``)
# ---------------------------------------------------------------------------

def _row_keys(idx):
    """View an (n, d) int index array as n lexicographic scalar keys."""
    a = np.ascontiguousarray(idx.astype(np.int64))
    return a.view([("", a.dtype)] * a.shape[1]).ravel()


def _coo_union_binary(x, y, fn):
    """Elementwise op over the union of two COO patterns (host-computed
    index union; value math stays in jax).  O(nnz log nnz) host work and
    O(nnz) memory — no densification (``phi/kernels/sparse/
    elementwise_kernel`` semantics)."""
    if tuple(x.bcoo.shape) != tuple(y.bcoo.shape):
        raise ValueError(
            f"sparse binary op shape mismatch: {tuple(x.bcoo.shape)} vs "
            f"{tuple(y.bcoo.shape)}")
    xb = jsparse.bcoo_sum_duplicates(x.bcoo)
    yb = jsparse.bcoo_sum_duplicates(y.bcoo)
    xi = np.asarray(xb.indices)
    yi = np.asarray(yb.indices)
    union = np.unique(np.concatenate([xi, yi], 0), axis=0).astype(np.int32)
    uk = _row_keys(union)

    def gather_vals(bcoo, src_idx):
        # position of each union index in this operand's nnz list (-1 =
        # absent → reads the appended explicit zero); vectorized searchsorted
        sk = _row_keys(src_idx)
        if sk.size == 0:
            return jnp.zeros((len(uk),), bcoo.data.dtype)
        order = np.argsort(sk)
        pos = np.searchsorted(sk, uk, sorter=order)
        pos = np.clip(pos, 0, sk.size - 1)
        hit = sk[order[pos]] == uk
        sel = np.where(hit, order[pos], -1).astype(np.int32)
        data = jnp.concatenate(
            [bcoo.data, jnp.zeros((1,), bcoo.data.dtype)])
        return data[sel]

    vals = fn(gather_vals(xb, xi), gather_vals(yb, yi))
    return SparseCooTensor(
        jsparse.BCOO((vals, jnp.asarray(union)), shape=x.bcoo.shape),
        stop_gradient=x.stop_gradient and y.stop_gradient)


def subtract(x, y, name=None):
    return _binary_dispatch(x, y, jnp.subtract)


def multiply(x, y, name=None):
    return _binary_dispatch(x, y, jnp.multiply)


def divide(x, y, name=None):
    return _binary_dispatch(x, y, jnp.divide)


def mv(x, vec, name=None):
    """Sparse matrix × dense vector (``sparse/binary.py`` mv)."""
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    if isinstance(x, SparseCooTensor):
        return Tensor(x.bcoo @ v)
    if isinstance(x, SparseCsrTensor):
        return Tensor(x.bcsr @ v)
    return Tensor(x._value @ v)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x@y) with sparse x (``sparse/binary.py``)."""
    prod = matmul(x, y)
    inp = input._value if isinstance(input, Tensor) else jnp.asarray(input)
    return Tensor(beta * inp + alpha * prod._value)


def masked_matmul(x, y, mask, name=None):
    """SDD: dense @ dense evaluated ONLY at the mask's nonzero positions
    (``sparse/binary.py`` masked_matmul; the reference lowers to cuSPARSE
    SDDMM).  Gather the needed rows of ``x`` and cols of ``y`` and contract
    per-nnz — compute is O(nnz·K), never materializing the dense product."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    if isinstance(mask, SparseCsrTensor):
        indptr = np.asarray(mask.bcsr.indptr)
        cols_ = jnp.asarray(mask.bcsr.indices)
        rows_ = jnp.asarray(
            np.repeat(np.arange(len(indptr) - 1), np.diff(indptr)).astype(np.int32))
        vals = jnp.einsum("nk,nk->n", xv[rows_], yv[:, cols_].T)
        return SparseCsrTensor(jsparse.BCSR(
            (vals, mask.bcsr.indices, mask.bcsr.indptr), shape=mask.bcsr.shape))
    idx = mask.bcoo.indices
    rows_, cols_ = idx[:, 0], idx[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows_], yv[:, cols_].T)
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=mask.bcoo.shape))


from . import nn  # noqa: F401,E402  (sparse layer/functional subpackage)


def isnan(x, name=None):
    """(``sparse/unary.py`` isnan) NaN mask over stored values only —
    pattern-preserving O(nnz) like the reference kernel."""
    return _value_map(x, jnp.isnan)


def slice(x, axes, starts, ends, name=None):
    """(``sparse/multiary.py`` slice over COO/CSR): keep entries whose
    index falls inside [start, end) per sliced axis, shifting indices —
    O(nnz) select, never densifies."""
    if not isinstance(x, _SparseTensorBase):
        idx = [builtins.slice(None)] * x.ndim
        for a, s, e in zip(axes, starts, ends):
            idx[a] = builtins.slice(s, e)
        return Tensor(x._value[tuple(idx)])
    was_csr = isinstance(x, SparseCsrTensor)
    coo = _to_coo(x)
    import numpy as _np

    ind = _np.asarray(coo.bcoo.indices)
    vals = coo.bcoo.data
    shape = list(coo.shape)
    norm = []
    for a, s, e in zip(axes, starts, ends):
        a = int(a) % len(shape)
        d = shape[a]
        s = int(s) + d if int(s) < 0 else int(s)
        e = int(e) + d if int(e) < 0 else int(e)
        norm.append((a, max(0, s), min(d, max(0, e))))
    keep = _np.ones(ind.shape[0], bool)
    for a, s, e in norm:
        keep &= (ind[:, a] >= s) & (ind[:, a] < e)
        shape[a] = max(0, e - s)
    new_ind = ind[keep].copy()
    for a, s, _ in norm:
        new_ind[:, a] -= s
    out = SparseCooTensor(jsparse.BCOO(
        (vals[_np.nonzero(keep)[0]], jnp.asarray(new_ind)),
        shape=tuple(shape)), stop_gradient=coo.stop_gradient)
    return _coo_to_csr(out) if was_csr and out.ndim == 2 else out


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """(``sparse/multiary.py`` pca_lowrank) randomized PCA of a sparse
    matrix: the only dense objects are (n, q)/(q, q) sketches — every
    product against ``x`` is a sparse matmul, O(nnz·q) (Halko et al.,
    the reference's torch.pca_lowrank algorithm)."""
    assert isinstance(x, _SparseTensorBase), "pca_lowrank needs sparse input"
    m, n = x.shape[-2], x.shape[-1]
    if q is None:
        q = builtins.min(6, m, n)
    coo = _to_coo(x).bcoo
    from ..core import random as _rng

    key = _rng.next_key()
    import jax as _jax

    G = _jax.random.normal(key, (n, q), coo.data.dtype)
    dense_mv = lambda M: jsparse.bcoo_dot_general(  # noqa: E731
        coo, M, dimension_numbers=(((1,), (0,)), ((), ())))
    dense_rmv = lambda M: jsparse.bcoo_dot_general(  # noqa: E731
        jsparse.bcoo_transpose(coo, permutation=(1, 0)), M,
        dimension_numbers=(((1,), (0,)), ((), ())))
    if center:
        ones = jnp.ones((m, 1), coo.data.dtype)
        col_mean = dense_rmv(ones / m).reshape(1, n)        # (1, n)
        mv = lambda M: dense_mv(M) - ones @ (col_mean @ M)  # noqa: E731
        rmv = lambda M: dense_rmv(M) - col_mean.T @ (ones.T @ M)  # noqa: E731
    else:
        mv, rmv = dense_mv, dense_rmv
    Y = mv(G)                                               # (m, q)
    Qm, _ = jnp.linalg.qr(Y)
    for _ in range(niter):
        Z = rmv(Qm)
        Qn, _ = jnp.linalg.qr(Z)
        Y = mv(Qn)
        Qm, _ = jnp.linalg.qr(Y)
    B = rmv(Qm).T                                           # (q, n)
    Ub, s, Vt = jnp.linalg.svd(B, full_matrices=False)
    U = Qm @ Ub
    return Tensor(U), Tensor(s), Tensor(Vt.T)
