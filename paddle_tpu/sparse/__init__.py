"""``paddle.sparse`` over jax.experimental.sparse (N9 capability).

COO/CSR tensors ride JAX's BCOO/BCSR; sparse matmul lowers to XLA
scatter/gather (TPU has no sparse MXU path — same position as the reference's
cuSPARSE fallback for unsupported shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor, to_tensor


class SparseCooTensor(Tensor):
    """Wrapper marking a Tensor as sparse COO; holds a BCOO internally."""

    __slots__ = ("bcoo",)

    def __init__(self, bcoo, stop_gradient=True):
        self.bcoo = bcoo
        super().__init__(bcoo.todense(), stop_gradient=stop_gradient)

    def indices(self):
        return Tensor(self.bcoo.indices.T)

    def values(self):
        return Tensor(self.bcoo.data)

    def to_dense(self):
        return Tensor(self.bcoo.todense())

    @property
    def nnz(self):
        return int(self.bcoo.nse)


class SparseCsrTensor(Tensor):
    __slots__ = ("bcsr",)

    def __init__(self, bcsr, stop_gradient=True):
        self.bcsr = bcsr
        super().__init__(bcsr.todense(), stop_gradient=stop_gradient)

    def crows(self):
        return Tensor(self.bcsr.indptr)

    def cols(self):
        return Tensor(self.bcsr.indices)

    def values(self):
        return Tensor(self.bcsr.data)

    def to_dense(self):
        return Tensor(self.bcsr.todense())


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    idx = indices._value if isinstance(indices, Tensor) else jnp.asarray(indices)
    val = values._value if isinstance(values, Tensor) else jnp.asarray(values)
    bcoo = jsparse.BCOO((val, idx.T.astype(jnp.int32)), shape=tuple(shape))
    return SparseCooTensor(bcoo, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    cr = crows._value if isinstance(crows, Tensor) else jnp.asarray(crows)
    cc = cols._value if isinstance(cols, Tensor) else jnp.asarray(cols)
    val = values._value if isinstance(values, Tensor) else jnp.asarray(values)
    bcsr = jsparse.BCSR((val, cc.astype(jnp.int32), cr.astype(jnp.int32)), shape=tuple(shape))
    return SparseCsrTensor(bcsr, stop_gradient)


def matmul(x, y, name=None):
    if isinstance(x, SparseCooTensor):
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(x.bcoo @ yv)
    if isinstance(x, SparseCsrTensor):
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(x.bcsr @ yv)
    from ..tensor import matmul as dense_matmul

    return dense_matmul(x, y)


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return Tensor(x.bcoo.todense() + y.bcoo.todense())
    return Tensor(x._value + y._value)


def relu(x, name=None):
    if isinstance(x, SparseCooTensor):
        bcoo = jsparse.BCOO((jax.nn.relu(x.bcoo.data), x.bcoo.indices), shape=x.bcoo.shape)
        return SparseCooTensor(bcoo)
    return Tensor(jax.nn.relu(x._value))


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


# ---------------------------------------------------------------------------
# Unary value ops: applied to stored values, sparsity preserved
# (``python/paddle/sparse/unary.py`` surface)
# ---------------------------------------------------------------------------

def _coo_map(x, fn):
    bcoo = jsparse.BCOO((fn(x.bcoo.data), x.bcoo.indices), shape=x.bcoo.shape)
    return SparseCooTensor(bcoo, stop_gradient=x.stop_gradient)


def _csr_map(x, fn):
    bcsr = jsparse.BCSR((fn(x.bcsr.data), x.bcsr.indices, x.bcsr.indptr),
                        shape=x.bcsr.shape)
    return SparseCsrTensor(bcsr, stop_gradient=x.stop_gradient)


def _value_map(x, fn):
    if isinstance(x, SparseCooTensor):
        return _coo_map(x, fn)
    if isinstance(x, SparseCsrTensor):
        return _csr_map(x, fn)
    return Tensor(fn(x._value))


def sin(x, name=None):
    return _value_map(x, jnp.sin)


def tan(x, name=None):
    return _value_map(x, jnp.tan)


def asin(x, name=None):
    return _value_map(x, jnp.arcsin)


def atan(x, name=None):
    return _value_map(x, jnp.arctan)


def sinh(x, name=None):
    return _value_map(x, jnp.sinh)


def tanh(x, name=None):
    return _value_map(x, jnp.tanh)


def asinh(x, name=None):
    return _value_map(x, jnp.arcsinh)


def atanh(x, name=None):
    return _value_map(x, jnp.arctanh)


def sqrt(x, name=None):
    return _value_map(x, jnp.sqrt)


def square(x, name=None):
    return _value_map(x, jnp.square)


def abs(x, name=None):
    return _value_map(x, jnp.abs)


def log1p(x, name=None):
    return _value_map(x, jnp.log1p)


def expm1(x, name=None):
    return _value_map(x, jnp.expm1)


def neg(x, name=None):
    return _value_map(x, jnp.negative)


def pow(x, factor, name=None):
    return _value_map(x, lambda v: jnp.power(v, factor))


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..core import dtype as dtype_mod

    vd = dtype_mod.convert_dtype(value_dtype) if value_dtype else None
    return _value_map(x, (lambda v: v.astype(vd)) if vd else (lambda v: v))


def deg2rad(x, name=None):
    return _value_map(x, jnp.deg2rad)


def rad2deg(x, name=None):
    return _value_map(x, jnp.rad2deg)


def coalesce(x, name=None):
    """Sum duplicate COO indices (``sparse/unary.py`` coalesce)."""
    bcoo = jsparse.bcoo_sum_duplicates(x.bcoo)
    return SparseCooTensor(bcoo, stop_gradient=x.stop_gradient)


def transpose(x, perm, name=None):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(
            jsparse.bcoo_transpose(x.bcoo, permutation=tuple(perm)),
            stop_gradient=x.stop_gradient)
    return Tensor(jnp.transpose(x._value, tuple(perm)))


def reshape(x, shape, name=None):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(
            jsparse.bcoo_reshape(x.bcoo, new_sizes=tuple(shape)),
            stop_gradient=x.stop_gradient)
    return Tensor(jnp.reshape(x._value, tuple(shape)))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    if axis is None and isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        # full reduction touches only the stored values: O(nnz)
        v = x.bcoo.data if isinstance(x, SparseCooTensor) else x.bcsr.data
        return Tensor(jnp.sum(v))
    dense = x.to_dense()._value if isinstance(
        x, (SparseCooTensor, SparseCsrTensor)) else x._value
    return Tensor(jnp.sum(dense, axis=axis, keepdims=keepdim))


# ---------------------------------------------------------------------------
# Binary ops over matching layouts (``sparse/binary.py``)
# ---------------------------------------------------------------------------

def _coo_union_binary(x, y, fn):
    """Elementwise op over the union of two COO patterns (host-computed
    index union; value math stays in jax)."""
    xi = np.asarray(x.bcoo.indices)
    yi = np.asarray(y.bcoo.indices)
    keys = {tuple(r) for r in xi.tolist()} | {tuple(r) for r in yi.tolist()}
    union = np.array(sorted(keys), dtype=np.int32).reshape(len(keys), xi.shape[1])

    def gather_vals(bcoo, idx):
        dense = bcoo.todense()
        return dense[tuple(idx[:, d] for d in range(idx.shape[1]))]

    vals = fn(gather_vals(x.bcoo, union), gather_vals(y.bcoo, union))
    return SparseCooTensor(jsparse.BCOO((vals, jnp.asarray(union)),
                                        shape=x.bcoo.shape))


def subtract(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return _coo_union_binary(x, y, jnp.subtract)
    return Tensor(x._value - y._value)


def multiply(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return _coo_union_binary(x, y, jnp.multiply)
    return Tensor(x._value * y._value)


def divide(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return _coo_union_binary(x, y, jnp.divide)
    return Tensor(x._value / y._value)


def mv(x, vec, name=None):
    """Sparse matrix × dense vector (``sparse/binary.py`` mv)."""
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    if isinstance(x, SparseCooTensor):
        return Tensor(x.bcoo @ v)
    if isinstance(x, SparseCsrTensor):
        return Tensor(x.bcsr @ v)
    return Tensor(x._value @ v)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x@y) with sparse x (``sparse/binary.py``)."""
    prod = matmul(x, y)
    inp = input._value if isinstance(input, Tensor) else jnp.asarray(input)
    return Tensor(beta * inp + alpha * prod._value)


def masked_matmul(x, y, mask, name=None):
    """SDD: dense @ dense evaluated ONLY at the mask's nonzero positions
    (``sparse/binary.py`` masked_matmul; the reference lowers to cuSPARSE
    SDDMM).  Gather the needed rows of ``x`` and cols of ``y`` and contract
    per-nnz — compute is O(nnz·K), never materializing the dense product."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    if isinstance(mask, SparseCsrTensor):
        indptr = np.asarray(mask.bcsr.indptr)
        cols_ = jnp.asarray(mask.bcsr.indices)
        rows_ = jnp.asarray(
            np.repeat(np.arange(len(indptr) - 1), np.diff(indptr)).astype(np.int32))
        vals = jnp.einsum("nk,nk->n", xv[rows_], yv[:, cols_].T)
        return SparseCsrTensor(jsparse.BCSR(
            (vals, mask.bcsr.indices, mask.bcsr.indptr), shape=mask.bcsr.shape))
    idx = mask.bcoo.indices
    rows_, cols_ = idx[:, 0], idx[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows_], yv[:, cols_].T)
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=mask.bcoo.shape))


from . import nn  # noqa: F401,E402  (sparse layer/functional subpackage)
