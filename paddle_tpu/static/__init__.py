"""``paddle.static`` — graph-mode facade.

Capability analog of the reference's static Program/Executor
(``python/paddle/static``, ``base/framework.py`` Program +
``base/executor.py``).  TPU-first design: a ``Program`` is a recorded op
list — every framework op already dispatches through ``run_op``, so under
``program_guard`` the dispatch layer appends (fn, inputs, outputs) nodes;
``Executor.run`` rebinds placeholder values from ``feed`` and replays the
list (optionally as one jitted XLA program).  In-place rebinds are recorded
as alias events so SSA resolution stays correct.

Static *training* (``append_backward`` + ``Optimizer.minimize`` inside a
Program): the backward is ONE recorded grad node whose fn is ``jax.grad``
of the replayed forward w.r.t. the parameter values — regenerated
symbolically by XLA, never a replay of stale tape closures — and the
optimizer's update ops record like any other op (with rebind/alias events
for the param writes).  Mutated training state (params, slots) persists
across ``Executor.run`` calls in a ``Scope`` (``global_scope()`` by
default), matching the reference's scope-variable semantics.  The
preferred TPU-first path for training remains ``paddle.jit.to_static`` over
the whole step; the Program path exists for reference-API parity (static
LR is frozen at build time; master-weight AMP uses the to_static path).
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core import dispatch as _dispatch
from ..core import dtype as dtype_mod
from ..core.tensor import Tensor


class InputSpec:
    """``paddle.static.InputSpec`` analog."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=False):
        self.shape = list(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


_static_mode = False


def in_static_mode() -> bool:
    return _static_mode


class _Node:
    __slots__ = ("kind", "name", "fn", "arg_ids", "arg_snaps", "kwargs",
                 "out_ids", "src_id")

    def __init__(self, kind, **kw):
        self.kind = kind
        for k, v in kw.items():
            setattr(self, k, v)


class Program:
    """A recorded op list with named placeholders (framework.py Program)."""

    def __init__(self):
        self.nodes: List[_Node] = []
        self.placeholders: Dict[str, int] = {}  # name -> tensor id
        self._keepalive: List[Tensor] = []      # keep ids unique/alive
        # training state (param/slot tensor ids) persisted across
        # Executor.run calls via the Scope; filled by append_backward /
        # _static_minimize
        self.state_ids: List[int] = []

    # --- observer callbacks (dispatch hook) -------------------------------
    def on_op(self, name, fn, args, kwargs, result):
        # kwarg tensors are frozen at record time (Program replay rebinds
        # positional args only — the documented static-graph contract)
        kwraw = {k: (v._value if isinstance(v, Tensor) else v)
                 for k, v in kwargs.items()}
        arg_ids, arg_snaps = [], []
        for a in args:
            if isinstance(a, Tensor):
                arg_ids.append(id(a))
                arg_snaps.append(a._value)
                self._keepalive.append(a)
            else:
                arg_ids.append(None)
                arg_snaps.append(a)
        outs = result if isinstance(result, (list, tuple)) else [result]
        out_ids = []
        for o in outs:
            if isinstance(o, Tensor):
                out_ids.append(id(o))
                self._keepalive.append(o)
            else:
                out_ids.append(None)
        self.nodes.append(_Node("op", name=name, fn=fn, arg_ids=arg_ids,
                                arg_snaps=arg_snaps, kwargs=kwraw,
                                out_ids=out_ids))

    def on_rebind(self, wrapper, source):
        self._keepalive.extend([wrapper, source])
        self.nodes.append(_Node("alias", out_ids=[id(wrapper)],
                                src_id=id(source), name="alias", fn=None,
                                arg_ids=[], arg_snaps=[], kwargs={}))

    # --- replay -----------------------------------------------------------
    def replay(self, env: Dict[int, Any]):
        return _replay_nodes(self.nodes, env)

    def global_block(self):
        return self

    def _id_tensor(self, tid: int) -> Tensor:
        # lazily-built id→tensor map, invalidated when keepalive grows
        cache = getattr(self, "_id_map", None)
        if cache is None or cache[0] != len(self._keepalive):
            cache = (len(self._keepalive),
                     {id(t): t for t in self._keepalive})
            self._id_map = cache
        t = cache[1].get(tid)
        if t is None:
            raise KeyError(f"tensor id {tid} not held by this Program")
        return t

    def _id_value(self, tid: int):
        return self._id_tensor(tid)._value

    def __repr__(self):
        return f"Program(nodes={len(self.nodes)}, feeds={list(self.placeholders)})"


def _replay_nodes(nodes: Sequence[_Node], env: Dict[int, Any]):
    for node in nodes:
        if node.kind == "alias":
            if node.src_id in env:
                env[node.out_ids[0]] = env[node.src_id]
            continue
        args = []
        for aid, snap in zip(node.arg_ids, node.arg_snaps):
            if aid is not None and aid in env:
                args.append(env[aid])
            else:
                args.append(snap)
        out = node.fn(*args, **node.kwargs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for oid, o in zip(node.out_ids, outs):
            if oid is not None:
                env[oid] = o
    return env


_default_main_program = Program()
_default_startup_program = Program()


def default_main_program() -> Program:
    return _default_main_program


def default_startup_program() -> Program:
    return _default_startup_program


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    """Record ops built inside the context into ``main_program``."""
    global _default_main_program
    prev_main = _default_main_program
    _default_main_program = main_program
    _dispatch._set_op_observer(main_program)
    try:
        yield
    finally:
        _dispatch._set_op_observer(None)
        _default_main_program = prev_main


def enable_static():
    global _static_mode
    _static_mode = True
    _dispatch._set_op_observer(_default_main_program)


def disable_static():
    global _static_mode
    _static_mode = False
    _dispatch._set_op_observer(None)


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Declare a named placeholder (``static.data`` analog).  The returned
    Tensor carries zeros of the given shape during build; ``Executor.run``
    substitutes the fed value on replay."""
    import jax.numpy as jnp

    d = dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()
    shape = [1 if (s is None or (isinstance(s, int) and s < 0)) else s
             for s in shape]
    t = Tensor(jnp.zeros(shape, d), name=name)
    prog = _default_main_program
    prog.placeholders[name] = id(t)
    prog._keepalive.append(t)
    return t


class Executor:
    """Replays a recorded Program with fed placeholder values
    (``base/executor.py`` analog).  ``use_jit=True`` compiles the whole
    replay into one XLA program (the PirInterpreter/CINN role — here XLA
    does scheduling, fusion and memory planning, SURVEY.md N26/N27)."""

    def __init__(self, place=None):
        self.place = place
        self._jit_cache: Dict[int, Any] = {}

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list: Optional[Sequence] = None, use_jit: bool = False,
            return_numpy: bool = True, scope: Optional["Scope"] = None):
        program = program or _default_main_program
        feed = feed or {}
        if isinstance(program, CompiledProgram):
            program, use_jit = program.program, True
        if hasattr(program, "run_feed"):  # loaded inference artifact
            outs = program.run_feed(feed)
            if fetch_list:
                name_to_i = {n: i for i, n in enumerate(program.fetch_names)}
                outs = [outs[name_to_i[f]] if isinstance(f, str)
                        and f in name_to_i else outs[i]
                        for i, f in enumerate(fetch_list)]
            return [np.asarray(o) if return_numpy else Tensor(o)
                    for o in outs]
        if scope is None:
            # per-program default scope: ids are CPython object ids, so a
            # process-global default would let a dead program's entry alias
            # a recycled id in a new program (and pin dead arrays forever)
            scope = program._scope = getattr(program, "_scope", None) or Scope()
        env: Dict[int, Any] = {}
        for name, value in feed.items():
            if name not in program.placeholders:
                raise KeyError(f"feed target '{name}' not declared via static.data")
            if isinstance(value, Tensor):
                value = value._value
            env[program.placeholders[name]] = jax.numpy.asarray(value)
        # training state (params/slots) persists across runs in the scope;
        # first run falls back to the record-time snapshots
        for sid in program.state_ids:
            env[sid] = (scope.vars[sid] if sid in scope.vars
                        else program._id_value(sid))

        if use_jit:
            # key includes the recorded length/state so a program extended
            # after a jit run (e.g. minimize appended later) re-stages
            key = (id(program), len(program.nodes), len(program.state_ids))
            fn = self._jit_cache.get(key)
            if fn is None:
                names = tuple(sorted(program.placeholders))
                sids = tuple(program.state_ids)

                def replay_pure(feed_vals, state_vals, _names=names,
                                _sids=sids, _prog=program):
                    e = dict(zip((_prog.placeholders[n] for n in _names),
                                 feed_vals))
                    e.update(zip(_sids, state_vals))
                    return _prog.replay(e)

                fn = jax.jit(replay_pure)
                self._jit_cache[key] = fn
            env = fn([env[program.placeholders[n]]
                      for n in sorted(program.placeholders)],
                     [env[sid] for sid in program.state_ids])
        else:
            program.replay(env)

        for sid in program.state_ids:
            if sid in env:
                scope.vars[sid] = env[sid]

        results = []
        for f in fetch_list or []:
            fid = id(f) if isinstance(f, Tensor) else program.placeholders[f]
            val = env.get(fid, f._value if isinstance(f, Tensor) else None)
            results.append(np.asarray(val) if return_numpy else Tensor(val))
        return results


def append_backward(loss: Tensor, parameter_list=None, no_grad_set=None):
    """Append gradient computation to the default main program
    (``base/backward.py`` append_backward analog).

    TPU-first: instead of emitting one grad op per forward op, the WHOLE
    backward is a single recorded node whose fn is ``jax.grad`` of the
    replayed forward with respect to the parameter values — XLA
    differentiates the real program, so replays with new feeds always get
    fresh gradients (no stale tape closures).  Returns ``[(param, grad)]``
    pairs like the reference.
    """
    from ..core.tensor import Parameter

    prog = _default_main_program
    if parameter_list is None:
        seen, params = set(), []
        for t in prog._keepalive:
            if (isinstance(t, Parameter) and not t.stop_gradient
                    and id(t) not in seen):
                seen.add(id(t))
                params.append(t)
    else:
        params = [p for p in parameter_list if not p.stop_gradient]
    if no_grad_set:
        drop = {id(p) for p in no_grad_set}
        params = [p for p in params if id(p) not in drop]
    if not params:
        raise ValueError("append_backward: no trainable parameters recorded")

    fwd_nodes = list(prog.nodes)           # freeze the forward subgraph
    param_ids = [id(p) for p in params]
    feed_names = sorted(prog.placeholders)
    feed_ids = [prog.placeholders[n] for n in feed_names]
    loss_id = id(loss)

    def fwd_pure(param_vals, feed_vals):
        env = dict(zip(param_ids, param_vals))
        env.update(zip(feed_ids, feed_vals))
        env = _replay_nodes(fwd_nodes, env)
        out = env[loss_id]
        if getattr(out, "size", 1) != 1:
            raise ValueError("append_backward requires a scalar loss")
        return out.reshape(())

    grad_of_params = jax.grad(fwd_pure, argnums=0)

    def grad_node_fn(*vals):
        n = len(param_ids)
        return tuple(grad_of_params(list(vals[:n]), list(vals[n:])))

    # eager-run once (build-time feeds) so the grad wrappers exist and the
    # optimizer's recorded update ops can reference them by id
    cur_param_vals = [p._value for p in params]
    cur_feed_vals = [prog._id_value(i) for i in feed_ids]
    grads_now = grad_node_fn(*cur_param_vals, *cur_feed_vals)
    grad_wrappers = [Tensor(g, stop_gradient=True) for g in grads_now]
    for p, gw in zip(params, grad_wrappers):
        p.grad = gw
    prog.on_op("append_backward_grad", grad_node_fn,
               params + [prog._id_tensor(i) for i in feed_ids], {},
               grad_wrappers)
    for pid in param_ids:
        if pid not in prog.state_ids:
            prog.state_ids.append(pid)
    return list(zip(params, grad_wrappers))


def _static_minimize(opt, loss: Tensor, parameters=None, no_grad_set=None):
    """``Optimizer.minimize`` inside an active Program recording: append
    the grad node, record the update ops (with rebind/alias events), and
    register params + optimizer slots as scope-persisted state.  The eager
    wrappers are rolled back so building the graph does not train."""
    if getattr(opt, "_use_master_weights", False):
        raise NotImplementedError(
            "multi_precision (master-weight AMP) is not supported in the "
            "static Program path — use paddle.jit.to_static over the train "
            "step instead (it threads master weights correctly)")
    prog = _default_main_program
    params_grads = append_backward(
        loss, parameters if parameters else opt._parameter_list,
        no_grad_set=no_grad_set)
    psnap = [(p, p._value) for p, _ in params_grads]
    pre_step = opt._step_count
    n_nodes_before = len(prog.nodes)
    opt.step()                     # records opt_* ops + alias events
    opt._step_count = pre_step
    for p, v in psnap:             # build must not train
        p._value = v
    # slots were freshly created during the recording step; roll each back
    # to the recorded op's arg snapshot — its true init (zeros for SGD/Adam
    # moments, but e.g. Adagrad's initial_accumulator_value, Rprop's lr
    # step sizes and NAdam's mu_prod=1 are NOT zero) — and persist them
    slot_ids = {id(t) for st in opt._state.values() for t in st.values()}
    for node in prog.nodes[n_nodes_before:]:
        if node.kind != "op":
            continue
        for aid, snap in zip(node.arg_ids, node.arg_snaps):
            if aid in slot_ids:
                t = next(t for st in opt._state.values()
                         for t in st.values() if id(t) == aid)
                t._value = snap
    for st in opt._state.values():
        for t in st.values():
            if id(t) not in prog.state_ids:
                prog.state_ids.append(id(t))
            prog._keepalive.append(t)
    for p, g in params_grads:
        p.grad = None
    return None, params_grads


def name_scope(prefix):
    return contextlib.nullcontext()


class Scope:
    """Variable store persisting training state across ``Executor.run``
    calls (``base/scope.py`` analog): maps tensor id → value."""

    def __init__(self):
        self.vars: Dict[int, Any] = {}


_global_scope = Scope()


def global_scope():
    return _global_scope


from . import nn  # noqa: E402,F401  (static.nn control flow + sequence ops)


# ---------------------------------------------------------------------------
# Static-graph API tail (``python/paddle/static/__init__.py`` surface)
# ---------------------------------------------------------------------------

Variable = Tensor  # static Variable IS a placeholder-carrying Tensor here


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """(``base/backward.py`` gradients) grads of ``targets`` w.r.t.
    ``inputs`` appended to the default program — same jax.grad-of-replay
    design as :func:`append_backward`, but for arbitrary inputs."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    prog = _default_main_program
    fwd_nodes = list(prog.nodes)
    in_ids = [id(t) for t in inputs]
    feed_names = sorted(prog.placeholders)
    feed_ids = [prog.placeholders[n] for n in feed_names
                if prog.placeholders[n] not in in_ids]
    tgt_ids = [id(t) for t in targets]

    def fwd_pure(in_vals, feed_vals):
        env = dict(zip(in_ids, in_vals))
        env.update(zip(feed_ids, feed_vals))
        env = _replay_nodes(fwd_nodes, env)
        total = 0.0
        for tid, t in zip(tgt_ids, targets):
            out = env.get(tid, t._value)
            total = total + out.sum()
        return total

    grad_fn = jax.grad(fwd_pure, argnums=0)

    def node_fn(*vals):
        n = len(in_ids)
        return tuple(grad_fn(list(vals[:n]), list(vals[n:])))

    now = node_fn(*[t._value for t in inputs],
                  *[prog._id_value(i) for i in feed_ids])
    wrappers = [Tensor(g, stop_gradient=True) for g in now]
    prog.on_op("gradients", node_fn,
               list(inputs) + [prog._id_tensor(i) for i in feed_ids], {},
               wrappers)
    return wrappers


@contextlib.contextmanager
def scope_guard(scope: "Scope"):
    """(``executor.py`` scope_guard) route Executor default-scope lookups
    through ``scope`` inside the context."""
    global _global_scope
    prev = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = prev


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both", name=None):
    """(``static/nn/control_flow.py`` Print) identity op that prints the
    tensor on every execution — ``jax.debug.print`` inside the recorded
    fn, so it fires under eager replay AND jitted replay."""
    msg = message or (input.name or "var")

    def f(v):
        jax.debug.print(msg + " = {v}", v=v)
        return v

    from ..core.dispatch import run_op

    return run_op("static_print", f, input)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """(``static/nn/common.py`` py_func) host-Python op inside the graph
    via ``jax.pure_callback``; optional ``backward_func`` becomes the
    custom VJP (also a host callback)."""
    import numpy as _np

    from ..core.dispatch import run_op

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(o.shape), o._value.dtype)
              for o in outs]

    def call_host(*vals):
        res = func(*[_np.asarray(v) for v in vals])
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(_np.asarray(r) for r in res)

    def f(*vals):
        res = jax.pure_callback(call_host, tuple(shapes), *vals)
        return res if len(res) > 1 else res[0]

    if backward_func is not None:
        @jax.custom_vjp
        def f_vjp(*vals):
            return f(*vals)

        def fwd(*vals):
            return f_vjp(*vals), vals

        def bwd(res_vals, g):
            gs = g if isinstance(g, tuple) else (g,)
            shapes_in = [jax.ShapeDtypeStruct(v.shape, v.dtype)
                         for v in res_vals]

            def host_bwd(*vals_and_grads):
                r = backward_func(*[_np.asarray(v) for v in vals_and_grads])
                r = r if isinstance(r, (list, tuple)) else [r]
                return tuple(_np.asarray(v) for v in r)

            return tuple(jax.pure_callback(
                host_bwd, tuple(shapes_in), *res_vals, *gs))

        f_vjp.defvjp(fwd, bwd)
        return run_op("py_func", f_vjp, *xs)
    return run_op("py_func", f, *xs)


class BuildStrategy:
    """(``compiler.py`` BuildStrategy) accepted for parity; every fusion /
    memory-optimize knob it carries is XLA's job on this substrate."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True


class ExecutionStrategy:
    """(``compiler.py`` ExecutionStrategy) accepted for parity."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """(``compiler.py`` CompiledProgram) marks a Program for whole-graph
    compilation: ``Executor.run`` executes it with the jitted replay."""

    def __init__(self, program: Program, build_strategy: Optional[BuildStrategy] = None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """(``tensor/creation.py`` create_global_var) a filled Tensor kept
    alive by the default program."""
    import jax.numpy as jnp

    t = Tensor(jnp.full(tuple(shape), value,
                        dtype_mod.convert_dtype(dtype)), name=name)
    _default_main_program._keepalive.append(t)
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """(``base/param_attr.py`` create_parameter)."""
    from ..core.tensor import Parameter
    from ..nn.initializer import Normal

    init = default_initializer or Normal(0.0, 0.02)
    v = init(tuple(shape), dtype_mod.convert_dtype(dtype))
    p = Parameter(v, name=name)
    _default_main_program._keepalive.append(p)
    return p


def cpu_places(device_count=None):
    n = device_count or len(jax.devices())
    return [f"cpu:{i}" for i in range(n)]


def cuda_places(device_ids=None):
    return []  # no CUDA in a TPU-first build


def xpu_places(device_ids=None):
    return []


@contextlib.contextmanager
def device_guard(device=None):
    """(``framework.py`` device_guard) scoped default-device selection."""
    from .. import device as device_mod

    prev = device_mod._current
    if device is not None:
        device_mod.set_device(device)
    try:
        yield
    finally:
        device_mod._current = prev


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """(``static/nn/metric.py`` accuracy) top-k accuracy as a Tensor."""
    import jax.numpy as jnp

    from ..core.dispatch import run_op

    def f(logits, lab):
        topk = jnp.argsort(-logits, axis=-1)[..., :k]
        hit = (topk == lab.reshape(-1, 1)).any(-1)
        return hit.mean(dtype=jnp.float32)

    return run_op("accuracy", f, input, label)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, name=None):
    """(``static/nn/metric.py`` auc) ROC-AUC of positive-class scores as a
    Tensor (rank statistic over the batch)."""
    import jax.numpy as jnp

    from ..core.dispatch import run_op

    def f(scores, lab):
        s = (scores[..., 1] if scores.ndim == 2 else scores).reshape(-1)
        lab_f = lab.reshape(-1).astype(jnp.float32)
        # tie-averaged Mann-Whitney ranks: r_i = #less + (#eq + 1)/2
        less = (s[None, :] < s[:, None]).sum(-1).astype(jnp.float32)
        eq = (s[None, :] == s[:, None]).sum(-1).astype(jnp.float32)
        ranks = less + (eq + 1.0) / 2.0
        pos = lab_f.sum()
        neg = lab_f.size - pos
        auc_v = (jnp.sum(ranks * lab_f) - pos * (pos + 1) / 2) / \
            jnp.maximum(pos * neg, 1)
        return auc_v.astype(jnp.float32)

    return run_op("auc", f, input, label)


class ExponentialMovingAverage:
    """(``static/ema.py`` ExponentialMovingAverage) EMA shadow of every
    trainable parameter: call ``update()`` after each step; ``apply()``
    swaps the EMA values in (context manager), ``restore()`` swaps back."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow: Dict[int, Any] = {}
        self._backup: Dict[int, Any] = {}
        self._params: List = []
        self._step = 0
        # bind to the program being BUILT when the EMA is created (the
        # reference constructs EMA inside the program context)
        self._program = _default_main_program

    def _tracked(self):
        if not self._params:
            from ..core.tensor import Parameter

            seen = set()
            for t in self._program._keepalive:
                if (isinstance(t, Parameter) and not t.stop_gradient
                        and id(t) not in seen):
                    seen.add(id(t))
                    self._params.append(t)
        return self._params

    def update(self):
        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in self._tracked():
            prev = self._shadow.get(id(p), p._value)
            self._shadow[id(p)] = d * prev + (1.0 - d) * p._value

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        for p in self._tracked():
            self._backup[id(p)] = p._value
            if id(p) in self._shadow:
                p._value = self._shadow[id(p)]
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._tracked():
            if id(p) in self._backup:
                p._value = self._backup.pop(id(p))


class WeightNormParamAttr:
    """(``base/param_attr.py`` WeightNormParamAttr) requested weight-norm
    reparameterization — not wired into layer creation on this substrate;
    raises at use so the gap is loud (use functional normalization or
    spectral tricks via plain ops instead)."""

    def __init__(self, *a, **k):
        raise NotImplementedError(
            "WeightNormParamAttr is not supported in this build; apply "
            "weight normalization functionally (w = g * v / ||v||) inside "
            "the layer's forward instead")


def _ipu_unsupported(name):
    def fn(*a, **k):
        raise NotImplementedError(
            f"paddle.static.{name} targets Graphcore IPUs — out of scope "
            "for a TPU-first build")

    fn.__name__ = name
    return fn


ipu_shard_guard = _ipu_unsupported("ipu_shard_guard")
IpuCompiledProgram = _ipu_unsupported("IpuCompiledProgram")
IpuStrategy = _ipu_unsupported("IpuStrategy")
set_ipu_shard = _ipu_unsupported("set_ipu_shard")
ctr_metric_bundle = _ipu_unsupported("ctr_metric_bundle")

from .io import (  # noqa: E402,F401
    deserialize_persistables,
    deserialize_program,
    load,
    load_from_file,
    load_inference_model,
    load_program_state,
    normalize_program,
    save,
    save_inference_model,
    save_to_file,
    serialize_persistables,
    serialize_program,
    set_program_state,
)
