"""Minimal ``paddle.static`` surface.

The TPU runtime is dynamic-first (SURVEY.md §7); static-graph capture is
``paddle_tpu.jit.to_static`` over the same eager code.  This module keeps the
pieces other APIs depend on (InputSpec, name guards).
"""

from __future__ import annotations

from ..core import dtype as dtype_mod


class InputSpec:
    """``paddle.static.InputSpec`` analog."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=False):
        self.shape = list(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"
