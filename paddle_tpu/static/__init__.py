"""``paddle.static`` — graph-mode facade.

Capability analog of the reference's static Program/Executor
(``python/paddle/static``, ``base/framework.py`` Program +
``base/executor.py``).  TPU-first design: a ``Program`` is a recorded op
list — every framework op already dispatches through ``run_op``, so under
``program_guard`` the dispatch layer appends (fn, inputs, outputs) nodes;
``Executor.run`` rebinds placeholder values from ``feed`` and replays the
list (optionally as one jitted XLA program).  In-place rebinds are recorded
as alias events so SSA resolution stays correct.

Scope: forward/inference graphs.  Static *training* in this framework is
``paddle.jit.to_static`` over the whole train step (SURVEY.md §7 layer 3)
— the Program facade intentionally does not re-implement append_backward.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core import dispatch as _dispatch
from ..core import dtype as dtype_mod
from ..core.tensor import Tensor


class InputSpec:
    """``paddle.static.InputSpec`` analog."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=False):
        self.shape = list(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


_static_mode = False


def in_static_mode() -> bool:
    return _static_mode


class _Node:
    __slots__ = ("kind", "name", "fn", "arg_ids", "arg_snaps", "kwargs",
                 "out_ids", "src_id")

    def __init__(self, kind, **kw):
        self.kind = kind
        for k, v in kw.items():
            setattr(self, k, v)


class Program:
    """A recorded op list with named placeholders (framework.py Program)."""

    def __init__(self):
        self.nodes: List[_Node] = []
        self.placeholders: Dict[str, int] = {}  # name -> tensor id
        self._keepalive: List[Tensor] = []      # keep ids unique/alive

    # --- observer callbacks (dispatch hook) -------------------------------
    def on_op(self, name, fn, args, kwraw, result):
        arg_ids, arg_snaps = [], []
        for a in args:
            if isinstance(a, Tensor):
                arg_ids.append(id(a))
                arg_snaps.append(a._value)
                self._keepalive.append(a)
            else:
                arg_ids.append(None)
                arg_snaps.append(a)
        outs = result if isinstance(result, (list, tuple)) else [result]
        out_ids = []
        for o in outs:
            if isinstance(o, Tensor):
                out_ids.append(id(o))
                self._keepalive.append(o)
            else:
                out_ids.append(None)
        self.nodes.append(_Node("op", name=name, fn=fn, arg_ids=arg_ids,
                                arg_snaps=arg_snaps, kwargs=kwraw,
                                out_ids=out_ids))

    def on_rebind(self, wrapper, source):
        self._keepalive.extend([wrapper, source])
        self.nodes.append(_Node("alias", out_ids=[id(wrapper)],
                                src_id=id(source), name="alias", fn=None,
                                arg_ids=[], arg_snaps=[], kwargs={}))

    # --- replay -----------------------------------------------------------
    def replay(self, env: Dict[int, Any]):
        for node in self.nodes:
            if node.kind == "alias":
                if node.src_id in env:
                    env[node.out_ids[0]] = env[node.src_id]
                continue
            args = []
            for aid, snap in zip(node.arg_ids, node.arg_snaps):
                if aid is not None and aid in env:
                    args.append(env[aid])
                else:
                    args.append(snap)
            out = node.fn(*args, **node.kwargs)
            outs = out if isinstance(out, (list, tuple)) else [out]
            for oid, o in zip(node.out_ids, outs):
                if oid is not None:
                    env[oid] = o
        return env

    def global_block(self):
        return self

    def __repr__(self):
        return f"Program(nodes={len(self.nodes)}, feeds={list(self.placeholders)})"


_default_main_program = Program()
_default_startup_program = Program()


def default_main_program() -> Program:
    return _default_main_program


def default_startup_program() -> Program:
    return _default_startup_program


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    """Record ops built inside the context into ``main_program``."""
    global _default_main_program
    prev_main = _default_main_program
    _default_main_program = main_program
    _dispatch._set_op_observer(main_program)
    try:
        yield
    finally:
        _dispatch._set_op_observer(None)
        _default_main_program = prev_main


def enable_static():
    global _static_mode
    _static_mode = True
    _dispatch._set_op_observer(_default_main_program)


def disable_static():
    global _static_mode
    _static_mode = False
    _dispatch._set_op_observer(None)


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Declare a named placeholder (``static.data`` analog).  The returned
    Tensor carries zeros of the given shape during build; ``Executor.run``
    substitutes the fed value on replay."""
    import jax.numpy as jnp

    d = dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()
    shape = [1 if (s is None or (isinstance(s, int) and s < 0)) else s
             for s in shape]
    t = Tensor(jnp.zeros(shape, d), name=name)
    prog = _default_main_program
    prog.placeholders[name] = id(t)
    prog._keepalive.append(t)
    return t


class Executor:
    """Replays a recorded Program with fed placeholder values
    (``base/executor.py`` analog).  ``use_jit=True`` compiles the whole
    replay into one XLA program (the PirInterpreter/CINN role — here XLA
    does scheduling, fusion and memory planning, SURVEY.md N26/N27)."""

    def __init__(self, place=None):
        self.place = place
        self._jit_cache: Dict[int, Any] = {}

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list: Optional[Sequence] = None, use_jit: bool = False,
            return_numpy: bool = True):
        program = program or _default_main_program
        feed = feed or {}
        env: Dict[int, Any] = {}
        for name, value in feed.items():
            if name not in program.placeholders:
                raise KeyError(f"feed target '{name}' not declared via static.data")
            if isinstance(value, Tensor):
                value = value._value
            env[program.placeholders[name]] = jax.numpy.asarray(value)

        if use_jit:
            fn = self._jit_cache.get(id(program))
            if fn is None:
                names = tuple(sorted(program.placeholders))

                def replay_pure(feed_vals, _names=names, _prog=program):
                    e = dict(zip((_prog.placeholders[n] for n in _names),
                                 feed_vals))
                    return _prog.replay(e)

                fn = jax.jit(replay_pure)
                self._jit_cache[id(program)] = fn
            env = fn([env[program.placeholders[n]]
                      for n in sorted(program.placeholders)])
        else:
            program.replay(env)

        results = []
        for f in fetch_list or []:
            fid = id(f) if isinstance(f, Tensor) else program.placeholders[f]
            val = env.get(fid, f._value if isinstance(f, Tensor) else None)
            results.append(np.asarray(val) if return_numpy else Tensor(val))
        return results


def name_scope(prefix):
    return contextlib.nullcontext()


class Scope:
    pass


def global_scope():
    return Scope()


from . import nn  # noqa: E402,F401  (static.nn control flow + sequence ops)
