"""``paddle.static.nn`` — control flow + sequence ops
(``python/paddle/static/nn/control_flow.py``, ``sequence_lod.py``).

TPU-first control flow: in eager mode the predicate is concrete, so
``cond``/``case``/``while_loop`` dispatch in Python (fully differentiable
through the tape — the reference's dygraph users write plain ``if``).
Under a ``to_static``/jit trace the predicate is a tracer and the ops
lower to ``lax.cond`` / ``lax.switch`` / ``lax.while_loop`` — compiled
data-dependent control flow with static shapes, XLA's native form.

Sequence ops use (data, length) padded batches — the LoD-tensor legacy
layout maps to padded [B, T, ...] + per-row lengths on TPU (ragged shapes
don't compile)."""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import run_op
from ..core.tensor import Tensor, to_tensor


def _ensure(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _is_traced(t: Tensor) -> bool:
    return isinstance(t._value, jax.core.Tracer)


def _unwrap(tree):
    return jax.tree.map(
        lambda o: o._value if isinstance(o, Tensor) else o, tree,
        is_leaf=lambda o: isinstance(o, Tensor))


def _wrap(tree):
    return jax.tree.map(
        lambda v: Tensor(v) if isinstance(v, jax.Array) or isinstance(
            v, jax.core.Tracer) else v, tree)


# --------------------------------------------------------------------------
# control flow (control_flow.py: cond:1436, case:942, switch_case:1065,
# while_loop:687)
# --------------------------------------------------------------------------

def cond(pred, true_fn: Callable = None, false_fn: Callable = None,
         name=None, return_names=None):
    p = _ensure(pred)
    if not _is_traced(p):
        taken = bool(p._host_read().reshape(()))
        return true_fn() if taken else false_fn()
    out = jax.lax.cond(p._value.reshape(()).astype(bool),
                       lambda: _unwrap(true_fn()),
                       lambda: _unwrap(false_fn()))
    return _wrap(out)


def case(pred_fn_pairs: Sequence[Tuple], default: Callable = None,
         name=None):
    """First pair whose predicate holds wins (control_flow.py:942)."""
    if default is None:
        *pred_fn_pairs, last = pred_fn_pairs
        default = last[1]
    result = default
    for pr, fn in reversed(list(pred_fn_pairs)):
        result = (lambda pr=pr, fn=fn, rest=result:
                  cond(pr, fn, rest if callable(rest) else (lambda: rest)))
    return result() if callable(result) else result


def switch_case(branch_index, branch_fns, default: Callable = None,
                name=None):
    """Integer-indexed branch dispatch (control_flow.py:1065).

    ``branch_fns``: dict {index: fn} or list of (index, fn) or list of fns.
    """
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        pairs = sorted((int(i), f) for i, f in branch_fns)
    else:
        pairs = list(enumerate(branch_fns))
    idx = _ensure(branch_index)
    if default is None:
        default = pairs[-1][1]
    if not _is_traced(idx):
        i = int(idx._host_read().reshape(()))
        for k, fn in pairs:
            if k == i:
                return fn()
        return default()
    # dense branch table for lax.switch: map arbitrary keys to slots,
    # unmatched indices take the default (last slot)
    keys = jnp.asarray([k for k, _ in pairs])
    slot = jnp.argmax(keys == idx._value.reshape(()).astype(keys.dtype))
    matched = jnp.any(keys == idx._value.reshape(()).astype(keys.dtype))
    slot = jnp.where(matched, slot, len(pairs))
    fns = [lambda fn=fn: _unwrap(fn()) for _, fn in pairs]
    fns.append(lambda: _unwrap(default()))
    return _wrap(jax.lax.switch(slot, fns))


def while_loop(cond_fn: Callable, body: Callable, loop_vars: List,
               is_test=False, name=None):
    """(control_flow.py:687) eager: Python loop (tape-differentiable);
    traced: ``lax.while_loop`` (forward; XLA's native loop)."""
    leaves = [v for v in jax.tree.leaves(
        loop_vars, is_leaf=lambda o: isinstance(o, Tensor))
        if isinstance(v, Tensor)]
    traced = any(_is_traced(t) for t in leaves) or _is_traced(
        _ensure(cond_fn(*loop_vars)))
    if not traced:
        vars_ = list(loop_vars)
        while bool(np.asarray(_ensure(cond_fn(*vars_))._value).reshape(())):
            out = body(*vars_)
            vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
        return vars_

    def c(raw):
        return _ensure(cond_fn(*_wrap(raw)))._value.reshape(()).astype(bool)

    def b(raw):
        out = body(*_wrap(raw))
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        return _unwrap(out)

    out = jax.lax.while_loop(c, b, _unwrap(list(loop_vars)))
    return _wrap(out)


def Assert(cond_t, data=None, summarize=20, name=None):
    """(control_flow.py:57) eager runtime assertion."""
    c = _ensure(cond_t)
    if _is_traced(c):
        return  # compiled programs: checks run via debug_nans/checkify
    if not bool(c._host_read().all()):
        vals = [_ensure(d)._host_read().reshape(-1)[:summarize]
                for d in (data or [])]
        raise AssertionError(f"paddle.static.nn.Assert failed; data={vals}")


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both", name=None):
    """(control_flow.py:2043) passthrough + host print (jax.debug.print
    when traced, so it fires from compiled programs too)."""
    t = _ensure(input)
    if _is_traced(t):
        jax.debug.print((message or "Print") + ": {x}", x=t._value)
        return t
    v = t._host_read().reshape(-1)[:summarize]
    print(f"{message or 'Print'}: shape={list(t.shape)} values={v}")
    return t


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """(static/nn/common.py py_func) host-callback op: ``func`` runs in
    Python via ``jax.pure_callback`` under jit, directly in eager."""
    xs = [_ensure(v) for v in (x if isinstance(x, (list, tuple)) else [x])]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(o.shape), o._value.dtype)
              for o in outs]

    def raw(*vals):
        res = func(*[np.asarray(v) for v in vals])
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(np.asarray(r, dtype=s.dtype).reshape(s.shape)
                     for r, s in zip(res, shapes))

    def kernel(*vals):
        if any(isinstance(v, jax.core.Tracer) for v in vals):
            result = jax.pure_callback(
                raw, tuple(shapes), *vals)
        else:
            result = raw(*vals)
        return result if len(result) > 1 else result[0]

    return run_op("py_func", kernel, *xs)


# --------------------------------------------------------------------------
# sequence ops over padded (data, length) batches (sequence_lod.py)
# --------------------------------------------------------------------------

def _length_mask(lengths, maxlen):
    return jnp.arange(maxlen)[None, :] < lengths[:, None]


def sequence_softmax(x, length, name=None):
    """Per-row softmax over the valid prefix ([B, T] padded)."""

    def f(v, ln):
        mask = _length_mask(ln, v.shape[1])
        z = jnp.where(mask, v, -jnp.inf)
        p = jax.nn.softmax(z, axis=1)
        return jnp.where(mask, p, 0.0)

    return run_op("sequence_softmax", f, _ensure(x), _ensure(length))


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """Pad the valid prefix with ``pad_value`` beyond ``length``."""
    pv = float(_ensure(pad_value)._host_read().reshape(-1)[0]) \
        if isinstance(pad_value, Tensor) else float(pad_value)

    def f(v, ln):
        mask = _length_mask(ln, v.shape[1])
        shape = mask.shape + (1,) * (v.ndim - 2)
        return jnp.where(mask.reshape(shape), v, pv)

    return run_op("sequence_pad", f, _ensure(x), _ensure(length)), length


def sequence_unpad(x, length, name=None):
    """Zero out the padding (padded-batch analog of LoD unpad)."""

    def f(v, ln):
        mask = _length_mask(ln, v.shape[1])
        shape = mask.shape + (1,) * (v.ndim - 2)
        return v * mask.reshape(shape).astype(v.dtype)

    return run_op("sequence_unpad", f, _ensure(x), _ensure(length))


def sequence_reverse(x, length, name=None):
    """Reverse each row's valid prefix, padding stays in place."""

    def f(v, ln):
        T = v.shape[1]
        pos = jnp.arange(T)[None, :]
        src = jnp.where(pos < ln[:, None], ln[:, None] - 1 - pos, pos)
        return jnp.take_along_axis(
            v, src.reshape(src.shape + (1,) * (v.ndim - 2)).astype(jnp.int32),
            axis=1) if v.ndim > 2 else jnp.take_along_axis(
            v, src.astype(jnp.int32), axis=1)

    return run_op("sequence_reverse", f, _ensure(x), _ensure(length))


def sequence_first_step(x, length, name=None):
    return run_op("sequence_first_step", lambda v, ln: v[:, 0],
                  _ensure(x), _ensure(length))


def sequence_last_step(x, length, name=None):
    def f(v, ln):
        idx = jnp.clip(ln - 1, 0, v.shape[1] - 1).astype(jnp.int32)
        return jnp.take_along_axis(
            v, idx.reshape((-1, 1) + (1,) * (v.ndim - 2)), axis=1)[:, 0]

    return run_op("sequence_last_step", f, _ensure(x), _ensure(length))


def sequence_pool(x, pool_type, length=None, name=None):
    """sum|average|max|sqrt|first|last over valid prefixes."""
    t = _ensure(x)
    ln = _ensure(length) if length is not None else to_tensor(
        np.full((t.shape[0],), t.shape[1], np.int32))
    pool_type = pool_type.lower()
    if pool_type == "first":
        return sequence_first_step(t, ln)
    if pool_type == "last":
        return sequence_last_step(t, ln)

    def f(v, l2):
        mask = _length_mask(l2, v.shape[1])
        m = mask.reshape(mask.shape + (1,) * (v.ndim - 2)).astype(v.dtype)
        if pool_type == "max":
            return jnp.max(jnp.where(m > 0, v, -jnp.inf), axis=1)
        s = jnp.sum(v * m, axis=1)
        if pool_type == "sum":
            return s
        denom = jnp.maximum(l2, 1).astype(v.dtype)
        denom = denom.reshape((-1,) + (1,) * (v.ndim - 2))
        if pool_type == "average":
            return s / denom
        if pool_type == "sqrt":
            return s / jnp.sqrt(denom)
        raise ValueError(f"unknown pool_type {pool_type}")

    return run_op("sequence_pool", f, t, ln)


def sequence_concat(inputs, name=None):
    """Row-wise concat of padded batches along time."""
    from ..tensor.manipulation import concat

    return concat(list(inputs), axis=1)


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """All win_size-grams per position (sequence_lod.py)."""

    def f(v):
        T = v.shape[1]
        idx = jnp.arange(T)[:, None] + jnp.arange(win_size)[None, :]
        gram = jnp.where(idx < T, v[:, jnp.clip(idx, 0, T - 1)], pad_value)
        return gram

    return run_op("sequence_enumerate", f, _ensure(input))


def sequence_expand(x, y, ref_level=-1, name=None):
    """Broadcast rows of ``x`` to ``y``'s time length."""

    def f(xv, yv):
        return jnp.broadcast_to(xv[:, None], (xv.shape[0], yv.shape[1])
                                + xv.shape[1:])

    return run_op("sequence_expand", f, _ensure(x), _ensure(y))
