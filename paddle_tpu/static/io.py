"""Static-graph save/load (``python/paddle/static/io.py`` capability).

TPU-first: the portable serialized form of a Program is its jitted replay
exported as StableHLO (``jax.export``) — parameters freeze into the
artifact as constants, exactly what an inference export wants — plus a
pickled name→array map for the trainable state (the pdmodel/pdiparams
pair).  ``load_inference_model`` returns a loaded-program object the
``Executor`` runs directly.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core.tensor import Parameter, Tensor
from ..parallel._compat import get_jax_export  # the ONE jax.export
                                               # binding (ISSUE 15)

_MODEL_SUFFIX = ".pdmodel"
_PARAMS_SUFFIX = ".pdiparams"


def _program():
    from . import default_main_program

    return default_main_program()


def _named_params(program) -> Dict[str, Parameter]:
    out: Dict[str, Parameter] = {}
    seen = set()
    i = 0
    for t in program._keepalive:
        if isinstance(t, Parameter) and id(t) not in seen:
            seen.add(id(t))
            out[t.name or f"param_{i}"] = t
            i += 1
    return out


# --- program state (``load_program_state``/``set_program_state``) ----------

def save(program, path: str, protocol: int = 4):
    """(``static/io.py`` save) persist every parameter of ``program``."""
    state = {k: p._host_read() for k, p in _named_params(program).items()}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + _PARAMS_SUFFIX if not path.endswith(_PARAMS_SUFFIX)
              else path, "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, path: str, executor=None, var_list=None):
    """(``static/io.py`` load) restore parameters saved by :func:`save`."""
    p = path if path.endswith(_PARAMS_SUFFIX) else path + _PARAMS_SUFFIX
    with open(p, "rb") as f:
        state = pickle.load(f)
    set_program_state(program, state)


def load_program_state(model_path: str, var_list=None) -> Dict[str, Any]:
    p = (model_path if model_path.endswith(_PARAMS_SUFFIX)
         else model_path + _PARAMS_SUFFIX)
    with open(p, "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict: Dict[str, Any]):
    params = _named_params(program)
    for k, v in state_dict.items():
        if k in params:
            # set_value: copy-on-ingest + loud shape check + dtype keep
            try:
                params[k].set_value(v)
            except ValueError as e:
                raise ValueError(f"set_program_state: {k}: {e}") from None


# --- inference export (``save_inference_model`` family) --------------------

class _LoadedProgram:
    """Deserialized inference program: a StableHLO artifact + feed/fetch
    naming.  ``Executor.run`` executes it directly."""

    def __init__(self, exported, feed_names: List[str],
                 fetch_names: List[str]):
        self._exported = exported
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)

    def run_feed(self, feed: Dict[str, Any]):
        import jax.numpy as jnp

        args = [jnp.asarray(feed[n]) for n in self.feed_names]
        out = self._exported.call(*args)
        return list(out) if isinstance(out, (list, tuple)) else [out]


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """(``static/io.py`` normalize_program) prune the program to the nodes
    the fetch targets actually depend on (dead-op elimination)."""
    from . import Program

    fetch_ids = {id(v) for v in fetch_vars}
    keep = [False] * len(program.nodes)
    needed = set(fetch_ids)
    for i in range(len(program.nodes) - 1, -1, -1):
        node = program.nodes[i]
        outs = [o for o in node.out_ids if o is not None]
        if node.kind == "alias":
            if node.out_ids[0] in needed:
                keep[i] = True
                needed.add(node.src_id)
            continue
        if any(o in needed for o in outs):
            keep[i] = True
            needed.update(a for a in node.arg_ids if a is not None)
    pruned = Program()
    pruned.nodes = [n for n, k in zip(program.nodes, keep) if k]
    pruned.placeholders = dict(program.placeholders)
    pruned._keepalive = list(program._keepalive)
    pruned.state_ids = list(program.state_ids)
    return pruned


def _export_bytes(program, feed_vars, fetch_vars) -> bytes:
    feed_ids = [id(v) for v in feed_vars]
    fetch_ids = [id(v) for v in fetch_vars]
    nodes = list(program.nodes)

    def pure(*feed_vals):
        from . import _replay_nodes

        env = dict(zip(feed_ids, feed_vals))
        env = _replay_nodes(nodes, env)
        return tuple(env.get(fid, v._value)
                     for fid, v in zip(fetch_ids, fetch_vars))

    specs = [jax.ShapeDtypeStruct(tuple(v.shape), v._value.dtype)
             for v in feed_vars]
    exported = get_jax_export().export(jax.jit(pure))(*specs)
    return exported.serialize()


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs) -> bytes:
    program = program or _program()
    return _export_bytes(program, _as_list(feed_vars), _as_list(fetch_vars))


def deserialize_program(data: bytes):
    exported = get_jax_export().deserialize(data)
    n_in = len(exported.in_avals)
    return _LoadedProgram(exported, [f"feed_{i}" for i in range(n_in)],
                          [f"fetch_{i}" for i in range(len(exported.out_avals))])


def serialize_persistables(feed_vars, fetch_vars, executor=None,
                           program=None, **kwargs) -> bytes:
    program = program or _program()
    state = {k: p._host_read() for k, p in _named_params(program).items()}
    return pickle.dumps(state)


def deserialize_persistables(program, data: bytes, executor=None):
    set_program_state(program, pickle.loads(data))


def save_to_file(path: str, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def _as_list(v):
    return list(v) if isinstance(v, (list, tuple)) else [v]


def _feed_name(program, var) -> str:
    for name, tid in program.placeholders.items():
        if tid == id(var):
            return name
    return var.name or f"feed_{id(var)}"


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """(``static/io.py`` save_inference_model) export the fetch
    computation over the feed placeholders as StableHLO + metadata."""
    program = program or _program()
    feed_vars = _as_list(feed_vars)
    fetch_vars = _as_list(fetch_vars)
    blob = _export_bytes(program, feed_vars, fetch_vars)
    meta = {
        "feed_names": [_feed_name(program, v) for v in feed_vars],
        "fetch_names": [v.name or f"fetch_{i}"
                        for i, v in enumerate(fetch_vars)],
    }
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    save_to_file(path_prefix + _MODEL_SUFFIX,
                 pickle.dumps({"stablehlo": blob, "meta": meta}))
    # params are frozen into the artifact; pdiparams records the state for
    # train-resume parity
    save_to_file(path_prefix + _PARAMS_SUFFIX,
                 serialize_persistables(feed_vars, fetch_vars,
                                        program=program))


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Returns ``[loaded_program, feed_names, fetch_names]`` — run it with
    ``Executor.run(program=loaded_program, feed=..., fetch_list=...)``."""
    raw = pickle.loads(load_from_file(path_prefix + _MODEL_SUFFIX))
    exported = get_jax_export().deserialize(raw["stablehlo"])
    lp = _LoadedProgram(exported, raw["meta"]["feed_names"],
                        raw["meta"]["fetch_names"])
    return [lp, lp.feed_names, lp.fetch_names]
