"""Places + save/load (``paddle.framework`` / ``paddle.save`` analog).

Serialization format: a pickle of nested dicts with numpy leaves — pickle-
compatible with the reference's ``paddle.save`` capability
(``python/paddle/framework/io.py``).  Distributed sharded checkpoints live in
``paddle_tpu.distributed.checkpoint``.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from .core.tensor import Parameter, Tensor


class Place:
    def __init__(self, id=0):
        self.id = id

    def __repr__(self):
        return f"{type(self).__name__}({self.id})"


class CPUPlace(Place):
    pass


class CUDAPlace(Place):
    pass


class TPUPlace(Place):
    pass


class CUDAPinnedPlace(Place):
    pass


def _to_saveable(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": obj._host_read(),
                "stop_gradient": obj.stop_gradient,
                "param": isinstance(obj, Parameter)}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


def _from_saveable(obj: Any) -> Any:
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            cls = Parameter if obj.get("param") else Tensor
            if cls is Parameter:
                t = Parameter(obj["data"], trainable=not obj.get("stop_gradient", False))
            else:
                t = Tensor(obj["data"], stop_gradient=obj.get("stop_gradient", True))
            return t
        return {k: _from_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saveable(v) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **kwargs):
    """``paddle.save`` analog."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path: str, **kwargs) -> Any:
    """``paddle.load`` analog."""
    with open(path, "rb") as f:
        return _from_saveable(pickle.load(f))
