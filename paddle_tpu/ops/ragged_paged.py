"""Ragged paged attention: ONE program for mixed prefill chunks + decode.

The serving engine's bounded compile count used to be paid for with three
separate bucketed program families (one-shot prefill / chunked prefill /
decode) and the padding each family's buckets waste.  Following Ragged
Paged Attention (PAPERS.md #1), this module serves the whole step shape
with a single kernel over a **packed token batch**: every scheduled
token — whether it belongs to a 1-token decode row or an n-token prefill
chunk — is one entry of a flat ``[T, H, D]`` query array, routed to its
sequence by per-token segment metadata:

``q``            ``[T, H, D]``   packed new-token queries (pads → null row)
``k/v_cache``    ``[num_blocks, block_size, Hkv, D]`` shared block pools
``block_tables`` ``[R, W]`` int32  per-ROW page tables (pad rows all-null)
``kv_lens``      ``[R]`` int32   total KV length per row AFTER this step
``seg_ids``      ``[T]`` int32   row each packed token belongs to
``q_pos``        ``[T]`` int32   absolute KV position of each token
→ out            ``[T, H, D]``

Token ``t`` attends causally over its row's pages: columns
``< min(kv_lens[seg_ids[t]], q_pos[t] + 1)`` — a decode row (one token at
position ``p``, ``kv_len = p + 1``) and a chunk token (mid-prompt
position) are the SAME predicate, which is what lets one launch fuse
both phases.  Padding tokens point at a pad row whose table is all null
pages (block 0) with ``kv_len = 1``; their output is finite garbage the
engine never reads.

Written twice against this one interface (the PR 9 oracle discipline):

* :func:`ragged_oracle` — the XLA gather/segment reference, the
  CPU-provable ground truth (the ragged analog of
  ``pallas_paged.decode_oracle``).  The interpret-mode parity sweep and
  the online :class:`~paddle_tpu.observability.audit.NumericsAuditor`
  both compare against it.
* :func:`_ragged_attention_kernel` — the Pallas TPU kernel: the block
  table rides scalar prefetch (``pltpu.PrefetchScalarGridSpec``) so the
  per-(token, page) grid step DMAs exactly the KV page it needs, with
  online-softmax state in VMEM scratch — the same shape as
  ``pallas_paged._decode_kernel`` with the per-SEQUENCE length swapped
  for the per-TOKEN causal limit.

**Mesh-spanning (the mp>1 fast path, at last):** :func:`ragged_paged_attention`
dispatches the kernel through ``shard_map`` over the ``mp`` axis — query
heads and KV pools sharded per ``KV_POOL_SPEC`` (the head dim), all
routing metadata replicated — so the Pallas path is no longer pinned off
under tensor parallelism: each shard runs the single-shard kernel on its
head slice and the row-parallel output projection does the psum, exactly
like the XLA path.  Interpret mode keeps the whole arrangement testable
on CPU meshes.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_x32 import no_x64

# np.float32 scalar, not a Python float: inside an OUTER jit the
# interpret-mode kernel body is staged and re-evaluated outside the
# no_x64() window, where a bare float would promote to f64 (same fix as
# pallas_paged / pallas_flash)
_NEG_INF = np.float32(-1e30)

# Which path the most recent dispatch took: "pallas" | "xla" (the same
# loud-fallback contract as ops/paged_attention.py).
last_path = None


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def ragged_oracle(q, k_cache, v_cache, block_tables, kv_lens, seg_ids,
                  q_pos):
    """XLA gather reference for the ragged packed step — the standing
    ground truth the Pallas kernel is differentially tested against
    (interpret-mode parity sweep offline, sampled shadow re-execution
    online via the NumericsAuditor).  Gathers each token's row pages to
    a dense ``[T, K, Hkv, D]`` context and masks with the per-token
    causal limit ``min(kv_lens[seg], q_pos + 1)``."""
    T, H, D = q.shape
    bs = k_cache.shape[1]
    Hkv = k_cache.shape[2]
    rep = H // Hkv
    W = block_tables.shape[1]
    scale = 1.0 / math.sqrt(D)

    bt = block_tables[seg_ids]                       # [T, W]
    k = k_cache[bt].reshape(T, W * bs, Hkv, D)
    v = v_cache[bt].reshape(T, W * bs, Hkv, D)

    qg = q.reshape(T, Hkv, rep, D)
    logits = jnp.einsum("thrd,tkhd->thrk", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    col = jnp.arange(W * bs)[None, :]
    limit = jnp.minimum(kv_lens[seg_ids], q_pos + 1)  # [T] causal ∧ len
    mask = col < limit[:, None]
    logits = jnp.where(mask[:, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("thrk,tkhd->thrd", probs, v.astype(jnp.float32))
    return out.reshape(T, H, D).astype(q.dtype)


def _ragged_kernel(seg_ref, pos_ref, bt_ref, len_ref, q_ref, k_ref, v_ref,
                   o_ref, acc_ref, m_ref, l_ref, *, scale, block_size,
                   n_pages, rep):
    """Grid (T, n_pages): token ``t`` walks its row's pages with online
    softmax in VMEM scratch — ``pallas_paged._decode_kernel`` with the
    sequence length replaced by the per-token causal limit."""
    t = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    seg = seg_ref[t]
    # causal ∧ length limit for THIS token; pages beyond it are skipped
    # (their DMA still reads page bt[seg, j], which is 0-padded — harmless)
    limit = jnp.minimum(len_ref[seg], pos_ref[t] + 1)

    @pl.when(j * block_size < limit)
    def _step():
        q = q_ref[0]                         # [H, D]
        k = k_ref[0]                         # [bs, Hkv, D]
        v = v_ref[0]                         # [bs, Hkv, D]
        hkv = k.shape[1]
        # plain 2-D dots for Mosaic: unroll the (static, small) KV-head
        # dim in Python instead of a 3-D batched dot_general
        parts = []
        for kvh in range(hkv):
            qh = q[kvh * rep:(kvh + 1) * rep, :]         # [rep, D]
            kh = k[:, kvh, :]                            # [bs, D]
            parts.append(jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))     # [rep, bs]
        s2 = (parts[0] if hkv == 1
              else jnp.concatenate(parts, axis=0)) * scale   # [H, bs]
        col = jax.lax.broadcasted_iota(jnp.int32, s2.shape, 1) \
            + j * block_size
        s2 = jnp.where(col < limit, s2, _NEG_INF)

        m_prev = m_ref[:, 0]                             # [H]
        m_new = jnp.maximum(m_prev, jnp.max(s2, axis=-1))
        alpha = jnp.exp(m_prev - m_new)                  # [H]
        p = jnp.exp(s2 - m_new[:, None])                 # [H, bs]
        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, -1)
        m_ref[:, 0] = m_new
        pv_parts = []
        for kvh in range(hkv):
            ph = p[kvh * rep:(kvh + 1) * rep, :]         # [rep, bs]
            vh = v[:, kvh, :]                            # [bs, D]
            pv_parts.append(jax.lax.dot_general(
                ph.astype(jnp.float32), vh.astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))     # [rep, D]
        pv = pv_parts[0] if hkv == 1 else jnp.concatenate(pv_parts, axis=0)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + pv

    @pl.when(j == n_pages - 1)
    def _finish():
        o_ref[0] = (acc_ref[:]
                    / jnp.maximum(l_ref[:, 0], np.float32(1e-9))[:, None]
                    ).astype(o_ref.dtype)


def _ragged_attention_kernel(q, k_cache, v_cache, block_tables, kv_lens,
                             seg_ids, q_pos):
    """Single-shard Pallas launch over the packed token batch (interpret
    mode off-TPU).  Under ``shard_map`` this runs per mp shard on the
    local head slice — the metadata operands are replicated, so the page
    walk is identical on every shard."""
    T, H, D = q.shape
    num_blocks, bs, Hkv, _ = k_cache.shape
    rep = H // Hkv
    n_pages = block_tables.shape[1]
    scale = 1.0 / math.sqrt(D)
    # Mosaic has no i64: scalar-prefetch operands must be 32-bit
    seg_ids = seg_ids.astype(jnp.int32)
    q_pos = q_pos.astype(jnp.int32)
    block_tables = block_tables.astype(jnp.int32)
    kv_lens = kv_lens.astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,   # seg_ids, q_pos, block_tables, kv_lens
        grid=(T, n_pages),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda t, j, seg, qp, bt, ln: (t, 0, 0)),
            # the scalar-prefetched table steers each page DMA through
            # the token's OWN row — the ragged gather never materializes
            pl.BlockSpec((1, bs, Hkv, D),
                         lambda t, j, seg, qp, bt, ln:
                         (bt[seg[t], j], 0, 0, 0)),
            pl.BlockSpec((1, bs, Hkv, D),
                         lambda t, j, seg, qp, bt, ln:
                         (bt[seg[t], j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, D),
                               lambda t, j, seg, qp, bt, ln: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, D), jnp.float32),    # acc
            pltpu.VMEM((H, 1), jnp.float32),    # running max
            pltpu.VMEM((H, 1), jnp.float32),    # running sum
        ],
    )
    kernel = functools.partial(
        _ragged_kernel, scale=scale, block_size=bs, n_pages=n_pages,
        rep=rep)
    with no_x64():
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((T, H, D), q.dtype),
            interpret=_interpret(),
        )(seg_ids, q_pos, block_tables, kv_lens, q, k_cache, v_cache)


def _mesh_kernel(q, k_cache, v_cache, block_tables, kv_lens, seg_ids,
                 q_pos):
    """The kernel, mesh-spanning when an ``mp`` axis is live: queries and
    pools shard along the head dim (``KV_POOL_SPEC``), routing metadata
    replicated, and each shard runs the single-shard kernel on its local
    head slice — per-head attention needs no collective; the engine's
    row-parallel output projection supplies the psum."""
    from ..distributed import topology

    mesh = topology.get_mesh()
    if (mesh is None or "mp" not in mesh.axis_names
            or mesh.shape["mp"] == 1
            or q.shape[1] % mesh.shape["mp"]
            or k_cache.shape[2] % mesh.shape["mp"]):
        return _ragged_attention_kernel(q, k_cache, v_cache, block_tables,
                                        kv_lens, seg_ids, q_pos)
    from jax.sharding import PartitionSpec as P

    from ..parallel._compat import shard_map
    from ..parallel.utils import manual_sharding_mode
    from .paged_attention import KV_POOL_SPEC

    mapped = shard_map(
        _ragged_attention_kernel, mesh=mesh,
        in_specs=(P(None, "mp", None), P(*KV_POOL_SPEC), P(*KV_POOL_SPEC),
                  P(), P(), P(), P()),
        out_specs=P(None, "mp", None), check_vma=False)
    with manual_sharding_mode():
        return mapped(q, k_cache, v_cache, block_tables, kv_lens,
                      seg_ids, q_pos)


def ragged_paged_attention(q, k_cache, v_cache, block_tables, kv_lens,
                           seg_ids, q_pos, use_pallas=None):
    """Packed ragged paged attention; returns ``[T, H, D]``.

    Dispatches to the Pallas kernel (``shard_map`` over ``mp`` when a
    mesh is live — the fast path spans the mesh instead of being pinned
    off at mp>1) when shapes are TPU-tileable; falls back to the XLA
    gather reference with a loud warning otherwise.  ``use_pallas``
    overrides the auto dispatch exactly like
    :func:`~paddle_tpu.ops.paged_attention.paged_attention`: ``True``
    forces the kernel (interpret mode off-TPU — the CPU parity path),
    ``False`` pins :func:`ragged_oracle`.  The operator kill switch
    (``PADDLE_TPU_DISABLE_PALLAS`` / ``disable_pallas_kernels``) still
    wins over ``use_pallas=True``
    (``paged_attention.pallas_dispatch`` is the one policy
    implementation both kernels share)."""
    global last_path
    from .paged_attention import pallas_dispatch

    T, H, D = q.shape
    tileable = D % 128 == 0 and k_cache.shape[1] % 8 == 0
    out, last_path = pallas_dispatch(
        lambda: _mesh_kernel(q, k_cache, v_cache, block_tables, kv_lens,
                             seg_ids, q_pos),
        lambda: ragged_oracle(q, k_cache, v_cache, block_tables, kv_lens,
                              seg_ids, q_pos),
        use_pallas, tileable, "pallas ragged paged attention")
    return out
