"""Paged (block) KV-cache attention for serving.

Capability analog of the reference's
``phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu`` (paged
KV-cache attention à la vLLM): the KV cache lives in fixed-size blocks
indexed per-sequence through a block table, so sequences share a global
block pool with no per-request contiguous allocation.

TPU-first: the cache pool is a dense ``[num_blocks, block_size, H, D]``
array updated with scatter writes (XLA keeps it resident in HBM and donates
the buffer between decode steps under jit); the gather of a sequence's
blocks is one ``take`` along the block dim — compiler-friendly static
shapes with a length mask instead of dynamic slicing.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class BlockKVCache:
    """Host-side block-pool manager (BlockTable bookkeeping is python; the
    cache tensors live on device)."""

    def __init__(self, num_blocks: int, block_size: int, num_heads: int,
                 head_dim: int, dtype=jnp.bfloat16):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.k_cache = jnp.zeros((num_blocks, block_size, num_heads, head_dim), dtype)
        self.v_cache = jnp.zeros((num_blocks, block_size, num_heads, head_dim), dtype)
        self._free = list(range(num_blocks - 1, 0, -1))  # block 0 = null page
        self.block_tables = {}  # seq_id -> list[int]
        self.seq_lens = {}      # seq_id -> int

    def allocate(self, seq_id: int, num_tokens: int):
        """Reserve enough blocks for ``num_tokens`` more tokens."""
        table = self.block_tables.setdefault(seq_id, [])
        cur = self.seq_lens.get(seq_id, 0)
        need = -(-(cur + num_tokens) // self.block_size) - len(table)
        for _ in range(need):
            if not self._free:
                raise RuntimeError("KV cache pool exhausted")
            table.append(self._free.pop())
        return table

    def free(self, seq_id: int):
        for b in self.block_tables.pop(seq_id, []):
            self._free.append(b)
        self.seq_lens.pop(seq_id, None)

    def write(self, seq_id: int, k: jax.Array, v: jax.Array):
        """Append [T, H, D] keys/values for one sequence."""
        T = k.shape[0]
        start = self.seq_lens.get(seq_id, 0)
        table = self.allocate(seq_id, T)
        pos = np.arange(start, start + T)
        blocks = np.asarray([table[p // self.block_size] for p in pos])
        offs = pos % self.block_size
        self.k_cache = self.k_cache.at[blocks, offs].set(k.astype(self.k_cache.dtype))
        self.v_cache = self.v_cache.at[blocks, offs].set(v.astype(self.v_cache.dtype))
        self.seq_lens[seq_id] = start + T

    def gather_view(self, seq_ids, max_blocks: Optional[int] = None):
        """Dense [B, max_blocks] block table + [B] lengths for the kernel."""
        if max_blocks is None:
            max_blocks = max(len(self.block_tables[s]) for s in seq_ids)
        bt = np.zeros((len(seq_ids), max_blocks), np.int32)
        lens = np.zeros((len(seq_ids),), np.int32)
        for i, s in enumerate(seq_ids):
            t = self.block_tables[s]
            bt[i, :len(t)] = t
            lens[i] = self.seq_lens[s]
        return jnp.asarray(bt), jnp.asarray(lens)


# Which path the most recent dispatch took: "pallas" | "xla" (same loud
# fallback contract as ops/flash_attention.py).
last_path: Optional[str] = None


class PagedCache:
    """Per-layer view of the shared block pool, handed to the model's
    attention as its ``cache`` (the model writes K/V into the slot and
    attends through the block table).  ``k_pool``/``v_pool`` are framework
    Tensors [num_blocks, block_size, Hkv, D] so the scatter write threads
    as jit state; the routing arrays are refreshed by the serving loop
    before each decode step."""

    def __init__(self, k_pool, v_pool):
        self.k_pool = k_pool
        self.v_pool = v_pool
        self.block_tables = None   # [B, max_blocks] int32
        self.seq_lens = None       # [B] int32 (AFTER this step's token)
        self.slot_blocks = None    # [B] int32 — page of this step's token
        self.slot_offsets = None   # [B] int32 — offset within the page

    def route(self, block_tables, seq_lens, slot_blocks, slot_offsets):
        self.block_tables = jnp.asarray(block_tables, jnp.int32)
        self.seq_lens = jnp.asarray(seq_lens, jnp.int32)
        self.slot_blocks = jnp.asarray(slot_blocks, jnp.int32)
        self.slot_offsets = jnp.asarray(slot_offsets, jnp.int32)


def _xla_paged_attention(q, k_cache, v_cache, block_tables, seq_lens):
    """XLA gather path: materializes the padded [B, S, H, D] context (GQA
    via grouped einsum, KV never head-repeated)."""
    B, H, D = q.shape
    max_blocks = block_tables.shape[1]
    bs = k_cache.shape[1]
    Hkv = k_cache.shape[2]
    rep = H // Hkv
    scale = 1.0 / math.sqrt(D)

    # gather each sequence's pages: [B, max_blocks, bs, Hkv, D] → [B, S, Hkv, D]
    k = k_cache[block_tables].reshape(B, max_blocks * bs, Hkv, D)
    v = v_cache[block_tables].reshape(B, max_blocks * bs, Hkv, D)

    qg = q.reshape(B, Hkv, rep, D)
    logits = jnp.einsum("bhrd,bshd->bhrs", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_blocks * bs)[None, None, None, :]
    mask = pos < seq_lens[:, None, None, None]
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhrs,bshd->bhrd", probs, v.astype(jnp.float32))
    return out.reshape(B, H, D).astype(q.dtype)


def paged_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                    block_tables: jax.Array, seq_lens: jax.Array) -> jax.Array:
    """Decode-step attention over a paged KV cache.

    q: [B, H, D] (one new token per sequence); k/v_cache:
    [num_blocks, block_size, Hkv, D]; block_tables: [B, max_blocks] int32;
    seq_lens: [B] int32.  Returns [B, H, D].

    Dispatches to the Pallas kernel (``pallas_paged.py`` — scalar-prefetch
    page DMA, no dense context copy) when shapes are TPU-tileable; falls
    back to the XLA gather path with a loud warning otherwise.
    """
    import os

    global last_path
    from ..core import flags

    B, H, D = q.shape
    disable = (os.environ.get("PADDLE_TPU_DISABLE_PALLAS") == "1"
               or flags.flag("disable_pallas_kernels"))
    tileable = D % 128 == 0 and k_cache.shape[1] % 8 == 0
    if not disable and tileable:
        try:
            from .pallas_paged import paged_attention_decode

            out = paged_attention_decode(q, k_cache, v_cache,
                                         block_tables, seq_lens)
            last_path = "pallas"
            return out
        except Exception as e:
            import warnings

            if (os.environ.get("PADDLE_TPU_STRICT_PALLAS") == "1"
                    or flags.flag("strict_pallas")):
                raise
            warnings.warn(
                f"pallas paged attention failed, falling back to the XLA "
                f"gather path: {type(e).__name__}: {e}",
                RuntimeWarning, stacklevel=2)
    last_path = "xla"
    return _xla_paged_attention(q, k_cache, v_cache, block_tables, seq_lens)
