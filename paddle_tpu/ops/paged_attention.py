"""Paged (block) KV-cache attention for serving.

Capability analog of the reference's
``phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu`` (paged
KV-cache attention à la vLLM): the KV cache lives in fixed-size blocks
indexed per-sequence through a block table, so sequences share a global
block pool with no per-request contiguous allocation.

TPU-first: the cache pool is a dense ``[num_blocks, block_size, H, D]``
array updated with scatter writes (XLA keeps it resident in HBM and donates
the buffer between decode steps under jit); the gather of a sequence's
blocks is one ``take`` along the block dim — compiler-friendly static
shapes with a length mask instead of dynamic slicing.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class PoolExhausted(RuntimeError):
    """The shared KV block pool has no free block for the request.

    A *typed* RuntimeError so serving layers can catch it and degrade
    gracefully (preempt + recompute, ``serving/kv_manager.py``) instead of
    failing the request."""


class BlockPool:
    """Refcounted block-pool bookkeeping (no device tensors) — the ONE
    implementation of the free-list / refcount / fork invariants, shared
    by :class:`BlockKVCache` (op layer) and the serving layer's
    :class:`~paddle_tpu.serving.KVCacheManager`.  Block 0 is the reserved
    null page that padding rows of a bucketed batch write into."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is the null page)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: list = list(range(num_blocks - 1, 0, -1))
        self._ref: dict = {}     # block -> owner count (shared prefixes)
        self._tables: dict = {}  # seq_id -> list[int]
        self._lens: dict = {}    # seq_id -> int

    @property
    def num_free(self) -> int:
        return len(self._free)

    def blocks_for(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)

    def blocks_needed(self, seq_id, num_tokens: int) -> int:
        cur = self._lens.get(seq_id, 0)
        held = len(self._tables.get(seq_id, ()))
        return max(0, self.blocks_for(cur + num_tokens) - held)

    def can_allocate(self, seq_id, num_tokens: int) -> bool:
        return self.blocks_needed(seq_id, num_tokens) <= len(self._free)

    def allocate(self, seq_id, num_tokens: int) -> bool:
        """All-or-nothing reservation of blocks for ``num_tokens`` more
        tokens; returns False (taking nothing) when the pool can't cover
        it, so the state stays clean for the caller's preemption/retry."""
        need = self.blocks_needed(seq_id, num_tokens)
        if need > len(self._free):
            return False
        table = self._tables.setdefault(seq_id, [])
        for _ in range(need):
            b = self._free.pop()
            self._ref[b] = 1
            table.append(b)
        return True

    def fork(self, src_seq, dst_seq) -> int:
        """Share ``src_seq``'s FULL blocks with ``dst_seq`` (refcount++, no
        copy).  Only whole blocks are shared — appends always land in
        blocks the destination owns alone, so no copy-on-write is ever
        needed.  Returns the number of tokens ``dst_seq`` starts with."""
        if dst_seq in self._tables:
            raise ValueError(f"fork target seq {dst_seq!r} already exists")
        n_full = self._lens.get(src_seq, 0) // self.block_size
        shared = self._tables.get(src_seq, [])[:n_full]
        for b in shared:
            self._ref[b] = self._ref.get(b, 1) + 1
        self._tables[dst_seq] = list(shared)
        self._lens[dst_seq] = n_full * self.block_size
        return n_full * self.block_size

    def free(self, seq_id) -> int:
        """Release the sequence; returns how many blocks went back to the
        pool (shared blocks stay out until their last owner frees)."""
        returned = 0
        for b in self._tables.pop(seq_id, []):
            n = self._ref.get(b, 1) - 1
            if n <= 0:
                self._ref.pop(b, None)
                self._free.append(b)
                returned += 1
            else:
                self._ref[b] = n
        self._lens.pop(seq_id, None)
        return returned


class BlockKVCache:
    """Host-side block-pool manager (BlockTable bookkeeping is python; the
    cache tensors live on device).

    Blocks are reference-counted so sequences can share a prefix without
    copying (``fork``): a shared block returns to the free list only when
    its last owner frees it — the copy-on-write-free reuse hook the
    serving layer's :class:`~paddle_tpu.serving.KVCacheManager` builds on.
    Bookkeeping is delegated to one shared :class:`BlockPool`; the public
    ``block_tables``/``seq_lens``/``_free`` attributes alias its state."""

    def __init__(self, num_blocks: int, block_size: int, num_heads: int,
                 head_dim: int, dtype=jnp.bfloat16):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.k_cache = jnp.zeros((num_blocks, block_size, num_heads, head_dim), dtype)
        self.v_cache = jnp.zeros((num_blocks, block_size, num_heads, head_dim), dtype)
        self._pool = BlockPool(num_blocks, block_size)
        self._free = self._pool._free        # same objects, mutated in place
        self._ref = self._pool._ref
        self.block_tables = self._pool._tables
        self.seq_lens = self._pool._lens

    def blocks_needed(self, seq_id: int, num_tokens: int) -> int:
        return self._pool.blocks_needed(seq_id, num_tokens)

    def can_allocate(self, seq_id: int, num_tokens: int) -> bool:
        return self._pool.can_allocate(seq_id, num_tokens)

    def allocate(self, seq_id: int, num_tokens: int):
        """Reserve enough blocks for ``num_tokens`` more tokens.

        All-or-nothing: on exhaustion raises :class:`PoolExhausted`
        WITHOUT having taken any block, so the pool state stays clean for
        the caller's preemption/retry policy (``try_allocate`` is the
        non-raising form)."""
        if not self._pool.allocate(seq_id, num_tokens):
            raise PoolExhausted(
                f"KV cache pool exhausted: seq {seq_id} needs "
                f"{self._pool.blocks_needed(seq_id, num_tokens)} block(s), "
                f"{len(self._free)} free — free or preempt a sequence and "
                "retry")
        return self.block_tables[seq_id]

    def try_allocate(self, seq_id: int, num_tokens: int):
        """``allocate`` returning ``None`` instead of raising on exhaustion."""
        if not self._pool.allocate(seq_id, num_tokens):
            return None
        return self.block_tables[seq_id]

    def fork(self, src_seq: int, dst_seq: int) -> int:
        return self._pool.fork(src_seq, dst_seq)

    def free(self, seq_id: int):
        self._pool.free(seq_id)

    def write(self, seq_id: int, k: jax.Array, v: jax.Array):
        """Append [T, H, D] keys/values for one sequence."""
        T = k.shape[0]
        start = self.seq_lens.get(seq_id, 0)
        table = self.allocate(seq_id, T)
        pos = np.arange(start, start + T)
        blocks = np.asarray([table[p // self.block_size] for p in pos])
        offs = pos % self.block_size
        self.k_cache = self.k_cache.at[blocks, offs].set(k.astype(self.k_cache.dtype))
        self.v_cache = self.v_cache.at[blocks, offs].set(v.astype(self.v_cache.dtype))
        self.seq_lens[seq_id] = start + T

    def gather_view(self, seq_ids, max_blocks: Optional[int] = None):
        """Dense [B, max_blocks] block table + [B] lengths for the kernel."""
        if max_blocks is None:
            max_blocks = max(len(self.block_tables[s]) for s in seq_ids)
        bt = np.zeros((len(seq_ids), max_blocks), np.int32)
        lens = np.zeros((len(seq_ids),), np.int32)
        for i, s in enumerate(seq_ids):
            t = self.block_tables[s]
            bt[i, :len(t)] = t
            lens[i] = self.seq_lens[s]
        return jnp.asarray(bt), jnp.asarray(lens)


# Which path the most recent dispatch took: "pallas" | "xla" (same loud
# fallback contract as ops/flash_attention.py).
last_path: Optional[str] = None


class PagedCache:
    """Per-layer view of the shared block pool, handed to the model's
    attention as its ``cache`` (the model writes K/V into the slot and
    attends through the block table).  ``k_pool``/``v_pool`` are framework
    Tensors [num_blocks, block_size, Hkv, D] so the scatter write threads
    as jit state; the routing arrays are refreshed by the serving loop
    before each decode step."""

    def __init__(self, k_pool, v_pool):
        self.k_pool = k_pool
        self.v_pool = v_pool
        self.block_tables = None   # [B, max_blocks] int32
        self.seq_lens = None       # [B] int32 (AFTER this step's token)
        self.slot_blocks = None    # [B] int32 — page of this step's token
        self.slot_offsets = None   # [B] int32 — offset within the page

    def route(self, block_tables, seq_lens, slot_blocks, slot_offsets):
        self.block_tables = jnp.asarray(block_tables, jnp.int32)
        self.seq_lens = jnp.asarray(seq_lens, jnp.int32)
        self.slot_blocks = jnp.asarray(slot_blocks, jnp.int32)
        self.slot_offsets = jnp.asarray(slot_offsets, jnp.int32)


def _xla_paged_attention(q, k_cache, v_cache, block_tables, seq_lens):
    """XLA gather path: materializes the padded [B, S, H, D] context (GQA
    via grouped einsum, KV never head-repeated)."""
    B, H, D = q.shape
    max_blocks = block_tables.shape[1]
    bs = k_cache.shape[1]
    Hkv = k_cache.shape[2]
    rep = H // Hkv
    scale = 1.0 / math.sqrt(D)

    # gather each sequence's pages: [B, max_blocks, bs, Hkv, D] → [B, S, Hkv, D]
    k = k_cache[block_tables].reshape(B, max_blocks * bs, Hkv, D)
    v = v_cache[block_tables].reshape(B, max_blocks * bs, Hkv, D)

    qg = q.reshape(B, Hkv, rep, D)
    logits = jnp.einsum("bhrd,bshd->bhrs", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_blocks * bs)[None, None, None, :]
    mask = pos < seq_lens[:, None, None, None]
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhrs,bshd->bhrd", probs, v.astype(jnp.float32))
    return out.reshape(B, H, D).astype(q.dtype)


def paged_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                    block_tables: jax.Array, seq_lens: jax.Array) -> jax.Array:
    """Decode-step attention over a paged KV cache.

    q: [B, H, D] (one new token per sequence); k/v_cache:
    [num_blocks, block_size, Hkv, D]; block_tables: [B, max_blocks] int32;
    seq_lens: [B] int32.  Returns [B, H, D].

    Dispatches to the Pallas kernel (``pallas_paged.py`` — scalar-prefetch
    page DMA, no dense context copy) when shapes are TPU-tileable; falls
    back to the XLA gather path with a loud warning otherwise.
    """
    import os

    global last_path
    from ..core import flags

    B, H, D = q.shape
    disable = (os.environ.get("PADDLE_TPU_DISABLE_PALLAS") == "1"
               or flags.flag("disable_pallas_kernels"))
    tileable = D % 128 == 0 and k_cache.shape[1] % 8 == 0
    if not disable and tileable:
        try:
            from .pallas_paged import paged_attention_decode

            out = paged_attention_decode(q, k_cache, v_cache,
                                         block_tables, seq_lens)
            last_path = "pallas"
            return out
        except Exception as e:
            import warnings

            if (os.environ.get("PADDLE_TPU_STRICT_PALLAS") == "1"
                    or flags.flag("strict_pallas")):
                raise
            warnings.warn(
                f"pallas paged attention failed, falling back to the XLA "
                f"gather path: {type(e).__name__}: {e}",
                RuntimeWarning, stacklevel=2)
    last_path = "xla"
    return _xla_paged_attention(q, k_cache, v_cache, block_tables, seq_lens)
