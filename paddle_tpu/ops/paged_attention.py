"""Paged (block) KV-cache attention for serving.

Capability analog of the reference's
``phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu`` (paged
KV-cache attention à la vLLM): the KV cache lives in fixed-size blocks
indexed per-sequence through a block table, so sequences share a global
block pool with no per-request contiguous allocation.

TPU-first: the cache pool is a dense ``[num_blocks, block_size, H, D]``
array updated with scatter writes (XLA keeps it resident in HBM and donates
the buffer between decode steps under jit); the gather of a sequence's
blocks is one ``take`` along the block dim — compiler-friendly static
shapes with a length mask instead of dynamic slicing.

Multi-chip (ISSUE 5): the pool tensors shard along the **head** dim over
the ``mp`` mesh axis (:func:`shard_kv_pool`) while every bookkeeping
structure — block tables, free list, refcounts, hashes — stays host-side
and replicated: one block index means the same page on every shard, so a
single scheduler decision routes N shards and only the per-block byte
footprint divides by mp.
"""

from __future__ import annotations

import hashlib
import math
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class PoolExhausted(RuntimeError):
    """The shared KV block pool has no free block for the request.

    A *typed* RuntimeError so serving layers can catch it and degrade
    gracefully (preempt + recompute, ``serving/kv_manager.py``) instead of
    failing the request."""


#: Root of every block-hash chain (the hash of the empty prefix).
#: Chains use SHA-256, not builtin ``hash()``: cached blocks are content-
#: addressed across tenants, so a collision silently serves one prompt's
#: KV to another — with a 64-bit non-cryptographic hash that is both
#: reachable at volume and constructible by an adversarial prompt.
_HASH_ROOT = hashlib.sha256(b"paddle_tpu.prefix_cache.v1").digest()


def _hash_block(parent: bytes, block_tokens) -> bytes:
    m = hashlib.sha256(parent)
    m.update(b"".join(int(t).to_bytes(8, "little", signed=True)
                      for t in block_tokens))
    return m.digest()


def prefix_chain_hashes(token_ids, block_size: int,
                        max_blocks: Optional[int] = None) -> List[bytes]:
    """Chain hashes of the leading FULL blocks of ``token_ids`` —
    ``out[i]`` commits to every token in blocks ``0..i`` (the same
    ``h_i = sha256(h_{i-1} || block_tokens_i)`` chain the prefix cache
    registers).  This is the shareable form of the hash walk: a router
    can compute it ONCE per request for prefix-affinity placement and
    hand it to :meth:`BlockPool.match_prefix` via ``precomputed=`` so
    admission does not re-hash the same leading blocks."""
    n = len(token_ids) // block_size
    if max_blocks is not None:
        n = min(n, max_blocks)
    out: List[bytes] = []
    h = _HASH_ROOT
    for i in range(n):
        h = _hash_block(h, token_ids[i * block_size:(i + 1) * block_size])
        out.append(h)
    return out


class BlockPool:
    """Refcounted block-pool bookkeeping (no device tensors) — the ONE
    implementation of the free-list / refcount / fork invariants, shared
    by :class:`BlockKVCache` (op layer) and the serving layer's
    :class:`~paddle_tpu.serving.KVCacheManager`.  Block 0 is the reserved
    null page that padding rows of a bucketed batch write into.

    **Prefix caching** (``enable_prefix_cache=True``): a FULL block whose
    content is the KV of a known token chain carries a chain hash
    ``h_i = sha256(h_{i-1} || block_tokens_i)`` registered via
    :meth:`record_block_hashes`.  When its last owner frees it, the block
    parks in a reuse LRU instead of the free list — content intact,
    revivable by :meth:`fork_prefix` at zero recompute cost — and is
    evicted (clobbered) only when an allocation cannot be covered by the
    free list alone.  All hash/LRU structures are bounded by the pool
    itself: at most ``num_blocks`` entries each, ever.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 enable_prefix_cache: bool = False):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is the null page)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.prefix_cache_enabled = enable_prefix_cache
        self._free: list = list(range(num_blocks - 1, 0, -1))
        self._ref: dict = {}     # block -> owner count (shared prefixes)
        self._tables: dict = {}  # seq_id -> list[int]
        self._lens: dict = {}    # seq_id -> int
        # prefix-cache state — every structure is pool-bounded (≤ one
        # entry per block), enforced by the invariants above
        self._block_hash: dict = {}   # unbounded-ok: ≤ num_blocks entries (block -> chain hash)
        self._hash_index: dict = {}   # unbounded-ok: ≤ num_blocks entries (chain hash -> block)
        self._chain_state: dict = {}  # unbounded-ok: ≤ live seqs (seq -> (blocks_hashed, last_hash)) so per-chunk re-registration hashes only NEW blocks
        self.cache_epoch = 0  # bumped whenever _hash_index changes, so
                              # callers may memoize match_prefix results
                              # keyed by (token_ids, epoch)
        self._reuse: "OrderedDict" = OrderedDict()  # unbounded-ok: ≤ num_blocks entries (refcount-0 cached blocks, LRU)
        self.reuse_evictions = 0  # monotonic: cached blocks clobbered for allocation
        self.reuse_hits = 0       # monotonic: blocks served from the prefix cache
        # --- observability hooks (ISSUE 13) --------------------------------
        # host-side, fired synchronously on the mutating thread; a hook
        # exception is swallowed — telemetry must never tear the pool's
        # free-list/refcount bookkeeping mid-mutation.
        self.on_evict = None   # fn(block, chain_depth, lifetime_steps, cause)
        self.on_revive = None  # fn(block, chain_depth, lru_depth, lifetime_steps)
        self.clock = 0         # caller-advanced step clock (the serving
                               # engine stamps step_seq) — park lifetimes
                               # are measured in these ticks
        self._block_depth: dict = {}  # unbounded-ok: ≤ num_blocks entries (block -> chain depth)
        self._park_step: dict = {}    # unbounded-ok: ≤ num_blocks entries (block -> clock at refcount-0 park)
        # --- block transfer (ISSUE 20) -------------------------------------
        # per-registered-block content identity: the tokens the chain hash
        # committed to, and the parent digest — what export_blocks /
        # export_chain serialize so a RECIPIENT pool can re-verify the
        # chain from _HASH_ROOT before admitting foreign KV content
        self._block_tokens: dict = {}  # unbounded-ok: ≤ num_blocks entries (block -> token tuple)
        self._block_parent: dict = {}  # unbounded-ok: ≤ num_blocks entries (block -> parent chain hash)

    @property
    def num_free(self) -> int:
        """Blocks on the free list proper (never held cached content)."""
        return len(self._free)

    @property
    def num_available(self) -> int:
        """Blocks an allocation can take: free list + evictable reuse LRU.
        The capacity number schedulers must plan against — a drained pool
        with a warm prefix cache has ``num_free < num_available``."""
        return len(self._free) + len(self._reuse)

    def blocks_for(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)

    def blocks_needed(self, seq_id, num_tokens: int) -> int:
        cur = self._lens.get(seq_id, 0)
        held = len(self._tables.get(seq_id, ()))
        return max(0, self.blocks_for(cur + num_tokens) - held)

    def can_allocate(self, seq_id, num_tokens: int) -> bool:
        return self.blocks_needed(seq_id, num_tokens) <= self.num_available

    def _take_block(self, cause: str = "other") -> int:
        """One block for a fresh allocation: free list first; then evict
        the LRU-oldest reusable cached block (its hash entries die with
        its content — a later prompt with that prefix just recomputes).
        An eviction fires :attr:`on_evict` with the clobbered block's
        chain depth, its park lifetime (in :attr:`clock` ticks), and the
        ``cause`` of the allocation (ISSUE 13 event-driven accounting —
        no more per-step counter diffing)."""
        if self._free:
            return self._free.pop()
        b, _ = self._reuse.popitem(last=False)
        depth = self._block_depth.get(b, 0)
        lifetime = self.clock - self._park_step.pop(b, self.clock)
        self._drop_hash(b)
        self.reuse_evictions += 1
        cb = self.on_evict
        if cb is not None:
            try:
                cb(b, depth, lifetime, cause)
            except Exception:
                pass  # swallow-ok: telemetry must never tear the pool bookkeeping mid-allocation
        return b

    def _drop_hash(self, b: int) -> None:
        h = self._block_hash.pop(b, None)
        self._block_depth.pop(b, None)
        self._block_tokens.pop(b, None)
        self._block_parent.pop(b, None)
        if h is not None and self._hash_index.get(h) == b:
            del self._hash_index[h]
            self.cache_epoch += 1

    def allocate(self, seq_id, num_tokens: int,
                 cause: str = "other") -> bool:
        """All-or-nothing reservation of blocks for ``num_tokens`` more
        tokens; returns False (taking nothing) when the pool can't cover
        it, so the state stays clean for the caller's preemption/retry.
        ``cause`` labels any reuse-LRU eviction this allocation forces
        (``decode_slot`` / ``prefill_chunk`` / ``other``)."""
        need = self.blocks_needed(seq_id, num_tokens)
        if need > self.num_available:
            return False
        table = self._tables.setdefault(seq_id, [])
        for _ in range(need):
            b = self._take_block(cause)
            self._ref[b] = 1
            table.append(b)
        return True

    def fork(self, src_seq, dst_seq) -> int:
        """Share ``src_seq``'s FULL blocks with ``dst_seq`` (refcount++, no
        copy).  Only whole blocks are shared — appends always land in
        blocks the destination owns alone, so no copy-on-write is ever
        needed.  Returns the number of tokens ``dst_seq`` starts with."""
        if dst_seq in self._tables:
            raise ValueError(f"fork target seq {dst_seq!r} already exists")
        n_full = self._lens.get(src_seq, 0) // self.block_size
        shared = self._tables.get(src_seq, [])[:n_full]
        for b in shared:
            self._ref[b] = self._ref.get(b, 1) + 1
        self._tables[dst_seq] = list(shared)
        self._lens[dst_seq] = n_full * self.block_size
        return n_full * self.block_size

    def free(self, seq_id) -> int:
        """Release the sequence; returns how many blocks became available
        again (shared blocks stay out until their last owner frees).  With
        the prefix cache on, a hashed block parks in the reuse LRU instead
        of the free list — still counted as available, but revivable.
        Within one sequence, later-chain blocks enter the LRU eviction
        side first, so a shrinking cache keeps the shortest (most
        shareable) prefixes longest."""
        returned = 0
        for b in reversed(self._tables.pop(seq_id, [])):
            n = self._ref.get(b, 1) - 1
            if n > 0:
                self._ref[b] = n
                continue
            self._ref.pop(b, None)
            returned += 1
            if self.prefix_cache_enabled and b in self._block_hash:
                self._reuse[b] = self._block_hash[b]
                self._park_step[b] = self.clock  # lifetime starts here
            else:
                self._free.append(b)
        self._lens.pop(seq_id, None)
        self._chain_state.pop(seq_id, None)
        return returned

    # --- prefix cache -------------------------------------------------------
    def match_prefix(self, token_ids,
                     precomputed: Optional[List[bytes]] = None) -> List[int]:
        """Blocks holding the longest cached block-prefix of ``token_ids``,
        capped so at least ONE token is always left to compute (the
        prefill must still produce last-token logits).  The chain hash
        ``h_i`` commits to every token in blocks 0..i, so one dict lookup
        per block walks the prefix — hashing stops at the first miss (a
        cold cache costs ONE block hash, not the whole prompt).

        ``precomputed`` (optional) carries leading chain hashes already
        computed elsewhere over the SAME leading tokens — e.g. the fleet
        router's prefix-affinity key (:func:`prefix_chain_hashes`) — so
        block ``i < len(precomputed)`` skips its hash; the walk resumes
        the chain from the last precomputed digest."""
        if not self.prefix_cache_enabled or len(token_ids) < 2:
            return []
        limit = (len(token_ids) - 1) // self.block_size
        bs = self.block_size
        blocks, h = [], _HASH_ROOT
        for i in range(limit):
            if precomputed is not None and i < len(precomputed):
                h = precomputed[i]
            else:
                h = _hash_block(h, token_ids[i * bs:(i + 1) * bs])
            b = self._hash_index.get(h)
            if b is None:
                break
            blocks.append(b)
        return blocks

    def reuse_count(self, blocks) -> int:
        """How many of ``blocks`` sit in the reuse LRU (refcount 0) —
        those leave the available set when forked, so schedulers must
        budget ``uncached_need + reuse_count``."""
        return sum(1 for b in blocks if b in self._reuse)

    def probe_prefix(self, token_ids) -> Tuple[int, int]:
        """(hit_blocks, of_which_from_reuse) for admission planning — no
        state change."""
        blocks = self.match_prefix(token_ids)
        return len(blocks), self.reuse_count(blocks)

    def fork_prefix(self, seq_id, token_ids, blocks: Optional[List[int]] = None) -> int:
        """Start ``seq_id`` on the longest cached block-prefix of
        ``token_ids``: live cached blocks gain an owner (refcount++),
        reuse-LRU blocks are revived (refcount 0 → 1) — zero recompute
        either way.  Returns the number of cached tokens the sequence
        starts with (0 on a cold miss or with the cache disabled).
        ``blocks`` skips re-hashing when the caller just ran
        :meth:`match_prefix` with NO pool mutation in between (admission
        probes then forks in one pass)."""
        if seq_id in self._tables:
            raise ValueError(f"fork target seq {seq_id!r} already exists")
        if blocks is None:
            blocks = self.match_prefix(token_ids)
        if blocks:
            self._chain_state[seq_id] = (
                len(blocks), self._block_hash[blocks[-1]])
        cb = self.on_revive
        # LRU position of each parked block BEFORE any revival mutates
        # the order: index 0 = the eviction end (would have been
        # clobbered by the very next allocation) — what the hit-depth
        # histogram records (ISSUE 13).  Built only when a subscriber
        # exists and a revive is possible: the O(len(_reuse)) walk must
        # not tax hook-less pool users or cold-miss forks.
        lru_order = ({b: i for i, b in enumerate(self._reuse)}
                     if cb is not None and blocks else None)
        for i, b in enumerate(blocks):
            if b in self._reuse:
                del self._reuse[b]
                self._ref[b] = 1
                lifetime = self.clock - self._park_step.pop(b, self.clock)
                if cb is not None:
                    try:
                        cb(b, self._block_depth.get(b, i + 1),
                           lru_order[b], lifetime)
                    except Exception:
                        pass  # swallow-ok: telemetry must never tear the pool bookkeeping mid-revive
            else:
                self._ref[b] = self._ref.get(b, 0) + 1
        self.reuse_hits += len(blocks)
        self._tables[seq_id] = list(blocks)
        self._lens[seq_id] = len(blocks) * self.block_size
        return len(blocks) * self.block_size

    def record_block_hashes(self, seq_id, token_ids,
                            num_tokens: Optional[int] = None) -> int:
        """Index ``seq_id``'s full blocks covered by the first
        ``num_tokens`` of ``token_ids`` (default: all — only tokens whose
        KV has been WRITTEN: callers register after the compute that fills
        the pages).  Idempotent; first block to claim a chain hash keeps
        it.  Returns how many new blocks were indexed.

        Incremental: the per-sequence chain state remembers how far this
        sequence has already been hashed, so a chunked prefill that
        registers after every chunk hashes each block ONCE over the whole
        prompt, not once per chunk (O(L) total, not O(L²))."""
        if not self.prefix_cache_enabled:
            return 0
        table = self._tables.get(seq_id, [])
        upto = len(token_ids) if num_tokens is None else num_tokens
        n_full = min(upto // self.block_size, len(table))
        done, h = self._chain_state.get(seq_id, (0, _HASH_ROOT))
        if done > n_full:  # recompute path restarted shorter: re-walk
            done, h = 0, _HASH_ROOT
        bs = self.block_size
        added = 0
        for i in range(done, n_full):
            parent = h
            h = _hash_block(h, token_ids[i * bs:(i + 1) * bs])
            b = table[i]
            if b in self._block_hash or h in self._hash_index:
                continue
            self._block_hash[b] = h
            self._block_depth[b] = i + 1  # chain depth in blocks
            self._block_tokens[b] = tuple(
                int(t) for t in token_ids[i * bs:(i + 1) * bs])
            self._block_parent[b] = parent
            self._hash_index[h] = b
            added += 1
        self._chain_state[seq_id] = (n_full, h)
        if added:
            self.cache_epoch += 1
        return added

    def block_chain_hash(self, block: int) -> Optional[bytes]:
        """Chain hash registered for ``block`` (``None`` when unhashed)
        — the prefix-heat table's key (ISSUE 13): the DEEPEST matched
        block's hash commits to the whole cached prefix."""
        return self._block_hash.get(block)

    def block_chain_depth(self, block: int) -> int:
        """Chain depth (in blocks) ``block`` was registered at; 0 when
        unhashed."""
        return self._block_depth.get(block, 0)

    # --- block transfer (ISSUE 20) ------------------------------------------
    def export_blocks(self, hashes) -> Optional[List[dict]]:
        """Serialize the pool-side metadata of the chain addressed by
        ``hashes`` (leading chain digests, root-first — the shape
        :func:`prefix_chain_hashes` produces).  Returns one record per
        block — ``{"hash", "depth", "tokens", "block"}`` — or ``None``
        when any hash is unindexed (nothing to transfer; the recipient
        just recomputes).  Pure read: no pool mutation, no refcount
        change — the caller gathers the device payload at the returned
        ``block`` indices while the donor keeps serving."""
        records: List[dict] = []
        for h in hashes:
            b = self._hash_index.get(h)
            if b is None:
                return None
            tokens = self._block_tokens.get(b)
            if tokens is None:
                return None
            records.append({"hash": h, "depth": self._block_depth.get(b, 0),
                            "tokens": tokens, "block": b})
        return records

    def export_chain(self, chain_hash: bytes) -> Optional[List[dict]]:
        """Like :meth:`export_blocks` but addressed by the DEEPEST chain
        digest alone (the prefix-heat table's key): walks parent links
        back to the root and returns the full leading chain, root-first.
        ``None`` when the chain is broken (an ancestor was evicted)."""
        out: List[dict] = []
        h = chain_hash
        while h != _HASH_ROOT:
            b = self._hash_index.get(h)
            if b is None:
                return None
            tokens = self._block_tokens.get(b)
            parent = self._block_parent.get(b)
            if tokens is None or parent is None:
                return None
            out.append({"hash": h, "depth": self._block_depth.get(b, 0),
                        "tokens": tokens, "block": b})
            h = parent
        out.reverse()
        return out

    def chain_lead(self, chain_hash: bytes) -> Optional[List[bytes]]:
        """Leading chain digests, root-first, of the indexed chain
        ending at ``chain_hash`` — the affinity-key material a router
        needs to recompute ring placement for a cached prefix without
        the prompt tokens.  ``None`` when the chain is broken (an
        ancestor was evicted).  Pure read."""
        out: List[bytes] = []
        h = chain_hash
        while h != _HASH_ROOT:
            b = self._hash_index.get(h)
            if b is None:
                return None
            parent = self._block_parent.get(b)
            if parent is None:
                return None
            out.append(h)
            h = parent
        out.reverse()
        return out

    def import_blocks(self, records) -> Optional[Dict[bytes, int]]:
        """Admit a foreign block run (the :meth:`export_blocks` record
        shape, root-first) into THIS pool's prefix cache.  The chain is
        re-verified from ``_HASH_ROOT`` over the shipped tokens before
        anything mutates — a digest mismatch raises ``ValueError`` and
        the pool is untouched (content addressing must never trust the
        sender).  Atomic all-or-nothing: returns ``None`` (no mutation)
        when the fresh blocks outnumber ``num_available``; otherwise
        every fresh block is taken, registered, and parked in the reuse
        LRU (refcount 0, revivable by :meth:`fork_prefix` exactly like a
        locally-computed prefix), and the ``{hash: block}`` placement map
        is returned so the caller scatters the KV payload into those
        pages.  Already-indexed hashes are skipped (idempotent).

        Pool invariants hold throughout: blocks move free→reuse only, so
        ``free + reuse + allocated == num_blocks`` is preserved.  Known
        benign edge: under pressure, taking a block may evict a reuse-LRU
        ancestor of this very chain — the imported deeper blocks then sit
        unreachable until re-imported (wasted space, never corruption)."""
        if not self.prefix_cache_enabled:
            raise ValueError("import_blocks needs the prefix cache enabled")
        h = _HASH_ROOT
        parent_of: Dict[bytes, bytes] = {}
        for i, rec in enumerate(records):
            tokens = tuple(int(t) for t in rec["tokens"])
            if len(tokens) != self.block_size:
                raise ValueError(
                    f"imported block {i} carries {len(tokens)} tokens; "
                    f"this pool's block_size is {self.block_size}")
            parent = h
            h = _hash_block(h, tokens)
            if h != rec["hash"]:
                raise ValueError(
                    f"imported block {i} (depth {i + 1}) fails chain-hash "
                    "verification: content does not match its digest")
            parent_of[h] = parent
        fresh = [rec for rec in records
                 if rec["hash"] not in self._hash_index]
        if len(fresh) > self.num_available:
            return None
        placed: Dict[bytes, int] = {}
        taken = [self._take_block("kv_import") for _ in fresh]
        for b, rec in zip(taken, fresh):
            hh = rec["hash"]
            self._block_hash[b] = hh
            self._block_depth[b] = int(rec["depth"])
            self._block_tokens[b] = tuple(int(t) for t in rec["tokens"])
            self._block_parent[b] = parent_of[hh]
            self._hash_index[hh] = b
            self._reuse[b] = hh
            self._park_step[b] = self.clock
            placed[hh] = b
        if placed:
            self.cache_epoch += 1
        return placed


class BlockKVCache:
    """Host-side block-pool manager (BlockTable bookkeeping is python; the
    cache tensors live on device).

    Blocks are reference-counted so sequences can share a prefix without
    copying (``fork``): a shared block returns to the free list only when
    its last owner frees it — the copy-on-write-free reuse hook the
    serving layer's :class:`~paddle_tpu.serving.KVCacheManager` builds on.
    Bookkeeping is delegated to one shared :class:`BlockPool`; the public
    ``block_tables``/``seq_lens``/``_free`` attributes alias its state."""

    def __init__(self, num_blocks: int, block_size: int, num_heads: int,
                 head_dim: int, dtype=jnp.bfloat16):
        self.num_blocks = num_blocks
        self.block_size = block_size
        # head-dim sharded over the mp mesh axis when one is live (the
        # bookkeeping below stays host-side/replicated either way)
        self.k_cache = shard_kv_pool(
            jnp.zeros((num_blocks, block_size, num_heads, head_dim), dtype))
        self.v_cache = shard_kv_pool(
            jnp.zeros((num_blocks, block_size, num_heads, head_dim), dtype))
        self._pool = BlockPool(num_blocks, block_size)
        self._free = self._pool._free        # same objects, mutated in place
        self._ref = self._pool._ref
        self.block_tables = self._pool._tables
        self.seq_lens = self._pool._lens

    def blocks_needed(self, seq_id: int, num_tokens: int) -> int:
        return self._pool.blocks_needed(seq_id, num_tokens)

    def can_allocate(self, seq_id: int, num_tokens: int) -> bool:
        return self._pool.can_allocate(seq_id, num_tokens)

    def allocate(self, seq_id: int, num_tokens: int):
        """Reserve enough blocks for ``num_tokens`` more tokens.

        All-or-nothing: on exhaustion raises :class:`PoolExhausted`
        WITHOUT having taken any block, so the pool state stays clean for
        the caller's preemption/retry policy (``try_allocate`` is the
        non-raising form)."""
        if not self._pool.allocate(seq_id, num_tokens):
            raise PoolExhausted(
                f"KV cache pool exhausted: seq {seq_id} needs "
                f"{self._pool.blocks_needed(seq_id, num_tokens)} block(s), "
                f"{len(self._free)} free — free or preempt a sequence and "
                "retry")
        return self.block_tables[seq_id]

    def try_allocate(self, seq_id: int, num_tokens: int):
        """``allocate`` returning ``None`` instead of raising on exhaustion."""
        if not self._pool.allocate(seq_id, num_tokens):
            return None
        return self.block_tables[seq_id]

    def fork(self, src_seq: int, dst_seq: int) -> int:
        return self._pool.fork(src_seq, dst_seq)

    def free(self, seq_id: int):
        self._pool.free(seq_id)

    def write(self, seq_id: int, k: jax.Array, v: jax.Array):
        """Append [T, H, D] keys/values for one sequence."""
        T = k.shape[0]
        start = self.seq_lens.get(seq_id, 0)
        table = self.allocate(seq_id, T)
        pos = np.arange(start, start + T)
        blocks = np.asarray([table[p // self.block_size] for p in pos])
        offs = pos % self.block_size
        self.k_cache = self.k_cache.at[blocks, offs].set(k.astype(self.k_cache.dtype))
        self.v_cache = self.v_cache.at[blocks, offs].set(v.astype(self.v_cache.dtype))
        self.seq_lens[seq_id] = start + T

    def gather_view(self, seq_ids, max_blocks: Optional[int] = None):
        """Dense [B, max_blocks] block table + [B] lengths for the kernel."""
        if max_blocks is None:
            max_blocks = max(len(self.block_tables[s]) for s in seq_ids)
        bt = np.zeros((len(seq_ids), max_blocks), np.int32)
        lens = np.zeros((len(seq_ids),), np.int32)
        for i, s in enumerate(seq_ids):
            t = self.block_tables[s]
            bt[i, :len(t)] = t
            lens[i] = self.seq_lens[s]
        return jnp.asarray(bt), jnp.asarray(lens)


#: PartitionSpec entries for a ``[num_blocks, block_size, H, D]`` KV pool
#: under tensor-parallel serving: sharded along the HEAD dim over ``mp``.
#: The single source of truth — :func:`shard_kv_pool` places pools with it
#: and the engine's explicit jit in/out shardings reuse it, so placement
#: and program specs cannot drift (drift = silent full-pool resharding
#: transfers every step).
KV_POOL_SPEC = (None, None, "mp", None)


def shard_kv_pool(pool):
    """Place a ``[num_blocks, block_size, H, D]`` KV pool sharded along the
    head dim over the ``mp`` mesh axis (tensor-parallel serving, ISSUE 5).

    No-op (replicated placement semantics unchanged) when there is no
    global mesh, the mesh has no ``mp`` axis, ``mp == 1``, or the head
    count does not divide evenly — callers that require sharding must
    validate divisibility themselves (the engine does)."""
    from ..distributed import topology

    mesh = topology.get_mesh()
    if (mesh is None or "mp" not in mesh.axis_names
            or mesh.shape["mp"] == 1 or pool.shape[2] % mesh.shape["mp"]):
        return pool
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.device_put(
        pool, NamedSharding(mesh, PartitionSpec(*KV_POOL_SPEC)))


# Which path the most recent dispatch took: "pallas" | "xla" (same loud
# fallback contract as ops/flash_attention.py).
last_path: Optional[str] = None


def pallas_dispatch(kernel_fn, oracle_fn, use_pallas, tileable,
                    name: str):
    """ONE home for the kernel-vs-oracle dispatch policy shared by the
    decode kernel (:func:`paged_attention`) and the unified ragged
    kernel (``ops.ragged_paged.ragged_paged_attention``): the operator
    kill switch (``PADDLE_TPU_DISABLE_PALLAS`` / the
    ``disable_pallas_kernels`` flag) always wins, ``use_pallas=True``
    forces the kernel past the tileability heuristic (interpret mode
    off-TPU), ``False`` pins the oracle, and a kernel failure falls back
    loudly (or re-raises under ``PADDLE_TPU_STRICT_PALLAS`` /
    ``strict_pallas``).  Returns ``(out, path)`` with ``path`` in
    ``{"pallas", "xla"}`` — callers publish it as their module's
    ``last_path``."""
    import os

    from ..core import flags

    disable = (os.environ.get("PADDLE_TPU_DISABLE_PALLAS") == "1"
               or flags.flag("disable_pallas_kernels"))
    if use_pallas is False:
        tileable = False          # pin the XLA gather path
    if not disable and (tileable or use_pallas is True):
        try:
            return kernel_fn(), "pallas"
        except Exception as e:
            import warnings

            if (os.environ.get("PADDLE_TPU_STRICT_PALLAS") == "1"
                    or flags.flag("strict_pallas")):
                raise
            warnings.warn(
                f"{name} failed, falling back to the XLA gather path: "
                f"{type(e).__name__}: {e}",
                RuntimeWarning, stacklevel=3)
    return oracle_fn(), "xla"


class PagedCache:
    """Per-layer view of the shared block pool, handed to the model's
    attention as its ``cache`` (the model writes K/V into the slot and
    attends through the block table).  ``k_pool``/``v_pool`` are framework
    Tensors [num_blocks, block_size, Hkv, D] so the scatter write threads
    as jit state; the routing arrays are refreshed by the serving loop
    before each decode step."""

    def __init__(self, k_pool, v_pool):
        self.k_pool = k_pool
        self.v_pool = v_pool
        self.block_tables = None   # [B, max_blocks] int32
        self.seq_lens = None       # [B] int32 (AFTER this step's token)
        self.slot_blocks = None    # [B] int32 — page of this step's token
                                   # ([B, S] in chunked-prefill mode: one
                                   # slot per chunk token)
        self.slot_offsets = None   # [B] int32 — offset within the page
        self.q_start = None        # chunked prefill only: global position
                                   # of the chunk's first token (scalar or
                                   # [B] int32) — offsets the causal mask.
                                   # In ragged mode ([T] int32): the
                                   # absolute position of EVERY packed
                                   # token
        self.seg_ids = None        # unified ragged step (ISSUE 11): [T]
                                   # int32 row index of each packed token
                                   # — non-None routes the model's
                                   # attention through ops/ragged_paged.py
                                   # (one fused prefill+decode launch)
        self.use_pallas = None     # decode kernel routing hint (ISSUE 5
                                   # satellite): True forces the Pallas
                                   # kernel (interpret mode off-TPU),
                                   # False forces the XLA gather path,
                                   # None keeps the auto dispatch

    def route(self, block_tables, seq_lens, slot_blocks, slot_offsets,
              q_start=None, seg_ids=None):
        self.block_tables = jnp.asarray(block_tables, jnp.int32)
        self.seq_lens = jnp.asarray(seq_lens, jnp.int32)
        self.slot_blocks = jnp.asarray(slot_blocks, jnp.int32)
        self.slot_offsets = jnp.asarray(slot_offsets, jnp.int32)
        if q_start is not None:
            self.q_start = jnp.asarray(q_start, jnp.int32)
        if seg_ids is not None:
            self.seg_ids = jnp.asarray(seg_ids, jnp.int32)


def _xla_paged_attention(q, k_cache, v_cache, block_tables, seq_lens):
    """XLA gather path: materializes the padded [B, S, H, D] context (GQA
    via grouped einsum, KV never head-repeated).

    This is also the **standing differential-testing oracle** for the
    Pallas decode kernel (``pallas_paged.decode_oracle`` re-exports it):
    the interpret-mode parity tests and the online numerics auditor
    (``observability/audit.py``) both compare the kernel against this
    path, so any kernel drift is caught offline AND in production."""
    B, H, D = q.shape
    max_blocks = block_tables.shape[1]
    bs = k_cache.shape[1]
    Hkv = k_cache.shape[2]
    rep = H // Hkv
    scale = 1.0 / math.sqrt(D)

    # gather each sequence's pages: [B, max_blocks, bs, Hkv, D] → [B, S, Hkv, D]
    k = k_cache[block_tables].reshape(B, max_blocks * bs, Hkv, D)
    v = v_cache[block_tables].reshape(B, max_blocks * bs, Hkv, D)

    qg = q.reshape(B, Hkv, rep, D)
    logits = jnp.einsum("bhrd,bshd->bhrs", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_blocks * bs)[None, None, None, :]
    mask = pos < seq_lens[:, None, None, None]
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhrs,bshd->bhrd", probs, v.astype(jnp.float32))
    return out.reshape(B, H, D).astype(q.dtype)


def paged_prefill_attention(q: jax.Array, k_cache: jax.Array,
                            v_cache: jax.Array, block_tables: jax.Array,
                            seq_lens: jax.Array,
                            q_start: jax.Array) -> jax.Array:
    """Chunked-prefill attention over a paged KV cache.

    q: [B, S, H, D] — ``S`` new tokens per sequence sitting at global
    positions ``q_start + [0, S)``; the chunk's own K/V has already been
    scattered into the pool, so the causal mask ``col <= q_start + row``
    covers both the previously computed prefix AND intra-chunk causality
    with one predicate.  ``seq_lens`` is the total KV length after the
    chunk (clamps pad rows away from garbage pages).  Returns
    [B, S, H, D].

    XLA gather path on purpose: a prefill chunk is compute-bound on the
    [S, K] score matmul (unlike the latency-bound single-token decode the
    Pallas kernel exists for), and the same grouped-einsum/float32-softmax
    shape as the dense prefill keeps greedy outputs token-identical
    between the chunked and one-shot programs.
    """
    B, S, H, D = q.shape
    max_blocks = block_tables.shape[1]
    bs = k_cache.shape[1]
    Hkv = k_cache.shape[2]
    rep = H // Hkv
    scale = 1.0 / math.sqrt(D)

    k = k_cache[block_tables].reshape(B, max_blocks * bs, Hkv, D)
    v = v_cache[block_tables].reshape(B, max_blocks * bs, Hkv, D)

    qg = q.reshape(B, S, Hkv, rep, D)
    logits = jnp.einsum("bqhrd,bkhd->bhrqk", qg, k,
                        preferred_element_type=jnp.float32) * scale
    col = jnp.arange(max_blocks * bs)[None, None, :]
    starts = (q_start[:, None, None] if jnp.ndim(q_start) == 1
              else q_start)                       # scalar or per-sequence
    row = starts + jnp.arange(S)[None, :, None]
    mask = (col <= row) & (col < seq_lens[:, None, None])  # [B, S, K]
    logits = jnp.where(mask[:, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhrqk,bkhd->bqhrd", probs.astype(v.dtype), v)
    return out.reshape(B, S, H, D).astype(q.dtype)


def paged_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                    block_tables: jax.Array, seq_lens: jax.Array,
                    use_pallas: Optional[bool] = None) -> jax.Array:
    """Decode-step attention over a paged KV cache.

    q: [B, H, D] (one new token per sequence); k/v_cache:
    [num_blocks, block_size, Hkv, D]; block_tables: [B, max_blocks] int32;
    seq_lens: [B] int32.  Returns [B, H, D].

    Dispatches to the Pallas kernel (``pallas_paged.py`` — scalar-prefetch
    page DMA, no dense context copy) when shapes are TPU-tileable; falls
    back to the XLA gather path with a loud warning otherwise.

    ``use_pallas`` overrides the auto dispatch (``EngineConfig.
    use_pallas_paged``, ISSUE 5): ``True`` routes through the Pallas
    kernel even when the tileability heuristic says no (off-TPU the
    kernel runs in interpret mode — the CPU smoke-test path); ``False``
    pins the XLA gather path (the mp>1 choice for the LEGACY programs:
    GSPMD partitions the gather einsums, while this kernel is
    single-shard — the unified ragged kernel spans the mesh instead).
    The operator kill switch (``PADDLE_TPU_DISABLE_PALLAS`` / the
    ``disable_pallas_kernels`` flag) still wins over ``use_pallas=True``
    (:func:`pallas_dispatch` is the one policy implementation).
    """
    global last_path

    B, H, D = q.shape
    tileable = D % 128 == 0 and k_cache.shape[1] % 8 == 0

    def kernel():
        from .pallas_paged import paged_attention_decode

        return paged_attention_decode(q, k_cache, v_cache, block_tables,
                                      seq_lens)

    out, last_path = pallas_dispatch(
        kernel,
        lambda: _xla_paged_attention(q, k_cache, v_cache, block_tables,
                                     seq_lens),
        use_pallas, tileable, "pallas paged attention")
    return out
