"""Flash attention for TPU.

Capability analog of the reference's flash-attn v2 binding
(``paddle/phi/kernels/gpu/flash_attn_kernel.cu``), built as a Pallas kernel
(block-streamed online-softmax over KV tiles in VMEM) with an XLA composite
fallback for small sequences / non-TPU backends.

Layout: [B, S, H, D] (paddle flash-attn convention).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_FLASH_MIN_SEQ = 1024  # below this, XLA's fused softmax path is already fast


def use_flash(q_shape, attn_mask) -> bool:
    import os

    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS") == "1":
        return False  # kill switch: force the XLA composite path
    if attn_mask is not None:
        return False
    if len(q_shape) != 4:
        return False
    seq, head_dim = q_shape[1], q_shape[3]
    if seq < _FLASH_MIN_SEQ or seq % 128 != 0:
        return False
    if head_dim % 128 != 0:
        return False
    return jax.default_backend() == "tpu"


def _reference_attention(q, k, v, causal: bool):
    B, Sq, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh, preferred_element_type=jnp.float32) * scale
    if causal:
        Sk = kh.shape[2]
        mask = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


def flash_attention_fwd(q, k, v, causal: bool = False):
    """Dispatch: Pallas fused kernel on TPU for long sequences, XLA otherwise."""
    if use_flash(q.shape, None):
        try:
            from .pallas_flash import flash_attention as pallas_flash

            # positional: custom_vjp with nondiff_argnums rejects kwargs
            return pallas_flash(q, k, v, causal)
        except Exception:
            pass
    return _reference_attention(q, k, v, causal)
