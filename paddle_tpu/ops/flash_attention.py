"""Flash attention for TPU.

Capability analog of the reference's flash-attn v2 binding
(``paddle/phi/kernels/gpu/flash_attn_kernel.cu``), built as a Pallas kernel
(block-streamed online-softmax over KV tiles in VMEM) with an XLA composite
fallback for small sequences / non-TPU backends.

Layout: [B, S, H, D] (paddle flash-attn convention).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_FLASH_MIN_SEQ = 1024  # below this, XLA's fused softmax path is already fast
_CHUNKED_MIN_AREA = 1024 * 1024  # Sq*Sk at which S^2 scores become the
                                 # memory bottleneck -> scan recurrence

# Which path the most recent dispatch took: "pallas" | "xla_chunked"
# (lax.scan flash recurrence, long sequences) | "xla" (composite).
# Benchmarks and tests read this so a kernel regression shows up as a loud
# signal, not a silent perf cliff (VERDICT r1 weak #5).
last_path: str | None = None


def use_flash(q_shape, attn_mask) -> bool:
    import os

    from ..core import flags

    if (os.environ.get("PADDLE_TPU_DISABLE_PALLAS") == "1"
            or flags.flag("disable_pallas_kernels")):
        return False  # kill switch: force the XLA composite path
    if attn_mask is not None:
        return False
    if len(q_shape) != 4:
        return False
    seq, head_dim = q_shape[1], q_shape[3]
    if seq < _FLASH_MIN_SEQ or seq % 128 != 0:
        return False
    # Mosaic tiling: the head_dim block must be lane-aligned (divisible by
    # 128) OR equal to the full array dim with sublane alignment — so 64
    # (BERT/GPT-2 head size; half-wide vregs, still beats the composite)
    # is legal alongside multiples of 128
    if head_dim % 128 != 0 and head_dim != 64:
        return False
    return jax.default_backend() == "tpu"


def _reference_attention(q, k, v, causal: bool):
    """XLA composite attention; GQA-native via grouped einsum (query heads
    reshaped [B,S,Hkv,rep,D] against ungrouped KV — no repeated KV buffer)."""
    B, Sq, H, D = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    rep = H // Hkv
    scale = 1.0 / math.sqrt(D)
    qg = q.reshape(B, Sq, Hkv, rep, D)
    logits = jnp.einsum("bqhrd,bkhd->bhrqk", qg, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhrqk,bkhd->bqhrd", probs, v)
    return out.reshape(B, Sq, H, D)


def flash_attention_fwd(q, k, v, causal: bool = False):
    """Dispatch: Pallas fused kernel on TPU for long sequences, XLA otherwise."""
    global last_path
    if use_flash(q.shape, None):
        try:
            from ..core import flags as _flags
            from .pallas_flash import flash_attention as pallas_flash
            from .autotune import cached_flash_blocks, tune_flash_blocks

            # cache lookup is a dict get — always consult it, so the
            # committed on-chip sweep results (AUTOTUNE.json) pick the
            # block geometry without any flag; live tuning (a measured
            # sweep on first encounter of a new shape) stays opt-in
            blocks = cached_flash_blocks(q.shape, k.shape,
                                         str(q.dtype), causal)
            if (blocks is None and _flags.flag("pallas_autotune")
                    and not isinstance(q, jax.core.Tracer)):
                blocks = tune_flash_blocks(q, k, v, causal)
            # positional: custom_vjp with nondiff_argnums rejects kwargs
            if blocks is not None:
                out = pallas_flash(q, k, v, causal, blocks[0], blocks[1])
            else:
                out = pallas_flash(q, k, v, causal)
            last_path = "pallas"
            return out
        except Exception as e:
            import os
            import warnings

            from ..core import flags

            if (os.environ.get("PADDLE_TPU_STRICT_PALLAS") == "1"
                    or flags.flag("strict_pallas")):
                raise
            warnings.warn(
                f"pallas flash attention failed, falling back to XLA "
                f"composite path (set PADDLE_TPU_STRICT_PALLAS=1 to raise): "
                f"{type(e).__name__}: {e}", RuntimeWarning, stacklevel=2)
    # XLA path: beyond this area the composite S^2 score matrix dominates
    # memory (first contact: it OOMs a 16 GB v5e at batch 8 x seq 2048
    # backward), so long sequences take the lax.scan flash recurrence
    # (O(S*block_k) live memory) instead
    if q.shape[1] * k.shape[1] >= _CHUNKED_MIN_AREA:
        from .chunked_attention import chunked_attention

        last_path = "xla_chunked"
        return chunked_attention(q, k, v, causal)
    last_path = "xla"
    return _reference_attention(q, k, v, causal)
