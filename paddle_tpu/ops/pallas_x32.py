"""Trace Pallas regions with x64 disabled.

``paddle_tpu`` enables ``jax_enable_x64`` globally for reference dtype
parity (int64-default integer tensors).  Inside a Mosaic kernel that is a
liability: Python int constants in kernel bodies and BlockSpec index maps
trace as i64, and Mosaic has no i64 support — its int64→int32 conversion
helper recurses forever (jax 0.9 ``_convert_helper``).  Every
``pl.pallas_call`` site therefore traces its kernel and index maps under
this context, which pins the trace-time default back to 32-bit without
touching the global config.
"""

from __future__ import annotations

import contextlib


def no_x64():
    try:
        from jax._src import config as _jcfg

        return _jcfg.enable_x64(False)
    except Exception:  # pragma: no cover - jax internals moved
        return contextlib.nullcontext()
