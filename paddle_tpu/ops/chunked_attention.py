"""Memory-efficient attention in pure XLA (lax.scan over KV chunks).

The O(S²) composite attention path materialises the full score matrix —
first chip contact showed that OOMs a 16 GB v5e at batch 8 × seq 2048
(backward keeps S² fp32 scores per layer).  This module is the
FlashAttention-2 recurrence (online softmax over KV chunks, log-sum-exp
residual, probability recomputation in the backward) expressed as
``lax.scan`` so XLA compiles it into a bounded-memory loop on ANY backend
— the fallback when Mosaic rejects the Pallas kernel, the CPU/long-context
testing path, and the per-shard compute of ring attention.

Peak live memory is O(S·block_k) per (batch, head) instead of O(S²):
the scan carry holds only the running (m, l, acc) statistics.

Public layout matches ``pallas_flash.flash_attention``: q ``[B, S, H, D]``,
k/v ``[B, Sk, Hkv, D]`` (GQA native — query heads grouped per KV head, KV
is never repeated).  Reference analog: memory-efficient attention in
``phi/kernels/fusion/cutlass/memory_efficient_attention`` (same role for
the CUDA build).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30


def _grouped(q, k):
    """[B,S,H,D] q → [B,Hkv,rep,Sq,D]; [B,Sk,Hkv,D] k → [B,Hkv,Sk,D]."""
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    rep = H // Hkv
    qg = q.transpose(0, 2, 1, 3).reshape(B, Hkv, rep, Sq, D)
    return qg


def _pad_kv(k, block_k):
    Sk = k.shape[1]
    pad = (-Sk) % block_k
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
    return k, Sk + pad


def _scan_fwd(q, k, v, scale, causal, block_k):
    """Returns (out [B,Sq,H,D], lse [B,H,Sq] fp32)."""
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    rep = H // Hkv
    Sk = k.shape[1]
    k, Skp = _pad_kv(k, block_k)
    v, _ = _pad_kv(v, block_k)
    n_chunks = Skp // block_k

    qg = _grouped(q, k)                                   # [B,Hkv,rep,Sq,D]
    kc = k.transpose(0, 2, 1, 3).reshape(B, Hkv, n_chunks, block_k, D)
    vc = v.transpose(0, 2, 1, 3).reshape(B, Hkv, n_chunks, block_k, D)
    kc = jnp.moveaxis(kc, 2, 0)                           # [n,B,Hkv,bk,D]
    vc = jnp.moveaxis(vc, 2, 0)

    q_pos = jnp.arange(Sq)[:, None]                       # [Sq, 1]

    def step(carry, xs):
        m, l, acc = carry
        ci, kb, vb = xs
        s = jnp.einsum("bgrqd,bgkd->bgrqk", qg, kb,
                       preferred_element_type=jnp.float32) * scale
        k_pos = ci * block_k + jnp.arange(block_k)[None, :]
        valid = k_pos < Sk                                # mask KV padding
        if causal:
            valid = valid & (k_pos <= q_pos + (Sk - Sq))
        s = jnp.where(valid[None, None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # mask p explicitly: a row with NO valid key has m_new == _NEG_INF,
        # where exp(s - m_new) == 1 would silently average V — such rows
        # must stay at l == 0 so the epilogue returns zeros (the documented
        # finite-masked-row contract)
        p = jnp.where(valid[None, None, None],
                      jnp.exp(s - m_new[..., None]), 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bgrqk,bgkd->bgrqd", p.astype(vb.dtype), vb,
            preferred_element_type=jnp.float32)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((B, Hkv, rep, Sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, rep, Sq), jnp.float32)
    a0 = jnp.zeros((B, Hkv, rep, Sq, D), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(
        step, (m0, l0, a0), (jnp.arange(n_chunks), kc, vc))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / safe_l[..., None]).astype(q.dtype)
    out = out.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)  # [B,Sq,H,D]
    lse = (m + jnp.log(safe_l)).reshape(B, H, Sq)
    return out, lse


def _scan_bwd(res, g, *, scale, causal, block_k):
    q, k, v, out, lse = res
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    rep = H // Hkv
    Sk = k.shape[1]
    kp, Skp = _pad_kv(k, block_k)
    vp, _ = _pad_kv(v, block_k)
    n_chunks = Skp // block_k

    qg = _grouped(q, kp)                                  # [B,Hkv,rep,Sq,D]
    dog = _grouped(g, kp)
    kc = jnp.moveaxis(
        kp.transpose(0, 2, 1, 3).reshape(B, Hkv, n_chunks, block_k, D), 2, 0)
    vc = jnp.moveaxis(
        vp.transpose(0, 2, 1, 3).reshape(B, Hkv, n_chunks, block_k, D), 2, 0)
    lse_g = lse.reshape(B, Hkv, rep, Sq)
    delta = jnp.einsum("bshd,bshd->bhs", g.astype(jnp.float32),
                       out.astype(jnp.float32)).reshape(B, Hkv, rep, Sq)
    q_pos = jnp.arange(Sq)[:, None]

    def step(dq_acc, xs):
        ci, kb, vb = xs
        s = jnp.einsum("bgrqd,bgkd->bgrqk", qg, kb,
                       preferred_element_type=jnp.float32) * scale
        k_pos = ci * block_k + jnp.arange(block_k)[None, :]
        valid = k_pos < Sk
        if causal:
            valid = valid & (k_pos <= q_pos + (Sk - Sq))
        s = jnp.where(valid[None, None, None], s, _NEG_INF)
        # same explicit mask as the forward: rows with no valid key have
        # lse == _NEG_INF and exp(s - lse) == 1 — their p must be 0
        p = jnp.where(valid[None, None, None],
                      jnp.exp(s - lse_g[..., None]), 0.0)  # [B,g,r,Sq,bk]
        dv_c = jnp.einsum("bgrqk,bgrqd->bgkd", p.astype(jnp.float32),
                          dog.astype(jnp.float32))
        dp = jnp.einsum("bgrqd,bgkd->bgrqk", dog, vb,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * scale
        dk_c = jnp.einsum("bgrqk,bgrqd->bgkd", ds, qg.astype(jnp.float32))
        dq_acc = dq_acc + jnp.einsum("bgrqk,bgkd->bgrqd",
                                     ds.astype(kb.dtype), kb,
                                     preferred_element_type=jnp.float32)
        return dq_acc, (dk_c, dv_c)

    dq0 = jnp.zeros((B, Hkv, rep, Sq, D), jnp.float32)
    dq, (dk_c, dv_c) = jax.lax.scan(
        step, dq0, (jnp.arange(n_chunks), kc, vc))
    dq = dq.reshape(B, H, Sq, D).transpose(0, 2, 1, 3).astype(q.dtype)
    dk = jnp.moveaxis(dk_c, 0, 2).reshape(B, Hkv, Skp, D)
    dv = jnp.moveaxis(dv_c, 0, 2).reshape(B, Hkv, Skp, D)
    dk = dk[:, :, :Sk].transpose(0, 2, 1, 3).astype(k.dtype)
    dv = dv[:, :, :Sk].transpose(0, 2, 1, 3).astype(v.dtype)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def chunked_attention(q, k, v, causal=False, block_k=DEFAULT_BLOCK_K):
    """O(S·block_k)-memory attention over [B,S,H,D] q / [B,Sk,Hkv,D] k,v.

    Fully-masked query rows (only possible with ``causal=True`` and
    Sq > Sk, an invalid decode shape) return zeros with zero gradients —
    the same finite-masked-row contract as the Pallas kernel — where the
    composite reference produces NaN."""
    assert q.shape[2] % k.shape[2] == 0
    scale = 1.0 / math.sqrt(q.shape[-1])
    out, _ = _scan_fwd(q, k, v, scale, causal, block_k)
    return out


def _vjp_fwd(q, k, v, causal, block_k):
    scale = 1.0 / math.sqrt(q.shape[-1])
    out, lse = _scan_fwd(q, k, v, scale, causal, block_k)
    return out, (q, k, v, out, lse)


def _vjp_bwd(causal, block_k, res, g):
    scale = 1.0 / math.sqrt(res[0].shape[-1])
    return _scan_bwd(res, g, scale=scale, causal=causal, block_k=block_k)


chunked_attention.defvjp(_vjp_fwd, _vjp_bwd)
