"""In-trace per-row token sampling (ISSUE 18).

The sampling reduction that turns a ``[rows, vocab]`` logits block into
``[rows]`` token ids **inside** the traced step program, so the host
fetches token ids only — stage (1) of the MPK-style device-resident
decode loop (PAPERS.md #5).  Sits next to the PR 9 logit-stats
reductions: both are cheap row-wise epilogues fused into the step
program's tail, adding no new program family and no new bucket axes.

Design constraints the serving layer relies on:

* **Greedy is the temperature==0 row of the same program.**  Every row
  carries its own ``(temperature, top_k, top_p, key)`` quartet; rows
  with ``temperature <= 0`` reduce to a pure argmax, bit-identical to
  the pre-ISSUE-18 host argmax.  One compiled program serves greedy and
  sampled batches — bucket sets and trace counts are unchanged.
* **Determinism under seed via counter-keyed Gumbel-max.**  The key for
  a draw is the raw u32 pair ``(seed, draw_index)`` (the request's
  output position) — a pure function of request state, NOT of engine
  step boundaries.  Preemption-recompute, dp placement, spec-decode
  verify packing and server-vs-offline all replay the identical key
  sequence, so the sampled stream is identical everywhere.  The noise
  itself is a counter-based integer-mix hash (murmur3 finalizer chain
  over ``(seed, draw, vocab lane)``), not threefry: the sampling
  epilogue is fused into EVERY bucketed step program, and a threefry
  lowering costs ~0.2s of XLA compile per program where the elementwise
  mix is free.  Gumbel-max only needs iid uniforms per lane; a
  full-avalanche hash of a unique counter triple is exactly that.
* **Filter pipeline order matches the host reference**
  (:meth:`~paddle_tpu.serving.request.SamplingParams.sample`):
  temperature scale -> top-k mask -> top-p nucleus mask -> draw.
  Gumbel-max over the masked scaled logits is distribution-identical to
  softmax-then-categorical, but needs no normalization and stays a pure
  ``argmax`` reduction on device.
* **top_p ∈ (0, 1] can never empty the distribution**: the max-prob
  token's cumsum entry is the first one compared against ``top_p``, so
  it always survives the nucleus cut (``top_p == 1.0`` keeps all).
  ``top_k <= 0`` means "no top-k filter" (protocol validates ``>= 0``).
"""

from __future__ import annotations

import jax.numpy as jnp

_NEG = jnp.float32(-1e30)  # mask value: finite, so argmax ties stay sane


def _fmix32(z):
    """murmur3 32-bit finalizer — full avalanche, pure elementwise u32
    ops (wrap-around mul), so it lowers to a handful of instructions."""
    z = z ^ (z >> jnp.uint32(16))
    z = z * jnp.uint32(0x85EBCA6B)
    z = z ^ (z >> jnp.uint32(13))
    z = z * jnp.uint32(0xC2B2AE35)
    z = z ^ (z >> jnp.uint32(16))
    return z


def _gumbel_from_keys(keys, V):
    """``[R, V]`` Gumbel noise from raw ``[R, 2]`` (seed, draw) u32 keys:
    hash the (seed, draw, lane) counter triple through a chained
    avalanche, map the top 24 bits to a strictly-interior uniform, and
    apply the double-log Gumbel transform."""
    seed = keys[:, 0:1]
    draw = keys[:, 1:2]
    lane = jnp.arange(V, dtype=jnp.uint32)[None, :]
    h = _fmix32(lane ^ _fmix32(draw ^ _fmix32(seed ^ jnp.uint32(0x9E3779B9))))
    # top 24 bits -> u in (0, 1) strictly (the +0.5 keeps log() finite)
    u = ((h >> jnp.uint32(8)).astype(jnp.float32) + 0.5) * jnp.float32(
        1.0 / (1 << 24))
    return -jnp.log(-jnp.log(u))


def make_keys(seed_draws, out=None):
    """Pack ``[(seed, draw_index), ...]`` into the raw ``[n, 2]`` u32 key
    array :func:`sample_tokens` consumes (host-side helper, numpy-free of
    jax so schedulers can call it without touching the device)."""
    import numpy as np
    n = len(seed_draws)
    keys = np.zeros((n, 2), dtype=np.uint32) if out is None else out
    for i, (seed, draw) in enumerate(seed_draws):
        keys[i, 0] = np.uint32(seed & 0xFFFFFFFF)
        keys[i, 1] = np.uint32(draw & 0xFFFFFFFF)
    return keys


def sample_tokens(logits, temps, top_ks, top_ps, keys):
    """Sample one token per row, in-trace.

    Args:
      logits: ``[R, V]`` float (any float dtype; upcast to f32).
      temps:  ``[R]`` f32 — ``<= 0`` means greedy (pure argmax).
      top_ks: ``[R]`` i32 — ``<= 0`` means no top-k filter.
      top_ps: ``[R]`` f32 — nucleus mass in ``(0, 1]``; ``1.0`` = off.
      keys:   ``[R, 2]`` u32 — raw ``(seed, draw_index)`` PRNG key data.

    Returns:
      ``[R]`` i32 token ids.
    """
    x32 = logits.astype(jnp.float32)
    V = x32.shape[-1]
    greedy = jnp.argmax(x32, axis=-1).astype(jnp.int32)

    x = x32 / jnp.maximum(temps[:, None], 1e-6)

    # top-k: mask everything below the k-th largest scaled logit.
    # k_eff == V when the filter is off, so the mask is a no-op then.
    sorted_desc = -jnp.sort(-x, axis=-1)
    k_eff = jnp.where(top_ks <= 0, V, jnp.minimum(top_ks, V))
    kth = jnp.take_along_axis(
        sorted_desc, (k_eff - 1).astype(jnp.int32)[:, None], axis=-1)
    x = jnp.where(x < kth, _NEG, x)

    # top-p: smallest prob mass >= top_p over the top-k-filtered dist.
    # The descending prob vector is softmax of the DESCENDING masked
    # logits (softmax is order-preserving), so the one sort above is
    # reused instead of sorting the probs again — the epilogue is fused
    # into every bucketed step program and each sort lowering is paid
    # per program.
    # unnormalized mass suffices: softmax's denominator cancels out of
    # ``csum/total >= top_p``, and thresholding against the ACTUAL total
    # (instead of a literal 1.0) keeps top_p == 1.0 from collapsing to
    # greedy when f32 rounding lands the full sum at 0.99999994
    sorted_masked = jnp.where(sorted_desc < kth, _NEG, sorted_desc)
    e = jnp.exp(sorted_masked - sorted_masked[:, 0:1])
    csum = jnp.cumsum(e, axis=-1)
    cut = jnp.argmax(csum >= top_ps[:, None] * csum[:, -1:], axis=-1)
    # cut back in LOGIT space: ``sorted_masked`` holds the same bits as
    # ``x`` (a sort is a permutation), so the comparison can never mask
    # the cut token itself — thresholding on a re-softmaxed prob vector
    # can, because the two softmax denominators sum in different orders
    # and drift a ulp apart, emptying the whole row
    pth = jnp.take_along_axis(sorted_masked, cut[:, None], axis=-1)
    x = jnp.where(x < pth, _NEG, x)

    # Gumbel-max draw, keyed per row by the raw (seed, draw_index) data.
    g = _gumbel_from_keys(keys, V)
    sampled = jnp.argmax(x + g, axis=-1).astype(jnp.int32)

    return jnp.where(temps <= 0.0, greedy, sampled)
