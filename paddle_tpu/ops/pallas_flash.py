"""Pallas TPU flash attention (forward + backward).

Capability analog of the reference's flash-attn v2 CUDA binding
(``paddle/phi/kernels/gpu/flash_attn_kernel.cu``), re-designed for the TPU
memory hierarchy: Q/K/V stream HBM→VMEM in MXU-aligned blocks, the online
softmax keeps running (max, sum, acc) statistics in VMEM scratch across the
KV grid dimension, and the backward recomputes P from the saved
log-sum-exp instead of materialising the [S, S] probability matrix —
O(S) memory in sequence length, matching FlashAttention-2's structure
but scheduled by the Mosaic pipeline (grid iteration double-buffers the
next KV block's DMA behind the current block's einsums automatically).

Public layout: [B, S, H, D] (paddle flash-attn convention).  Internally the
kernels run on [B, H, S, D]: Mosaic requires the last two dims of every
block to be divisible by (8, 128) or equal to the array dims, so the
blocked dims (seq, head_dim) must be the minor-most two — the wrapper
transposes at entry/exit (a layout change XLA fuses into neighbouring
ops).  Softmax statistics (lse, delta) travel as [B, H, S, 1] so their
(block_q, 1) blocks satisfy the same tiling rule.  All statistics are fp32
regardless of input dtype.
"""

from __future__ import annotations

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_x32 import no_x64

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = np.float32(-1e30)  # large-negative instead of -inf: keeps masked rows finite

_LANES = 128  # stats are kept (BQ, 128) — min f32 tile is (8, 128)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k,
                n_k):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: whole block is masked iff q_block_end < k_block_start
    run = True
    if causal:
        run = (qi + 1) * block_q > ki * block_k

    @pl.when(run)
    def _step():
        q = q_ref[0, 0, :, :]                    # [BQ, D]
        k = k_ref[0, 0, :, :]                    # [BK, D]
        v = v_ref[0, 0, :, :]                    # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [BQ, BK]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + qi * block_q
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + ki * block_k
            s = jnp.where(rows >= cols, s, _NEG_INF)

        m_prev = m_ref[:, :1]                    # [BQ, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                   # [BQ, BK] f32
        corr = jnp.exp(m_prev - m_new)           # [BQ, 1]
        l_new = l_ref[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == n_k - 1)
    def _finish():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, jnp.float32(1.0), l)
        o_ref[0, 0, :, :] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0, :, 0] = (m_ref[:, 0] + jnp.log(safe_l[:, 0]))


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    B, S, H, D = q.shape
    Sk = k.shape[1]
    n_q = pl.cdiv(S, block_q)
    n_k = pl.cdiv(Sk, block_k)
    # GQA: query head h reads KV head h // group straight from the BlockSpec
    # index map — no jnp.repeat, no extra KV HBM traffic
    group = H // k.shape[2]

    # kernels run on [B, H, S, D] (Mosaic tiling: blocked dims minor-most)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, n_k=n_k)

    with no_x64():
        out, lse = pl.pallas_call(
            kernel,
            grid=(B, H, n_q, n_k),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_k, D),
                             lambda b, h, i, j: (b, h // np.int32(group), j, 0)),
                pl.BlockSpec((1, 1, block_k, D),
                             lambda b, h, i, j: (b, h // np.int32(group), j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
                jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, D), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
            ],
            interpret=_interpret(),
        )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse[..., 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, scale, causal, block_q, block_k, n_k):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        run = (qi + 1) * block_q > ki * block_k

    @pl.when(run)
    def _step():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]                # [BQ, 1]
        delta = delta_ref[0, 0, :, :]            # [BQ, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + qi * block_q
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + ki * block_k
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)                     # [BQ, BK]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [BQ, BK]
        ds = p * (dp - delta) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finish():
        dq_ref[0, 0, :, :] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, n_q, group):
    ki = pl.program_id(2)
    gi = pl.program_id(3)
    qi = pl.program_id(4)

    @pl.when((gi == 0) & (qi == 0))
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = (qi + 1) * block_q > ki * block_k

    @pl.when(run)
    def _step():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]                # [BQ, 1]
        delta = delta_ref[0, 0, :, :]            # [BQ, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + qi * block_q
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + ki * block_k
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)                     # [BQ, BK]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [BK, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale            # [BQ, BK]
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [BK, D]

    @pl.when((gi == group - 1) & (qi == n_q - 1))
    def _finish():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(res, g, *, scale, causal, block_q, block_k):
    q, k, v, out, lse = res
    B, S, H, D = q.shape
    Sk = k.shape[1]
    Hkv = k.shape[2]
    group = H // Hkv
    n_q = pl.cdiv(S, block_q)
    n_k = pl.cdiv(Sk, block_k)
    do = g

    # delta_i = rowsum(dO_i · O_i)  — tiny elementwise reduce, leave to XLA
    delta = jnp.einsum("bshd,bshd->bhs", do.astype(jnp.float32),
                       out.astype(jnp.float32))

    # kernels run on [B, H, S, D]; stats as [B, H, S, 1] (legal (bq, 1) tiles)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = do.transpose(0, 2, 1, 3)
    lse4 = lse[..., None]
    delta4 = delta[..., None]

    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    k_spec = pl.BlockSpec((1, 1, block_k, D),
                          lambda b, h, i, j: (b, h // np.int32(group), j, 0))
    r_spec = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))

    with no_x64():
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k, n_k=n_k),
            grid=(B, H, n_q, n_k),
            in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec],
            out_specs=[q_spec],
            out_shape=[jax.ShapeDtypeStruct((B, H, S, D), q.dtype)],
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
            interpret=_interpret(),
        )(qt, kt, vt, dot, lse4, delta4)[0]

    # dk/dv: for each KV block, accumulate across the whole query-head group
    # then the q blocks — grid (B, Hkv, n_k, group, n_q), KV block resident
    # in VMEM for the full (group × n_q) sweep
    q_spec2 = pl.BlockSpec((1, 1, block_q, D),
                           lambda b, kh, j, g_, i: (b, kh * group + g_, i, 0))
    k_spec2 = pl.BlockSpec((1, 1, block_k, D),
                           lambda b, kh, j, g_, i: (b, kh, j, 0))
    r_spec2 = pl.BlockSpec((1, 1, block_q, 1),
                           lambda b, kh, j, g_, i: (b, kh * group + g_, i, 0))
    with no_x64():
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k, n_q=n_q,
                              group=group),
            grid=(B, Hkv, n_k, group, n_q),
            in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2],
            out_specs=[k_spec2, k_spec2],
            out_shape=[
                jax.ShapeDtypeStruct((B, Hkv, Sk, D), k.dtype),
                jax.ShapeDtypeStruct((B, Hkv, Sk, D), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, D), jnp.float32),
                pltpu.VMEM((block_k, D), jnp.float32),
            ],
            interpret=_interpret(),
        )(qt, kt, vt, dot, lse4, delta4)
    return (dq.transpose(0, 2, 1, 3), dk.transpose(0, 2, 1, 3),
            dv.transpose(0, 2, 1, 3))


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal=False,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Fused attention over [B, S, H, D] q and [B, S, Hkv, D] k/v.

    GQA/MQA-native: when Hkv < H (H divisible by Hkv), each query head reads
    its group's KV head directly via the BlockSpec index map — KV is streamed
    from HBM once per group, never materialised repeated."""
    assert q.shape[2] % k.shape[2] == 0, (
        f"query heads {q.shape[2]} not divisible by kv heads {k.shape[2]}")
    scale = np.float32(1.0 / math.sqrt(q.shape[-1]))
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _vjp_fwd(q, k, v, causal, block_q, block_k):
    scale = np.float32(1.0 / math.sqrt(q.shape[-1]))
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _vjp_bwd(causal, block_q, block_k, res, g):
    scale = np.float32(1.0 / math.sqrt(res[0].shape[-1]))
    return _flash_bwd(res, g, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
