"""Fused TPU kernels (Pallas) — the N8 fused-kernel library equivalent."""

from . import flash_attention  # noqa: F401
