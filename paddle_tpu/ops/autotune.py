"""Kernel autotune cache (N11 — ``paddle/phi/kernels/autotune/cache.h``).

The reference memoizes cuDNN algorithm choices per input configuration;
here the tunable is the Pallas block geometry (block_q, block_k) of the
flash-attention kernel.  Tuning times each admissible candidate on the
live device (forward + backward, blocked until ready) and memoizes the
winner keyed by (shape, dtype, causal, device kind), persisted to a JSON
file so later processes skip the sweep — the analog of the reference's
serialized autotune cache.

Enabled with ``FLAGS pallas_autotune`` (off by default: the sweep costs a
few compiles on first encounter of a new shape).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

_CACHE_PATH = os.path.join(
    os.path.dirname(__file__), "..", "_native", "autotune_cache.json")
# Committed measured results (tools/autotune_onchip.py writes the winners
# here; the file is checked in so every later process — including CI and
# the driver's bench run — starts from on-chip-measured block choices).
_COMMITTED_PATH = os.path.join(
    os.path.dirname(__file__), "..", "..", "AUTOTUNE.json")

_memory: Dict[str, Tuple[int, int]] = {}
_loaded = False

_counter_cache = (None, None, None)  # (registry, hit_counter, miss_counter)


def _count(hit: bool) -> None:
    """Registry hit/miss counters (objects cached per registry identity:
    the lookup path runs per flash-attention call, but a
    ``set_registry()`` swap must not leave us writing to the old one)."""
    global _counter_cache
    from ..observability import get_registry

    reg = get_registry()
    cached_reg, hit_c, miss_c = _counter_cache
    if cached_reg is not reg:
        hit_c = reg.counter(
            "autotune_cache_hits_total",
            "flash block-geometry cache lookups that hit")
        miss_c = reg.counter(
            "autotune_cache_misses_total",
            "flash block-geometry cache lookups that missed")
        _counter_cache = (reg, hit_c, miss_c)
    (hit_c if hit else miss_c).inc()


def _migrate_key(key: str) -> str:
    """Normalize a persisted cache key to the batch-free format.

    Pre-migration keys embedded the full q/kv shapes including batch
    (``flash|(8, 2048, 8, 128)|...``); block choice depends only on
    (seq, heads, head_dim), so bench's OOM-ladder batch halving caused
    silent cache misses.  Old 4-tuple shape fields drop their leading
    batch dim on load, so committed AUTOTUNE.json results keep hitting."""
    parts = key.split("|")
    if len(parts) != 6 or parts[0] != "flash":
        return key
    import ast

    out = [parts[0]]
    for field in parts[1:3]:
        try:
            shape = ast.literal_eval(field)
        except (ValueError, SyntaxError):
            return key
        if isinstance(shape, tuple) and len(shape) == 4:
            shape = shape[1:]
        out.append(str(tuple(shape)))
    return "|".join(out + parts[3:])


def _load():
    global _loaded
    if _loaded:
        return
    _loaded = True
    for path in (_COMMITTED_PATH, _CACHE_PATH):  # runtime cache wins
        try:
            with open(path) as f:
                _memory.update(
                    {_migrate_key(k): tuple(v)
                     for k, v in json.load(f).items()})
        except (OSError, ValueError):
            pass


def _save():
    try:
        os.makedirs(os.path.dirname(_CACHE_PATH), exist_ok=True)
        with open(_CACHE_PATH, "w") as f:
            json.dump({k: list(v) for k, v in _memory.items()}, f)
    except OSError:
        pass


def _key(q_shape, kv_shape, dtype, causal) -> str:
    import jax

    kind = jax.devices()[0].device_kind
    # batch is deliberately NOT part of the key: the Pallas grid iterates
    # batch as an outer dimension, so the best (block_q, block_k) depends
    # only on (seq, heads, head_dim) — and bench's OOM-ladder batch
    # halving must keep hitting the committed winners
    q = tuple(q_shape)[1:] if len(q_shape) == 4 else tuple(q_shape)
    kv = tuple(kv_shape)[1:] if len(kv_shape) == 4 else tuple(kv_shape)
    return f"flash|{q}|{kv}|{dtype}|{causal}|{kind}"


def candidates(seq_q: int, seq_k: int, head_dim: int) -> List[Tuple[int, int]]:
    """Admissible (block_q, block_k): MXU-aligned, dividing the sequence,
    within a conservative VMEM budget."""
    out = []
    for bq in (128, 256, 512):
        for bk in (128, 256, 512):
            if seq_q % bq or seq_k % bk:
                continue
            # rough VMEM estimate: q + k + v + acc + s tiles (fp32)
            vmem = (bq * head_dim * 2 + bk * head_dim * 2 * 2
                    + bq * head_dim * 4 + bq * bk * 4)
            if vmem > 12 * 1024 * 1024:
                continue
            out.append((bq, bk))
    return out or [(128, 128)]


def tune_flash_blocks(q, k, v, causal: bool,
                      iters: int = 3) -> Tuple[int, int]:
    """Measured sweep over block candidates; memoized + persisted."""
    import jax

    from .pallas_flash import flash_attention

    _load()
    key = _key(q.shape, k.shape, str(q.dtype), causal)
    hit = _memory.get(key)
    _count(hit is not None)
    if hit is not None:
        return hit

    from ..observability import get_tracer

    best, best_t = (128, 128), float("inf")
    with get_tracer().span("autotune_sweep", cat="autotune",
                           key=key) as sp:
        for bq, bk in candidates(q.shape[1], k.shape[1], q.shape[3]):
            try:
                def step(q_, k_, v_):
                    out, vjp = jax.vjp(
                        lambda a, b, c: flash_attention(a, b, c, causal, bq, bk),
                        q_, k_, v_)
                    return out, vjp(out)

                jitted = jax.jit(step)
                jax.block_until_ready(jitted(q, k, v))  # compile
                t0 = time.perf_counter()
                for _ in range(iters):
                    r = jitted(q, k, v)
                jax.block_until_ready(r)
                dt = (time.perf_counter() - t0) / iters
            except Exception:
                continue
            if dt < best_t:
                best, best_t = (bq, bk), dt
        sp.set_attribute("best", str(best))
    _memory[key] = best
    _save()
    return best


def cached_flash_blocks(q_shape, kv_shape, dtype,
                        causal) -> Optional[Tuple[int, int]]:
    """Cache lookup only (no tuning) — the hot-path accessor."""
    _load()
    hit = _memory.get(_key(q_shape, kv_shape, dtype, causal))
    _count(hit is not None)
    return hit


def record(q_shape, kv_shape, dtype, causal, blocks: Tuple[int, int],
           committed: bool = False) -> str:
    """Store a measured winner; ``committed=True`` also writes the
    repo-root ``AUTOTUNE.json`` (the checked-in results table the sweep
    tool produces on the live chip).  Returns the cache key."""
    _load()
    key = _key(q_shape, kv_shape, dtype, causal)
    _memory[key] = tuple(blocks)
    _save()
    if committed:
        table = {}
        try:
            with open(_COMMITTED_PATH) as f:
                table = json.load(f)
        except (OSError, ValueError):
            pass
        table[key] = list(blocks)
        with open(_COMMITTED_PATH, "w") as f:
            json.dump(table, f, indent=1, sort_keys=True)
    return key
