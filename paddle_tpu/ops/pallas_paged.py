"""Pallas TPU paged-attention decode kernel.

Capability analog of the reference's
``phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu`` (vLLM-style
paged KV attention), re-designed for TPU: the per-sequence block table is a
**scalar-prefetch** argument (``pltpu.PrefetchScalarGridSpec``), so the
index map can steer each grid step's HBM→VMEM DMA straight to the right KV
page — the gather never materializes a contiguous [B, S, H, D] copy the
way the XLA ``take`` path does.  Online softmax statistics live in VMEM
scratch across the page dimension, exactly like the flash kernel
(``pallas_flash.py``); GQA/MQA is native (query heads grouped per KV head,
KV pages are read once).

q: [B, H, D] (one decode token per sequence)
k/v_cache: [num_blocks, block_size, Hkv, D]
block_tables: [B, max_blocks] int32   (page ids per sequence, 0-padded)
seq_lens: [B] int32
→ out: [B, H, D]
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_x32 import no_x64

# np.float32 scalar, not a Python float: inside an OUTER jit the
# interpret-mode kernel body is staged and re-evaluated outside the
# no_x64() window, where a bare float would promote to f64 and trip
# the MLIR verifier (same fix as pallas_flash's np-scalar consts)
_NEG_INF = np.float32(-1e30)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, block_size, n_pages,
                   rep):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    seq_len = len_ref[b]
    # pages beyond the sequence are skipped entirely (their DMA still reads
    # page bt[b, j], which is 0-padded — harmless)
    @pl.when(j * block_size < seq_len)
    def _step():
        q = q_ref[0]                         # [H, D]
        k = k_ref[0]                         # [bs, Hkv, D]
        v = v_ref[0]                         # [bs, Hkv, D]
        hkv = k.shape[1]
        # Mosaic's matmul wants plain 2-D dots — unroll the (static, small)
        # KV-head dimension in Python instead of a 3-D batched dot_general.
        # logits[kvh*rep + r, t] = q[kvh*rep + r, :] · k[t, kvh, :]
        parts = []
        for kvh in range(hkv):
            qh = q[kvh * rep:(kvh + 1) * rep, :]         # [rep, D]
            kh = k[:, kvh, :]                            # [bs, D]
            parts.append(jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))     # [rep, bs]
        s2 = (parts[0] if hkv == 1
              else jnp.concatenate(parts, axis=0)) * scale   # [H, bs]
        pos = jax.lax.broadcasted_iota(jnp.int32, s2.shape, 1) + j * block_size
        s2 = jnp.where(pos < seq_len, s2, _NEG_INF)

        m_prev = m_ref[:, 0]                             # [H]
        m_new = jnp.maximum(m_prev, jnp.max(s2, axis=-1))
        alpha = jnp.exp(m_prev - m_new)                  # [H]
        p = jnp.exp(s2 - m_new[:, None])                 # [H, bs]
        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, -1)
        m_ref[:, 0] = m_new
        # pv[kvh*rep + r, d] = sum_t p[kvh*rep + r, t] v[t, kvh, d]
        pv_parts = []
        for kvh in range(hkv):
            ph = p[kvh * rep:(kvh + 1) * rep, :]         # [rep, bs]
            vh = v[:, kvh, :]                            # [bs, D]
            pv_parts.append(jax.lax.dot_general(
                ph.astype(jnp.float32), vh.astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))     # [rep, D]
        pv = pv_parts[0] if hkv == 1 else jnp.concatenate(pv_parts, axis=0)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + pv

    @pl.when(j == n_pages - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:, 0], np.float32(1e-9))[:, None]
                    ).astype(o_ref.dtype)


def decode_oracle(q, k_cache, v_cache, block_tables, seq_lens):
    """The kernel's differential-testing oracle: the XLA gather path
    with identical routing semantics (``paged_attention._xla_paged_
    attention``), paired here so kernel and oracle live side by side.
    The fast CPU interpret-mode parity tests run every decode bucket
    shape through both, and the online :class:`~paddle_tpu
    .observability.audit.NumericsAuditor` re-executes sampled serving
    decode steps through the same reference — the standing harness the
    ROADMAP's ragged-kernel rewrite will land against."""
    from .paged_attention import _xla_paged_attention

    return _xla_paged_attention(q, k_cache, v_cache, block_tables,
                                seq_lens)


def paged_attention_decode(q, k_cache, v_cache, block_tables, seq_lens):
    """Fused paged decode attention; returns [B, H, D]."""
    B, H, D = q.shape
    num_blocks, bs, Hkv, _ = k_cache.shape
    rep = H // Hkv
    n_pages = block_tables.shape[1]
    scale = 1.0 / math.sqrt(D)
    # Mosaic has no i64: scalar-prefetch operands must be 32-bit
    block_tables = block_tables.astype(jnp.int32)
    seq_lens = seq_lens.astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,   # block_tables, seq_lens
        grid=(B, n_pages),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, j, bt, ln: (b, 0, 0)),
            # the scalar-prefetched block table drives the page DMA:
            pl.BlockSpec((1, bs, Hkv, D),
                         lambda b, j, bt, ln: (bt[b, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, Hkv, D),
                         lambda b, j, bt, ln: (bt[b, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, j, bt, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, D), jnp.float32),    # acc
            pltpu.VMEM((H, 1), jnp.float32),    # running max
            pltpu.VMEM((H, 1), jnp.float32),    # running sum
        ],
    )
    kernel = functools.partial(
        _decode_kernel, scale=scale, block_size=bs, n_pages=n_pages, rep=rep)
    with no_x64():
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
            interpret=_interpret(),
        )(block_tables, seq_lens, q, k_cache, v_cache)
