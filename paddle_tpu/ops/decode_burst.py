"""Device-resident decode-burst loop (ISSUE 19).

``run_burst`` chains up to ``n_steps`` decode steps inside ONE traced
program with a ``lax.fori_loop`` (traced trip count → lowers to a
``while_loop``, which ``jax.export`` serializes fine): each iteration
writes the input token's KV into its pre-routed pool slot, runs the
model, samples the next token with the ISSUE 18 fused epilogue, and
feeds that token straight back in as the next iteration's input — the
host sees only the final ``[B, N]`` token buffer.  This is stage 2 of
the MPK-style mega-kernel plan (PAPERS.md #5): the host loop does
bookkeeping only, returning to the device at burst granularity instead
of token granularity.

Division of labor with the engine:

* **Host-side clamp, device-side EOS masking.**  The engine clamps the
  burst length so no row can exceed ``max_new_tokens`` or the pool's
  pre-allocated slots mid-burst; the ONLY in-trace early exit is EOS.
  A row that samples its EOS token emits it (matching the per-step
  host path, where the EOS token is appended before the finish), then
  goes inactive: its remaining iterations write KV to the null page
  (block 0 — the same sink bucketed padding rows use) and its buffer
  lanes stay ``-1`` (token ids are argmax indices, always ``>= 0``, so
  ``-1`` is an unambiguous not-emitted sentinel).
* **Sampling keys advance in-trace.**  The draw key for iteration ``j``
  is ``(seed, draw0 + j)`` — an active row emits exactly one token per
  iteration, so ``draw0 + j`` IS the row's output position, and the
  burst replays the identical counter-hashed Gumbel sequence the
  per-step path consumes: burst-on is bit-identical to burst-off for
  greedy and sampled rows alike.
* **KV discipline matches per-step decode exactly.**  Iteration ``j``
  writes the KV of its INPUT token at position ``pos0 + j``; a row that
  emits ``e`` tokens has written positions ``pos0 .. pos0+e-1`` and its
  newest emitted token's KV is NOT yet written — precisely the state
  the host's ``commit(e)`` bookkeeping describes.

Oracle discipline (PR 9/10): :func:`burst_oracle` is the ground-truth
twin — the same arithmetic as an eager Python loop over the SAME
``model_step`` callable, no ``fori_loop``, no masking cleverness.  The
parity sweep in the tests drives both over the full (rows × burst
length) bucket lattice.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .sampling import sample_tokens


def _step_keys(keys, j):
    """Advance every row's (seed, draw) key pair to iteration ``j``:
    seed column untouched, draw column ``+ j`` (u32 wrap-around is the
    counter semantics :func:`_gumbel_from_keys` expects)."""
    bump = jnp.stack([jnp.uint32(0), jnp.asarray(j).astype(jnp.uint32)])
    return keys + bump[None, :]


def run_burst(model_step, n_steps, vocab, ids, pos, lens, active,
              eos_ids, slot_blocks, slot_offsets, temps, top_ks,
              top_ps, keys, k_pools, v_pools):
    """Run up to ``n_steps`` chained decode steps in-trace.

    Args:
      model_step: callable ``(ids[B,1], pos[B], lens[B], slot_blocks[B],
        slot_offsets[B], k_pools, v_pools) -> (last_logits[B,V],
        k_pools, v_pools)`` — one decode forward writing the input
        token's KV into the routed slot (the engine closes this over its
        block tables and traced parameters).
      n_steps: i32 scalar (traced ok) — actual burst length N ≤ the
        ``slot_blocks`` width Nb; iterations ``>= n_steps`` never run.
      vocab: static int — logits width (fixes the carry shape).
      ids: ``[B, 1]`` i32 — each row's input token (its last emission).
      pos: ``[B]`` i32 — that token's position (= committed KV length).
      lens: ``[B]`` i32 — attention length AFTER the slot write
        (``pos + 1`` for real rows, 1 for padding rows).
      active: ``[B]`` bool — real rows; padding rows never emit.
      eos_ids: ``[B]`` i32 — per-row EOS token id, ``-1`` = none.
      slot_blocks / slot_offsets: ``[B, Nb]`` i32 — iteration ``j``'s
        KV slot per row, precomputed host-side from the pre-extended
        block tables (position ``pos + j``).
      temps / top_ks / top_ps / keys: the ISSUE 18 sampling quartet;
        ``keys[:, 1]`` holds each row's FIRST draw index.
      k_pools / v_pools: per-layer pool tensors, threaded through the
        loop carry so donation holds across all N steps.

    Returns:
      ``(tokens[B, Nb] i32 with -1 = not emitted, last_logits[B, V]
      f32, k_pools, v_pools)``.
    """
    B, Nb = slot_blocks.shape
    buf0 = jnp.full((B, Nb), -1, jnp.int32)
    last0 = jnp.zeros((B, vocab), jnp.float32)

    def body(j, carry):
        ids_c, pos_c, lens_c, act, buf, last, kp, vp = carry
        # inactive rows (padding, or already-finished mid-burst) write
        # into the null page — same sink as bucketed decode padding
        sb = jnp.where(act, slot_blocks[:, j], 0)
        so = jnp.where(act, slot_offsets[:, j], 0)
        logits, kp, vp = model_step(ids_c, pos_c, lens_c, sb, so, kp, vp)
        # inactive rows sample greedy (temp 0) — cheap, discarded
        toks = sample_tokens(logits, jnp.where(act, temps, 0.0),
                             top_ks, top_ps, _step_keys(keys, j))
        buf = buf.at[:, j].set(jnp.where(act, toks, -1))
        last = jnp.where(act[:, None], logits, last)
        # EOS is EMITTED then deactivates the row (per-step parity:
        # the host appends the EOS token before finishing the request)
        still = act & (toks != eos_ids)
        ids_c = jnp.where(still[:, None], toks[:, None], ids_c)
        pos_c = jnp.where(still, pos_c + 1, pos_c)
        lens_c = jnp.where(still, lens_c + 1, lens_c)
        return ids_c, pos_c, lens_c, still, buf, last, kp, vp

    carry = (ids, pos, lens, active, buf0, last0, k_pools, v_pools)
    carry = jax.lax.fori_loop(jnp.int32(0), n_steps, body, carry)
    _, _, _, _, buf, last, k_out, v_out = carry
    return buf, last, k_out, v_out


def burst_oracle(model_step, n_steps, vocab, ids, pos, lens, active,
                 eos_ids, slot_blocks, slot_offsets, temps, top_ks,
                 top_ps, keys, k_pools, v_pools):
    """Ground-truth twin of :func:`run_burst`: an eager Python loop over
    the SAME ``model_step``, one decode step at a time, no traced
    control flow — the reference the interpret-mode parity sweep holds
    the fast path to (PR 9/10 oracle discipline)."""
    B, Nb = slot_blocks.shape
    buf = jnp.full((B, Nb), -1, jnp.int32)
    last = jnp.zeros((B, vocab), jnp.float32)
    act = active
    n = int(n_steps)
    for j in range(n):
        sb = jnp.where(act, slot_blocks[:, j], 0)
        so = jnp.where(act, slot_offsets[:, j], 0)
        logits, k_pools, v_pools = model_step(
            ids, pos, lens, sb, so, k_pools, v_pools)
        toks = sample_tokens(logits, jnp.where(act, temps, 0.0),
                             top_ks, top_ps, _step_keys(keys, j))
        buf = buf.at[:, j].set(jnp.where(act, toks, -1))
        last = jnp.where(act[:, None], logits, last)
        still = act & (toks != eos_ids)
        ids = jnp.where(still[:, None], toks[:, None], ids)
        pos = jnp.where(still, pos + 1, pos)
        lens = jnp.where(still, lens + 1, lens)
        act = still
    return buf, last, k_pools, v_pools
