"""``paddle.regularizer`` (``python/paddle/regularizer.py``): L1/L2 decay
config objects consumed by ParamAttr/optimizers (weight_decay carriers)."""

from __future__ import annotations


class WeightDecayRegularizer:
    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    """(``regularizer.py`` L1Decay) lasso penalty coeff·|w|."""


class L2Decay(WeightDecayRegularizer):
    """(``regularizer.py`` L2Decay) ridge penalty coeff·||w||² — the form
    optimizers consume as ``weight_decay``."""
