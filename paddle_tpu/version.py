"""``paddle.version`` (generated ``python/paddle/version.py`` analog)."""

full_version = "2.6.0+tpu"
major = "2"
minor = "6"
patch = "0"
rc = "0"
cuda_version = "False"
cudnn_version = "False"
xpu_version = "False"
istaged = False
commit = "tpu-native"
with_pip_cuda_libraries = "OFF"


def show():
    print(f"paddle_tpu {full_version} (commit {commit}); "
          "backend: XLA/TPU via JAX")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version


def xpu():
    return xpu_version
