"""Bounded in-process metrics history for the serving stack (ISSUE 14).

Every observability layer so far is point-in-time: ``/metrics`` is an
instant snapshot, the fleet gauges are only as fresh as the last
refresh, and nothing watches a series *over time*.  This module adds the
missing axis: a :class:`HistoryStore` samples a shared
:class:`~paddle_tpu.observability.metrics.MetricsRegistry` on a
deterministic **engine-step cadence** into fixed-size rings per series —
the substrate the :class:`~paddle_tpu.observability.alerts.AlertEngine`
evaluates its threshold / rate / SLO **burn-rate** rules over, and the
signal the planned SLO-driven replica scaling and cache-aware
rebalancing actuators will consume.

Semantics:

* **Counters** are stored as their monotone cumulative values;
  :meth:`increase` derives the windowed rate at query time as the sum of
  per-sample deltas **clamped to >= 0** — a replica rebuild that
  restarts an engine-local counter at zero (the PR 12 chaos-phase
  caveat) reads as a reset, never as a negative rate.
* **Gauges** are sampled directly; **histograms** contribute their exact
  streaming aggregates as two derived series, ``<name>_count`` and
  ``<name>_sum`` (both cumulative, so rate rules and latency-over-window
  math work on them like counters).
* Every sample runs the registry's **collect hooks** first (ISSUE 14
  satellite), then reads all series values inside ONE
  ``registry.atomic()`` block — related counters (the SLO goodput pair)
  are pairwise-consistent in every sample.
* The x-axis is the store's own **sample index** (monotone, one per
  sample) plus the triggering engine step: alert windows are measured in
  samples, never wall-clock, so an evaluation replayed over the same
  recorded window produces the same transitions (the AuditConfig /
  FaultPlan determinism discipline).

Boundedness (``tools/check_bounded_metrics.py`` lints this module): the
memory bound is a hard ``max_series x ring_len`` — each series ring is a
``deque(maxlen=ring_len)``; series beyond ``max_series`` are **dropped**
and counted on ``serving_history_series_dropped_total`` (once per
distinct dropped key), never silently truncated.
"""

from __future__ import annotations

import sys
import threading
import traceback
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from .metrics import MetricsRegistry, _label_suffix

# pre-registered metric names this module owns (tools/check_metrics_docs
# lints that each appears in README's metrics table)
METRIC_NAMES = (
    "serving_history_samples_total",
    "serving_history_series_dropped_total",
)

# listeners are a small fixed set (the alert engine, maybe a recorder);
# accumulating past this is a leak
_MAX_LISTENERS = 8


@dataclass(frozen=True)
class HistoryConfig:
    """Sampler knobs — a frozen, value-comparable config (the
    AuditConfig discipline: the fleet refuses heterogeneous replica
    configs, and two stores built from equal configs behave
    identically)."""

    sample_every_steps: int = 1   # engine-step cadence: one sample per
    # this many on_step() ticks.  The tick count is FLEET-wide at dp>1
    # (every replica's engine thread ticks the one shared store), so a
    # sample pass — collect hooks + full-registry read + rule
    # evaluation, serialized under the sample lock — runs dp times per
    # fleet step-round at the default.  Cheap next to a jitted engine
    # step at this repo's dp, but raise this (~dp or more) on a wide
    # fleet so sampling cost stays constant per round instead of
    # scaling with dp.
    ring_len: int = 512           # samples retained per series
    max_series: int = 1024        # hard series cap; beyond it, dropped
    # + counted (memory bound = max_series x ring_len entries)

    def __post_init__(self):
        if self.sample_every_steps < 1:
            raise ValueError(f"sample_every_steps must be >= 1, got "
                             f"{self.sample_every_steps}")
        if self.ring_len < 2:
            raise ValueError(f"ring_len must be >= 2 (a rate needs two "
                             f"samples), got {self.ring_len}")
        if self.max_series < 1:
            raise ValueError(f"max_series must be >= 1, got "
                             f"{self.max_series}")


class HistoryStore:
    """Fixed-size per-series rings over one registry's series.

    The engine thread(s) drive sampling through :meth:`on_step` (the
    fleet router binds every replica's engine to ONE store, so at dp>1
    the tick count is fleet-wide); HTTP handler threads read windows
    under the store lock.  Each ring entry is ``(sample_index, step,
    value)`` — ``step`` is the triggering engine's step counter, carried
    for operator readability; all window math uses the sample index.
    """

    def __init__(self, registry: MetricsRegistry,
                 config: Optional[HistoryConfig] = None):
        self.cfg = config or HistoryConfig()
        self.registry = registry
        self._lock = threading.Lock()
        # serializes whole sample passes: two engine threads sampling
        # concurrently must not interleave their read/append phases (a
        # later sample index must never carry older values)
        self._sample_lock = threading.Lock()
        self._rings: Dict[str, deque] = {}  # unbounded-ok: capped at cfg.max_series by _ring_for (drop counter past it)
        self._kinds: Dict[str, str] = {}    # unbounded-ok: one entry per ring key, same max_series cap
        self._names: Dict[str, List[str]] = {}  # unbounded-ok: metric name -> ring keys, bounded by the ring-key cap
        self._dropped: set = set()          # unbounded-ok: distinct dropped keys, bounded by the registry's own max_series cap
        self.samples = 0                    # monotone sample index
        self._ticks = 0                     # on_step() calls since start
        self._listeners: List[Callable] = []  # unbounded-ok: add_listener refuses past _MAX_LISTENERS
        self._c_samples = registry.counter(
            "serving_history_samples_total",
            "metrics-history samples taken")
        self._c_dropped = registry.counter(
            "serving_history_series_dropped_total",
            "series dropped by the history store's max_series cap "
            "(counted once per distinct series)")

    # --- feeding ------------------------------------------------------------
    def add_listener(self, fn: Callable[[int, int], None]
                     ) -> Callable[[], None]:
        """Register ``fn(sample_index, step)``, called after every
        sample (on the sampling engine thread; exceptions swallowed
        with a stderr report — a broken evaluator must never kill the
        replica) — the alert engine's evaluation hook.  Returns a
        zero-arg remover."""
        with self._lock:
            if len(self._listeners) >= _MAX_LISTENERS:
                raise RuntimeError(
                    f"history store already has {_MAX_LISTENERS} "
                    "listeners — register one evaluator object, not one "
                    "per request")
            self._listeners.append(fn)

        def remove() -> None:
            with self._lock:
                try:
                    self._listeners.remove(fn)
                except ValueError:
                    pass  # swallow-ok: already removed — remover is idempotent

        return remove

    def on_step(self, step: int) -> Optional[int]:
        """Engine-step tick: sample every ``sample_every_steps`` ticks.
        Thread-safe (at dp>1 every replica's engine thread ticks the
        same store).  Returns the new sample index when a sample was
        taken, else ``None``."""
        with self._lock:
            self._ticks += 1
            due = self._ticks % self.cfg.sample_every_steps == 0
        if not due:
            return None
        return self.sample(step)

    def sample(self, step: Optional[int] = None) -> int:
        """Take one sample of every registry series NOW: run the collect
        hooks (fresh derived gauges), read all values inside one
        ``registry.atomic()`` block (pairwise-consistent counters), then
        append to the rings.  Returns the sample index."""
        with self._sample_lock:
            return self._sample_locked(step)

    def _sample_locked(self, step: Optional[int]) -> int:
        self.registry.run_collect_hooks()
        metrics = self.registry.series()
        # one atomic read pass: (kind, key-suffix, metric, value tuple)
        reads: List[Tuple[str, str, object, Tuple]] = []
        with self.registry.atomic():
            for m in metrics:
                key = m.name + _label_suffix(m.labels)
                if m.kind == "counter":
                    reads.append(("counter", key, m.name, (m._value,)))
                elif m.kind == "gauge":
                    reads.append(("gauge", key, m.name, (m._value,)))
                elif m.kind == "histogram":
                    # under the metric's own lock too: observe()
                    # updates count then sum under that lock only, and
                    # a torn (count, sum) pair would record a sample
                    # where a request's count arrived without its sum
                    with m._lock:
                        reads.append(("histogram", key, m.name,
                                      (m.count, m.sum)))
        with self._lock:
            self.samples += 1
            idx = self.samples
            st = -1 if step is None else int(step)
            for kind, key, name, vals in reads:
                if kind == "histogram":
                    self._append(f"{key}:count", f"{name}_count",
                                 "counter", idx, st, float(vals[0]))
                    self._append(f"{key}:sum", f"{name}_sum",
                                 "counter", idx, st, float(vals[1]))
                else:
                    self._append(key, name, kind, idx, st, float(vals[0]))
            listeners = tuple(self._listeners)
        self._c_samples.inc()
        for fn in listeners:
            try:
                fn(idx, st)
            except Exception:
                # swallow-ok: listeners run on the sampling ENGINE
                # thread (EngineCore.step -> on_step -> sample) — a
                # broken evaluator reported loudly must never kill the
                # replica (and, fleet-wide, every replica the supervisor
                # rebuilds after it), same discipline as collect hooks
                sys.stderr.write("[history] sample listener failed:\n"
                                 + traceback.format_exc())
        return idx

    def _append(self, key: str, name: str, kind: str, idx: int,
                step: int, value: float) -> None:
        # caller holds self._lock
        ring = self._rings.get(key)
        if ring is None:
            if len(self._rings) >= self.cfg.max_series:
                # hard memory bound: drop the NEW series, count it once
                if key not in self._dropped:
                    self._dropped.add(key)
                    self._c_dropped.inc()
                return
            ring = self._rings[key] = deque(maxlen=self.cfg.ring_len)
            self._kinds[key] = kind
            self._names.setdefault(name, []).append(key)
        ring.append((idx, step, value))

    # --- queries ------------------------------------------------------------
    def keys(self) -> List[str]:
        """Every tracked series key (``name{labels}[:count|:sum]``)."""
        with self._lock:
            return sorted(self._rings)

    def names(self) -> List[str]:
        """Every tracked metric name (histograms contribute their
        ``_count`` / ``_sum`` derived names)."""
        with self._lock:
            return sorted(self._names)

    def match(self, name: str) -> List[str]:
        """Ring keys whose metric name is exactly ``name`` — one per
        label set (the per-replica view of a fleet series)."""
        with self._lock:
            return list(self._names.get(name, ()))

    def kind(self, key: str) -> Optional[str]:
        with self._lock:
            return self._kinds.get(key)

    def window(self, key: str, n: Optional[int] = None) -> List[Dict]:
        """The last ``n`` samples of ``key`` (all retained when ``n`` is
        None), oldest first, as ``{"i": sample, "step": step, "v":
        value}`` rows."""
        with self._lock:
            ring = self._rings.get(key)
            rows = list(ring) if ring is not None else []
        if n is not None:
            rows = rows[-int(n):]
        return [{"i": i, "step": s, "v": v} for i, s, v in rows]

    def latest(self, key: str) -> Optional[float]:
        with self._lock:
            ring = self._rings.get(key)
            if not ring:
                return None
            return ring[-1][2]

    def increase(self, key: str, window: int) -> Optional[float]:
        """Windowed increase of a cumulative series: the sum of
        per-sample deltas over the last ``window`` samples, each clamped
        to >= 0 — a counter reset (replica rebuild restarting a counter
        at zero) contributes nothing instead of a negative rate.
        ``None`` until the series has two samples."""
        with self._lock:
            ring = self._rings.get(key)
            if ring is None or len(ring) < 2:
                return None
            rows = list(ring)[-(int(window) + 1):]
        total = 0.0
        for (_, _, prev), (_, _, cur) in zip(rows, rows[1:]):
            total += max(0.0, cur - prev)
        return total

    def covers(self, name: str, window: int) -> bool:
        """True when every series of ``name`` holds a FULL ``window`` of
        recorded deltas (ring length >= window + 1).  The burn-rate
        evaluator's cold-start guard: two samples after a restart, a
        64-sample "slow" window computed over the only delta available
        is just the fast window wearing a slow label — the sustained
        evidence it exists to demand is not there yet."""
        with self._lock:
            keys = self._names.get(name, ())
            if not keys:
                return False
            return all(len(self._rings[k]) > window for k in keys)

    def name_latest_sum(self, name: str) -> Optional[float]:
        """Fleet view of a name: sum of the latest sample across every
        label set (counters/gauges); ``None`` when untracked."""
        vals = [self.latest(k) for k in self.match(name)]
        vals = [v for v in vals if v is not None]
        return sum(vals) if vals else None

    def name_increase(self, name: str, window: int) -> Optional[float]:
        """Fleet view of a cumulative name: sum of :meth:`increase`
        across every label set (per-replica resets clamp per series)."""
        vals = [self.increase(k, window) for k in self.match(name)]
        vals = [v for v in vals if v is not None]
        return sum(vals) if vals else None

    def stats(self) -> Dict:
        """Store shape for the debug surface: sample count, tick count,
        series count, dropped count, config."""
        with self._lock:
            return {
                "samples": self.samples,
                "ticks": self._ticks,
                "series": len(self._rings),
                "dropped_series": len(self._dropped),
                "config": {
                    "sample_every_steps": self.cfg.sample_every_steps,
                    "ring_len": self.cfg.ring_len,
                    "max_series": self.cfg.max_series,
                },
            }
